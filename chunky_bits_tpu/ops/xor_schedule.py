"""Scheduled-XOR lowering of GF(2^8) coding matrices (ROADMAP item 4).

Lowers an r x k byte matrix to a flat program of plane-wide XORs, the
CPU analogue of the bit-plane matmul the device path runs
(ops/bitplane.py): expand the matrix to its 8r x 8k GF(2) bit-matrix
(gf256.expand_to_bit_matrix — the exact math `apply_bitplane` einsums
on-device), view every shard as 8 bit-planes, and emit one XOR per set
bit after greedy common-subexpression elimination, per *Accelerating
XOR-based Erasure Coding using Program Optimization Techniques*
(arXiv:2108.02692) and the ring-transform framing of arXiv:1701.07731.

Layout note — why bit-planes and not contiguous sub-packets: parity
chunks are content-addressed and golden-pinned, so the engine must be
byte-identical to the table codecs.  A sub-packet scheme (plane v =
bytes [vP, (v+1)P)) is GL(2)-conjugate to the byte codec — it
round-trips data but emits *different parity bytes*, which would fork
the wire format.  Bit-planes (plane v, byte t8, bit b = bit v of shard
byte 8*t8+b — little bit order) make the XOR program compute exactly
``bits(mat (x) shards)``, so every emitted byte matches numpy/native/
jax.  The transpose in and out of plane layout is one cheap pass per
byte (the native executor does it with SIMD movemask / 8x8 bit
transposes inside its L1 tile loop); the schedule replaces the k*r
per-byte table work.

Schedules are pure data: ``(dst, src, kind)`` int32 triples over a
plane arena ``[inputs 0..8k) | temps | outputs]``, executed by the
native engine (``cb_xor_exec`` in native/gf256.cpp, tiled so the whole
arena stays L1/L2-resident) or by :func:`apply_numpy`, the vectorized
reference executor the identity tests diff against.  Decode matrices
are per-erasure-pattern, so built schedules live in a bounded LRU
keyed by matrix digest (:func:`get_schedule`) shared by every caller —
the encode path, ``ReconstructBatcher`` groups and ``RepairPlanner``
decode plans all reach it through ``NativeBackend.apply_matrix``.
"""

from __future__ import annotations

import hashlib
import heapq
import threading
from collections import OrderedDict

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops import gf256

#: op kinds in the flat program: dst := src / dst ^= src / dst := 0
OP_COPY, OP_XOR, OP_ZERO = 0, 1, 2

#: greedy-CSE temp ceiling: bounds both schedule-build time and the
#: executor's arena (n_planes * tile bytes); extraction just stops at
#: the cap — correctness never depends on how far CSE got
MAX_TEMPS = 1024


class XorSchedule:
    """One compiled XOR program for a fixed GF(2^8) matrix.

    ``ops`` is a C-contiguous int32 ``[n, 3]`` array of
    ``(dst_plane, src_plane, kind)`` triples over the arena
    ``[0, 8k)`` input planes, ``[8k, 8k + n_temps)`` temporaries,
    ``[out_base, out_base + 8r)`` output planes, in execution order
    (every temp is defined before first use; each output plane's run
    starts with OP_COPY or OP_ZERO).
    """

    __slots__ = ("k", "r", "n_temps", "ops", "raw_xors", "digest")

    def __init__(self, k: int, r: int, n_temps: int, ops: np.ndarray,
                 raw_xors: int, digest: bytes) -> None:
        self.k = k
        self.r = r
        self.n_temps = n_temps
        self.ops = ops
        self.raw_xors = raw_xors
        self.digest = digest

    @property
    def n_planes(self) -> int:
        return 8 * self.k + self.n_temps + 8 * self.r

    @property
    def out_base(self) -> int:
        return 8 * self.k + self.n_temps

    @property
    def n_xors(self) -> int:
        """Scheduled XOR count (OP_XOR ops) — the CSE win metric:
        compare against ``raw_xors - 8r`` (one per set bit minus the
        copies that seed each output)."""
        return int(np.count_nonzero(self.ops[:, 2] == OP_XOR))


def matrix_digest(mat: np.ndarray) -> bytes:
    """Cache key for a coding matrix: shape-qualified content hash."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    h = hashlib.sha256()
    h.update(b"%dx%d:" % mat.shape)
    h.update(mat.tobytes())
    return h.digest()


def _cse(rows: list[set], n_in: int,
         max_temps: int) -> tuple[list[tuple[int, int, int]], int]:
    """Greedy pair-frequency CSE (2108.02692 §4): repeatedly hoist the
    plane pair shared by the most output rows into a temp, until no
    pair occurs twice (or the temp cap).  Mutates ``rows`` in place;
    returns ``(temp defs [(t, a, b)], n_temps)``."""
    counts: dict[tuple[int, int], int] = {}
    heap: list[tuple[int, int, int]] = []

    def bump(a: int, b: int, by: int) -> None:
        p = (a, b) if a < b else (b, a)
        c = counts.get(p, 0) + by
        if c <= 0:
            counts.pop(p, None)
            return
        counts[p] = c
        if c >= 2:
            heapq.heappush(heap, (-c, p[0], p[1]))

    # initial co-occurrence counts in one boolean matmul, not a Python
    # pair loop: C[a, b] = number of rows containing both planes
    m = np.zeros((len(rows), n_in), dtype=np.uint8)
    for ri, row in enumerate(rows):
        m[ri, list(row)] = 1
    co = m.T.astype(np.int32) @ m.astype(np.int32)
    for a, b in zip(*np.nonzero(np.triu(co, k=1) >= 2)):
        a, b = int(a), int(b)
        counts[(a, b)] = int(co[a, b])
        heapq.heappush(heap, (-int(co[a, b]), a, b))

    temps: list[tuple[int, int, int]] = []
    next_id = n_in
    while heap and len(temps) < max_temps:
        negc, a, b = heapq.heappop(heap)
        p = (a, b)
        if counts.get(p, 0) != -negc:
            continue  # stale heap entry (lazy deletion)
        if -negc < 2:
            break
        t = next_id
        next_id += 1
        temps.append((t, a, b))
        for row in rows:
            if a not in row or b not in row:
                continue
            row.discard(a)
            row.discard(b)
            for x in row:
                bump(a, x, -1)
                bump(b, x, -1)
                bump(t, x, +1)
            row.add(t)
        counts.pop(p, None)
    return temps, next_id - n_in


def verify_schedule(sched: XorSchedule, mat: np.ndarray) -> None:
    """Symbolic proof that ``sched`` computes exactly ``mat``.

    Replays the program over GF(2) with each input plane as a basis
    vector: every arena plane carries its coefficient row over the 8k
    input planes (COPY assigns the row, XOR adds it mod 2, ZERO clears
    it — temp-slot recycling falls out naturally since a slot is just
    whatever row was last written).  After the replay, output plane u
    must hold row u of ``gf256.expand_to_bit_matrix(mat)`` — the exact
    math every other backend computes — so a schedule that passes is
    byte-identical to the table codecs *by construction*, for every
    shard content, not just the fuzzed ones (2108.02692's verification
    step).  Raises :class:`ErasureError` on the first mismatching
    output row; runs at compile time (one [n_planes, 8k] bit matrix,
    one row op per scheduled op), so the always-on cost rides the slow
    path that already amortizes behind the ScheduleCache.
    """
    m2 = gf256.expand_to_bit_matrix(mat)
    r8, k8 = m2.shape
    if (r8, k8) != (8 * sched.r, 8 * sched.k):
        raise ErasureError(
            f"schedule geometry {sched.r}x{sched.k} does not match "
            f"matrix bit-expansion {r8 // 8}x{k8 // 8}")
    sym = np.zeros((sched.n_planes, k8), dtype=np.uint8)
    sym[:k8] = np.eye(k8, dtype=np.uint8)
    for dst, src, kind in sched.ops.tolist():
        if kind == OP_COPY:
            sym[dst] = sym[src]
        elif kind == OP_XOR:
            sym[dst] ^= sym[src]
        elif kind == OP_ZERO:
            sym[dst] = 0
        else:
            raise ErasureError(f"unknown op kind {kind} in schedule")
    got = sym[sched.out_base:]
    if not np.array_equal(got, m2):
        bad = int(np.nonzero((got != m2).any(axis=1))[0][0])
        raise ErasureError(
            f"xor schedule miscompiles matrix {sched.digest.hex()[:16]}: "
            f"output plane {bad} (row {bad // 8} bit {bad % 8}) computes "
            "a different GF(2) combination than the bit-matrix row — "
            "refusing to cache a program that would fork the wire format")


def build_schedule(mat: np.ndarray,
                   max_temps: int = MAX_TEMPS) -> XorSchedule:
    """Compile ``mat`` (uint8 [r, k], r >= 1) into an :class:`XorSchedule`.

    The program computes ``out[i] = XOR_j mat[i, j] (x) shards[j]`` in
    bit-plane layout; identity rows become single copies, zero rows an
    OP_ZERO (decode matrices contain both).  Every build is verified
    symbolically (:func:`verify_schedule`) before the schedule escapes
    to a caller or the cache.
    """
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    if mat.ndim != 2 or mat.shape[0] < 1 or mat.shape[1] < 1:
        raise ErasureError(f"cannot schedule a matrix shaped {mat.shape}")
    r, k = mat.shape
    digest = matrix_digest(mat)
    m2 = gf256.expand_to_bit_matrix(mat)
    raw_xors = int(m2.sum())
    rows: list[set] = [set(np.nonzero(m2[i])[0].tolist())
                       for i in range(8 * r)]
    temps, n_temps = _cse(rows, 8 * k, max_temps)

    # Logical order: a temp is defined (copy+xor pair) immediately
    # before its first use, outputs stream in row order — this keeps
    # temp liveness short so the slot recycling below can fold the
    # arena down (fewer live planes => bigger L1 tiles in the
    # executor, which measures as throughput: the tile loop's
    # per-op dispatch overhead amortizes over the tile length).
    defs = {t: (a, b) for t, a, b in temps}
    emitted: set = set()
    ops: list[tuple[int, int, int]] = []

    def emit_def(x: int) -> None:
        if x < 8 * k or x in emitted:
            return
        a, b = defs[x]
        emit_def(a)
        emit_def(b)
        emitted.add(x)
        ops.append((x, a, OP_COPY))
        ops.append((x, b, OP_XOR))

    out_base = 8 * k + n_temps
    for u, row in enumerate(rows):
        dst = out_base + u
        if not row:
            ops.append((dst, 0, OP_ZERO))
            continue
        terms = sorted(row)
        for x in terms:
            emit_def(x)
        ops.append((dst, terms[0], OP_COPY))
        for x in terms[1:]:
            ops.append((dst, x, OP_XOR))

    # Temp-slot recycling: remap logical temp ids onto a small pool of
    # arena slots freed at each temp's last use — full CSE with a
    # near-minimal arena.
    last_use: dict[int, int] = {}
    for i, (dst, src, kind) in enumerate(ops):
        if kind != OP_ZERO and src >= 8 * k:
            last_use[src] = i
        # a temp's own def ops keep it live at least to its last use
        if dst < out_base and dst >= 8 * k:
            last_use.setdefault(dst, i)
    slot_of: dict[int, int] = {}
    free: list[int] = []
    n_slots = 0
    remapped: list[tuple[int, int, int]] = []
    for i, (dst, src, kind) in enumerate(ops):
        if kind != OP_ZERO and 8 * k <= src < out_base:
            src_slot = 8 * k + slot_of[src]
            if last_use[src] == i:
                heapq.heappush(free, slot_of.pop(src))
        elif kind == OP_ZERO:
            src_slot = 0
        else:
            src_slot = src
        if 8 * k <= dst < out_base:
            if dst not in slot_of:
                if free:
                    slot_of[dst] = heapq.heappop(free)
                else:
                    slot_of[dst] = n_slots
                    n_slots += 1
            dst_slot = 8 * k + slot_of[dst]
        else:
            dst_slot = dst
        remapped.append((dst_slot, src_slot, kind))
    # outputs sit right after the recycled temp pool
    shift = n_temps - n_slots
    final = [(d - shift if d >= out_base else d,
              s - shift if kind != OP_ZERO and s >= out_base else s,
              kind)
             for d, s, kind in remapped]
    arr = np.ascontiguousarray(np.array(final, dtype=np.int32))
    sched = XorSchedule(k, r, n_slots, arr, raw_xors, digest)
    verify_schedule(sched, mat)
    return sched


class ScheduleCache:
    """Bounded LRU of built schedules keyed by matrix digest.

    Decode matrices are per-erasure-pattern, so an unbounded cache
    would grow with observed failure patterns; the LRU keeps the hot
    working set (the encode matrix plus the patterns currently being
    repaired) and evicts cold patterns.  Thread-safe — worker threads
    of the host pipeline dispatch through it concurrently.
    """

    def __init__(self, maxsize: int = 64) -> None:
        if maxsize < 1:
            raise ErasureError("schedule cache needs maxsize >= 1")
        self.maxsize = maxsize
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, XorSchedule]" = OrderedDict()
        # weakly self-register so /metrics and /stats surface the LRU's
        # hit/miss/eviction counters (cb_xor_schedule_*) — the same
        # polled-source pattern as the chunk cache; the process-shared
        # _CACHE below lives for the process, per-test instances drop
        # out with their owners (the registry holds only a weakref)
        from chunky_bits_tpu.obs.metrics import get_registry

        get_registry().register_source("xor_schedule", self)

    def get(self, mat: np.ndarray) -> XorSchedule:
        key = matrix_digest(mat)
        with self._lock:
            sched = self._entries.get(key)
            if sched is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return sched
            self.misses += 1
        # build outside the lock: a large decode-pattern build must not
        # stall concurrent encode dispatches (a racing duplicate build
        # is rare and merely wasted work — last writer wins)
        sched = build_schedule(mat)
        with self._lock:
            self._entries[key] = sched
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                self.evictions += 1
        return sched

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def info(self) -> dict:
        with self._lock:
            return {"size": len(self._entries), "maxsize": self.maxsize,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


#: the process-shared cache every native-backend dispatch goes through
_CACHE = ScheduleCache()


def get_schedule(mat: np.ndarray) -> XorSchedule:
    """Process-shared :class:`ScheduleCache` lookup (build on miss)."""
    return _CACHE.get(mat)


def schedule_cache_info() -> dict:
    """Introspection for tests and the bench grid."""
    return _CACHE.info()


# ---- numpy reference executor (identity oracle for the native engine) ----


def planes_of(rows: np.ndarray) -> np.ndarray:
    """Byte rows ``[n, S]`` (S % 8 == 0) -> bit-planes ``[8n, S/8]``:
    plane ``8i + v`` byte ``t8`` bit ``b`` = bit ``v`` of row ``i``
    byte ``8*t8 + b`` — the little-bit-order layout the native
    executor's movemask/transpose8 kernels produce."""
    n, s = rows.shape
    if s % 8:
        raise ErasureError("bit-plane layout needs S % 8 == 0")
    bits = np.unpackbits(rows.reshape(n, s, 1), axis=2,
                         bitorder="little")  # [n, S, 8]: bit v of byte t
    return np.packbits(bits.transpose(0, 2, 1), axis=2,
                       bitorder="little").reshape(8 * n, s // 8)


def bytes_of(planes: np.ndarray) -> np.ndarray:
    """Inverse of :func:`planes_of`: ``[8n, P]`` planes -> ``[n, 8P]``."""
    n8, p = planes.shape
    if n8 % 8:
        raise ErasureError("plane count must be a multiple of 8")
    bits = np.unpackbits(planes.reshape(n8 // 8, 8, p, 1), axis=3,
                         bitorder="little")  # [n, 8, P, 8]
    # -> [n, P, 8(t%8), 8(v)] then pack the v axis into the byte value
    return np.packbits(bits.transpose(0, 2, 3, 1), axis=3,
                       bitorder="little").reshape(n8 // 8, 8 * p)


def apply_numpy(sched: XorSchedule, shards: np.ndarray) -> np.ndarray:
    """Reference executor: ``out[b, r, s] = mat (x) shards[b, k, s]``
    via the schedule, vectorized across the batch (each arena plane is
    one ``[b * P]`` row).  Byte-identical to every other backend by
    construction — the identity tests diff it against numpy/native."""
    if shards.ndim != 3 or shards.shape[1] != sched.k:
        raise ErasureError(
            f"expected shards [B, {sched.k}, S], got {shards.shape}")
    b, k, s = shards.shape
    if s % 8:
        raise ErasureError("xor schedule needs S % 8 == 0")
    out = np.zeros((b, sched.r, s), dtype=np.uint8)
    if b == 0 or s == 0:
        return out
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    p = s // 8
    arena = np.zeros((sched.n_planes, b * p), dtype=np.uint8)
    arena[:8 * k] = planes_of(
        shards.reshape(b * k, s)).reshape(b, 8 * k, p).transpose(
            1, 0, 2).reshape(8 * k, b * p)
    for dst, src, kind in sched.ops.tolist():
        if kind == OP_COPY:
            arena[dst] = arena[src]
        elif kind == OP_XOR:
            arena[dst] ^= arena[src]
        else:
            arena[dst] = 0
    outp = arena[sched.out_base:].reshape(8 * sched.r, b, p).transpose(
        1, 0, 2).reshape(b * 8 * sched.r, p)
    return bytes_of(outp).reshape(b, sched.r, s)
