"""Native C++ CPU erasure backend (the correctness oracle).

Compiles ``native/gf256.cpp`` on first use with g++ (build cached next to the
source, keyed by a source hash) and binds it with ctypes — no pybind11
needed.  This fills the role of the reference's ``reed-solomon-erasure``
SIMD crate (reference: Cargo.toml:21): byte movement and GF math at native
speed on the host, with the GIL released for the whole call.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops import gf256
from chunky_bits_tpu.ops.backend import ErasureBackend

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SOURCE = os.path.join(_NATIVE_DIR, "gf256.cpp")
_BUILD_LOCK = threading.Lock()
_LIB = None


def _build_library() -> str:
    """Compile the codec if the cached .so is missing or stale."""
    with open(_SOURCE, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    build_dir = os.path.join(_NATIVE_DIR, "_build")
    # lint: fsio-escape-ok native .so build cache, not storage-plane
    # state — the crash harness never replays it
    os.makedirs(build_dir, exist_ok=True)
    lib_path = os.path.join(build_dir, f"libcbgf-{tag}.so")
    if os.path.exists(lib_path):
        return lib_path
    # Compile to a process-private name and rename into place so a killed or
    # concurrent build can never leave a truncated .so at the cached path.
    tmp_path = f"{lib_path}.tmp.{os.getpid()}"
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            _SOURCE, "-o", tmp_path]
    attempts = [
        base[:1] + ["-march=native"] + base[1:],
        base,
    ]
    last_err = None
    for cmd in attempts:
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            # lint: fsio-escape-ok build-cache publish; worst case on a
            # crash is a rebuild, never storage-plane corruption
            os.replace(tmp_path, lib_path)
            return lib_path
        except (subprocess.SubprocessError, OSError) as err:
            last_err = err
        finally:
            if os.path.exists(tmp_path):
                try:
                    # lint: fsio-escape-ok build temp cleanup only
                    os.remove(tmp_path)
                except OSError:
                    pass
    raise ErasureError(f"failed to build native gf256 codec: {last_err}")


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None:
            return _LIB
        lib = ctypes.CDLL(_build_library())
        lib.cb_apply_matrix.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.cb_apply_matrix.restype = None
        lib.cb_gf_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
        lib.cb_gf_mul.restype = ctypes.c_uint8
        lib.cb_sha256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p,
        ]
        lib.cb_sha256.restype = None
        lib.cb_sha256_is_accelerated.argtypes = []
        lib.cb_sha256_is_accelerated.restype = ctypes.c_int
        lib.cb_sha256_file.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p,
        ]
        lib.cb_sha256_file.restype = ctypes.c_int
        lib.cb_sha256_rows.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.cb_sha256_rows.restype = None
        lib.cb_encode_hash.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.cb_encode_hash.restype = None
        lib.cb_xor_exec.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.cb_xor_exec.restype = None
        lib.cb_xor_set_impl.argtypes = [ctypes.c_int]
        lib.cb_xor_set_impl.restype = ctypes.c_int
        lib.cb_xor_get_impl.argtypes = []
        lib.cb_xor_get_impl.restype = ctypes.c_int
        lib.cb_gf_set_level.argtypes = [ctypes.c_int]
        lib.cb_gf_set_level.restype = ctypes.c_int
        lib.cb_gf_get_level.argtypes = []
        lib.cb_gf_get_level.restype = ctypes.c_int
        # Field self-check: C++ tables must agree with the Python tables.
        for a, b in ((2, 0x80), (3, 7), (255, 255), (29, 1)):
            if lib.cb_gf_mul(a, b) != gf256.gf_mul(a, b):
                raise ErasureError("native GF tables disagree with python")
        # Hash self-check: one KAT against hashlib.
        probe = b"chunky-bits-tpu sha self-check"
        out = ctypes.create_string_buffer(32)
        lib.cb_sha256(probe, len(probe), out)
        if out.raw != hashlib.sha256(probe).digest():
            raise ErasureError("native sha256 disagrees with hashlib")
        _LIB = lib
    return _LIB


def sha256_buf(data) -> bytes:
    """Native one-shot SHA-256 (SHA-NI when the CPU has it)."""
    lib = _load()
    out = ctypes.create_string_buffer(32)
    data = bytes(data)
    lib.cb_sha256(data, len(data), out)
    return out.raw


def sha256_is_accelerated() -> bool:
    return bool(_load().cb_sha256_is_accelerated())


def xor_force_impl(level: int) -> int:
    """Force the scheduled-XOR engine's kernel tier (0 scalar / 1 SSE2
    / 2 AVX2); clamped to the runtime-detected ceiling, returns the
    effective tier.  Process-wide — tests pin the scalar fallback with
    this, bench --config 12 sweeps it."""
    return int(_load().cb_xor_set_impl(int(level)))


def gf_force_level(level: int) -> int:
    """Force the byte-table kernel tier (0 scalar table / 1 AVX2
    pshufb / 2 GFNI); clamped to what this build+CPU has, returns the
    effective tier.  Output bytes are identical at every tier — the
    knob exists so bench --config 12 can measure the XOR engine
    against each table tier a deployment might run."""
    return int(_load().cb_gf_set_level(int(level)))


_ALL = 0xFFFFFFFFFFFFFFFF


def sha256_file(path: str, start: int = 0,
                length: Optional[int] = None) -> bytes:
    """Hash a file byte range in one native streaming pass (SHA-NI),
    never surfacing the bytes to Python — the read+verify fusion for
    local chunk verification.  ``length=None`` hashes start..EOF.
    Raises OSError on I/O failure or a short file."""
    lib = _load()
    out = ctypes.create_string_buffer(32)
    want = _ALL if length is None else int(length)
    rc = lib.cb_sha256_file(os.fsencode(path), int(start), want, out)
    if rc == -2:
        raise OSError(f"short file: {path!r} has fewer than "
                      f"{start + (length or 0)} bytes")
    if rc != 0:
        raise OSError(f"cannot hash {path!r}")
    return out.raw


def sha256_rows(rows: np.ndarray, out: np.ndarray,
                nthreads: int = 0) -> None:
    """out[..., 32] = sha256 of each row of uint8 rows[..., S], hashed by
    the native engine in one threaded, GIL-free call.  ``nthreads``
    bounds the internal std::thread fan-out (0 = hardware concurrency);
    the host pipeline passes 1 per slice so total parallelism stays the
    scheduler's worker count, not workers x cores."""
    lib = _load()
    n = int(np.prod(rows.shape[:-1]))
    if n == 0 or rows.shape[-1] == 0:
        out[...] = np.frombuffer(
            hashlib.sha256(b"").digest(), dtype=np.uint8)
        return
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    if not out.flags.c_contiguous:
        raise ErasureError("sha256_rows needs a contiguous output")
    lib.cb_sha256_rows(
        rows.ctypes.data_as(ctypes.c_char_p), n, rows.shape[-1],
        out.ctypes.data_as(ctypes.c_void_p), int(nthreads),
    )


class NativeBackend(ErasureBackend):
    """ctypes binding over the C++ codec; thread-parallel across the batch.

    ``xor_schedule`` selects the scheduled-XOR engine
    (ops/xor_schedule.py + ``cb_xor_exec``) for matrix applies instead
    of the byte-table kernels: ``None`` resolves
    ``tunables.xor_schedule_enabled`` at first dispatch (the flag
    contract — set the env var before the first encode), an explicit
    bool pins it for this instance (tests and bench A/B both legs in
    one process without env games).  Output is byte-identical either
    way; shard lengths that are not a multiple of 8 fall back to the
    table path per call.
    """

    name = "native"

    def __init__(self, nthreads: int = 0,
                 xor_schedule: Optional[bool] = None):
        self.nthreads = nthreads
        self._lib = _load()
        self._xor = xor_schedule

    def _xor_enabled(self) -> bool:
        if self._xor is None:
            from chunky_bits_tpu.cluster.tunables import (
                xor_schedule_enabled,
            )

            self._xor = xor_schedule_enabled()
        return self._xor

    def _xor_apply(self, mat: np.ndarray, shards: np.ndarray,
                   out: np.ndarray, nthreads: int) -> None:
        """Run one batched matrix apply through the scheduled-XOR
        engine (caller guarantees s % 8 == 0, r >= 1, contiguity)."""
        from chunky_bits_tpu.ops import xor_schedule

        sched = xor_schedule.get_schedule(mat)
        b, _k, s = shards.shape
        self._lib.cb_xor_exec(
            sched.ops.ctypes.data_as(ctypes.c_void_p),
            sched.ops.shape[0], sched.n_planes, sched.k, sched.r,
            shards.ctypes.data_as(ctypes.c_char_p), b, s,
            out.ctypes.data_as(ctypes.c_void_p), nthreads,
        )

    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        b, k, s = shards.shape
        r = mat.shape[0]
        out = np.zeros((b, r, s), dtype=np.uint8)
        if r == 0 or b == 0 or s == 0:
            return out
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if s % 8 == 0 and self._xor_enabled():
            self._xor_apply(mat, shards, out, self.nthreads)
            return out
        self._lib.cb_apply_matrix(
            mat.ctypes.data_as(ctypes.c_char_p), r, k,
            shards.ctypes.data_as(ctypes.c_char_p), b, s,
            out.ctypes.data_as(ctypes.c_void_p), self.nthreads,
        )
        return out

    def encode_and_hash(
        self, mat: np.ndarray, shards: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Fused ingest step: parity[b, r, s] plus sha256 digests
        [b, k + r, 32] of every data-then-parity shard, one native pass
        per batch item (the shard stays cache-hot between GF math and
        hashing, and the GIL is released once for the whole batch)."""
        b, k, s = shards.shape
        r = mat.shape[0]
        parity = np.zeros((b, r, s), dtype=np.uint8)
        hashes = np.zeros((b, k + r, 32), dtype=np.uint8)
        return self.encode_and_hash_into(mat, shards, parity, hashes,
                                         self.nthreads)

    def encode_and_hash_into(
        self, mat: np.ndarray, shards: np.ndarray,
        out_parity: np.ndarray, out_hashes: np.ndarray,
        nthreads: Optional[int] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """``encode_and_hash`` writing into caller-provided contiguous
        ``out_parity[b, r, s]`` / ``out_hashes[b, k+r, 32]`` slices — the
        host pipeline's zero-copy sliced entry point: each scheduler
        worker encodes+hashes its contiguous stripe range with
        ``nthreads=1`` directly into its rows of the shared outputs, so
        assembling the batch result is positional, not a copy."""
        b, k, s = shards.shape
        r = mat.shape[0]
        if b == 0 or s == 0:
            # zero-length shards still hash: digest must be sha256(b""),
            # matching the generic fallback (ops/backend.py)
            if b and s == 0:
                out_hashes[:, :] = np.frombuffer(
                    hashlib.sha256(b"").digest(), dtype=np.uint8)
            return out_parity, out_hashes
        if not (out_parity.flags.c_contiguous
                and out_hashes.flags.c_contiguous):
            raise ErasureError("encode_and_hash_into needs contiguous "
                               "outputs")
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        nt = self.nthreads if nthreads is None else int(nthreads)
        if r > 0 and s % 8 == 0 and self._xor_enabled():
            # XOR-engine ingest: parity via the scheduled program, then
            # the SHA-NI row hasher over data+parity rows.  Loses the
            # table path's per-block encode/hash interleave but keeps
            # the pipeline's slicing contract intact (each stripe slice
            # arrives here with nthreads=1 and writes only its rows).
            self._xor_apply(mat, shards, out_parity, nt)
            # one native call per row family (data, parity), not per
            # batch item: digests land in flat scratch and scatter into
            # out_hashes' interleaved rows (a 32-byte-per-row copy)
            ddig = np.empty((b * k, 32), dtype=np.uint8)
            self._lib.cb_sha256_rows(
                shards.ctypes.data_as(ctypes.c_char_p), b * k, s,
                ddig.ctypes.data_as(ctypes.c_void_p), nt,
            )
            out_hashes[:, :k] = ddig.reshape(b, k, 32)
            pdig = np.empty((b * r, 32), dtype=np.uint8)
            self._lib.cb_sha256_rows(
                out_parity.ctypes.data_as(ctypes.c_char_p), b * r, s,
                pdig.ctypes.data_as(ctypes.c_void_p), nt,
            )
            out_hashes[:, k:] = pdig.reshape(b, r, 32)
            return out_parity, out_hashes
        self._lib.cb_encode_hash(
            mat.ctypes.data_as(ctypes.c_char_p), r, k,
            shards.ctypes.data_as(ctypes.c_char_p), b, s,
            out_parity.ctypes.data_as(ctypes.c_void_p),
            out_hashes.ctypes.data_as(ctypes.c_void_p),
            nt,
        )
        return out_parity, out_hashes
