"""GF(2^8) arithmetic core.

Field: GF(2^8) with the reducing polynomial x^8 + x^4 + x^3 + x^2 + 1
(0x11d) and generator 2 — the exact field used by the reference's erasure
codec, the ``reed-solomon-erasure`` crate's ``galois_8::Field`` (reference:
Cargo.toml:21; used at src/file/file_part.rs:77,161,302), which is itself the
Backblaze JavaReedSolomon convention.  Shard-level byte-identity with the
reference depends on this module being exactly that field.

Everything here is plain numpy on the host: tables are tiny (≤64 KiB) and the
hot batched codec paths live in the backends (ops/cpu_backend.py,
ops/jax_backend.py), not here.
"""

from __future__ import annotations

import numpy as np

GF_POLY = 0x11D
GF_GEN = 2
ORDER = 255


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    # exp is periodic with period 255; extend so exp[log a + log b] never wraps
    for i in range(ORDER, 512):
        exp[i] = exp[i - ORDER]
    log[0] = -1  # log(0) is undefined; sentinel
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()

# MUL_TABLE[a, b] = a ⊗ b over GF(2^8); 64 KiB, used to derive per-coefficient
# lookup rows for the numpy codec and the bit-matrices for the TPU codec.
_a = np.arange(256, dtype=np.int32)
_la = LOG_TABLE[_a][:, None]
_lb = LOG_TABLE[_a][None, :]
MUL_TABLE = EXP_TABLE[(_la + _lb) % ORDER].astype(np.uint8)
MUL_TABLE[0, :] = 0
MUL_TABLE[:, 0] = 0
del _a, _la, _lb


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % ORDER])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(EXP_TABLE[(ORDER - LOG_TABLE[a]) % ORDER])


def gf_pow(a: int, n: int) -> int:
    """a^n with the Backblaze ``galois.exp`` convention: a^0 == 1, 0^n == 0
    for n > 0.  This is what the reference's Vandermonde builder relies on."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % ORDER])


def gf_mul_bytes(c: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of ``data`` by the constant ``c`` (vectorized)."""
    return MUL_TABLE[c][data]


def mul_bit_matrix(c: int) -> np.ndarray:
    """The 8x8 GF(2) matrix of 'multiply by constant c'.

    GF(2^8) is an 8-dimensional vector space over GF(2); multiplication by a
    constant is linear, so ``bits(c ⊗ x) = M_c @ bits(x) (mod 2)`` where
    column j of M_c holds ``bits(c ⊗ 2^j)``.  This is the bridge that turns
    the reference's byte-wise GF codec (src/file/file_part.rs:161) into plain
    binary matmuls that run on the TPU MXU.

    Returns uint8 [8, 8]; row k, col j = bit k of c ⊗ 2^j. Bit 0 is the LSB.
    """
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = gf_mul(c, 1 << j)
        for k in range(8):
            m[k, j] = (prod >> k) & 1
    return m


def expand_to_bit_matrix(mat: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix [r, c] into its GF(2) bit-matrix [r*8, c*8].

    Block (i, j) is ``mul_bit_matrix(mat[i, j])``, so for byte vectors x,
    ``bits(mat ⊗ x) = expand_to_bit_matrix(mat) @ bits(x) (mod 2)``.
    """
    r, c = mat.shape
    out = np.zeros((r * 8, c * 8), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            out[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = mul_bit_matrix(
                int(mat[i, j])
            )
    return out
