"""The ``ErasureBackend`` boundary and the per-geometry ``ErasureCoder``.

This is the pluggable seam the north-star asks for, placed at exactly the
boundary the reference has between its part codec and the
``reed-solomon-erasure`` crate (reference: src/file/file_part.rs:77,128,
161-165,302-305 — ``ReedSolomon::new(d, p)`` / ``encode_sep`` /
``reconstruct`` / ``reconstruct_data``).

A backend implements one primitive — apply a GF(2^8) matrix to a batch of
stacked shards — and the coder builds the encode/decode matrices on the host
(they are tiny) and dispatches batches to it.  Backends:

* ``numpy``  — pure-numpy table codec; always available; slow-ish.
* ``native`` — C++ table codec via ctypes (ops/cpu_backend.py); the CPU
  oracle, byte-identical to the reference's crate.
* ``jax``    — batched bit-plane matmuls on TPU (ops/jax_backend.py).
* ``mesh``   — the same bit-plane kernels sharded over every visible
  device with per-dispatch layout selection and a double-buffered
  dispatch window (ops/mesh_backend.py); ``jax:dp4,sp2`` pins one
  explicit mesh instead (parallel/backend.py).

All of them produce byte-identical shards; tests assert it.
"""

from __future__ import annotations

import hashlib
import threading
from abc import ABC, abstractmethod
from typing import Callable, Optional, Sequence

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops import gf256, matrix


class ErasureBackend(ABC):
    """Applies GF(2^8) matrices to batches of shards."""

    name: str = "abstract"

    @abstractmethod
    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        """out[b, i, s] = XOR_k mat[i, k] ⊗ shards[b, k, s].

        ``mat`` is uint8 [r, k]; ``shards`` is uint8 [B, k, S]; returns
        uint8 [B, r, S].
        """


class NumpyBackend(ErasureBackend):
    """Vectorized table-lookup codec; the always-available fallback."""

    name = "numpy"

    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        b, k, s = shards.shape
        r = mat.shape[0]
        out = np.zeros((b, r, s), dtype=np.uint8)
        for i in range(r):
            acc = out[:, i, :]
            for j in range(k):
                c = int(mat[i, j])
                if c == 0:
                    continue
                if c == 1:
                    acc ^= shards[:, j, :]
                else:
                    acc ^= gf256.gf_mul_bytes(c, shards[:, j, :])
        return out


_REGISTRY: dict[str, ErasureBackend] = {}
_REGISTRY_LOCK = threading.Lock()


def register_backend(backend: ErasureBackend) -> None:
    with _REGISTRY_LOCK:
        _REGISTRY[backend.name] = backend


def cpu_fallback_backend() -> ErasureBackend:
    """The codec used whenever a device backend degrades (init timeout,
    mid-run dispatch timeout): the native C++ engine when it builds,
    else numpy.  One definition so every degrade path picks fallbacks
    identically."""
    try:
        from chunky_bits_tpu.ops.cpu_backend import NativeBackend

        return NativeBackend()
    # lint: broad-except-ok native build probe; numpy fallback is
    # byte-identical (conformance tests pin it), only slower
    except Exception:
        return NumpyBackend()


def _build_device_backend(name: str, build: Callable[[], ErasureBackend],
                          what: str) -> ErasureBackend:
    """Construct a device backend; on a device-init timeout degrade
    ``backend: jax`` to the native CPU codec with a loud warning instead
    of hanging the operation (the tunneled chip's PJRT init blocks
    forever when the endpoint is down — init-time outages only; see
    jax_backend.await_device_init).  The caller registers a degraded
    instance under the *requested* name so one process pays the timeout
    at most once per spec.  Other failures keep their ErasureError
    contract."""
    from chunky_bits_tpu.errors import DeviceInitTimeout

    try:
        return build()
    except DeviceInitTimeout as err:
        import warnings

        warnings.warn(
            f"backend {name!r} unavailable: {err}; DEGRADED to the "
            f"native CPU codec for the rest of this process (output "
            f"stays byte-identical, throughput drops to the host's CPU "
            f"band)", RuntimeWarning, stacklevel=4)
        return cpu_fallback_backend()
    except ErasureError:
        raise
    except Exception as err:  # e.g. no usable jax device/platform
        raise ErasureError(f"{what} unavailable: {err}") from err


def get_backend(name: Optional[str] = None) -> ErasureBackend:
    """Resolve a backend by name, building it lazily.

    ``None`` resolves the default: $CHUNKY_BITS_TPU_BACKEND if set, else the
    native C++ oracle if it builds, else numpy.  The ``jax`` backend is only
    picked by explicit request (cluster tunables or env) because importing
    jax in short-lived CLI calls costs seconds.
    """
    if name is None:
        from chunky_bits_tpu.cluster.tunables import BACKEND_ENV, env_str

        name = env_str(BACKEND_ENV) or "auto"
    with _REGISTRY_LOCK:
        if name in _REGISTRY:
            return _REGISTRY[name]
    if name == "numpy":
        backend: ErasureBackend = NumpyBackend()
    elif name == "native":
        from chunky_bits_tpu.ops.cpu_backend import NativeBackend

        backend = NativeBackend()
    elif name.startswith("native:"):
        # explicit host thread count, e.g. "native:4" — bounds the C++
        # codec/hasher's std::thread fan-out (plain "native" uses
        # hardware_concurrency); the knob cluster.yaml tunables expose
        # for hosts shared with other work
        from chunky_bits_tpu.ops.cpu_backend import NativeBackend

        spec = name[len("native:"):]
        if not spec.isdigit() or int(spec) < 1:
            raise ErasureError(
                f"bad native thread count {spec!r} (want e.g. native:4)")
        backend = NativeBackend(nthreads=int(spec))
        backend.name = name
    elif name == "jax":
        from chunky_bits_tpu.ops.jax_backend import JaxBackend

        backend = _build_device_backend(name, JaxBackend,
                                        "jax erasure backend")
        if backend.name != "jax":  # degraded: cache under requested name
            with _REGISTRY_LOCK:
                _REGISTRY[name] = backend
            return backend
    elif name == "mesh":
        # every visible device, per-dispatch auto layout, double-buffered
        # dispatch window (ops/mesh_backend.py); same degrade contract as
        # "jax" — a device-init timeout caches the CPU fallback under the
        # requested name so the process pays the timeout once
        from chunky_bits_tpu.ops.mesh_backend import MeshBackend

        backend = _build_device_backend(name, MeshBackend,
                                        "mesh erasure backend")
        if backend.name != "mesh":  # degraded: cache under requested name
            with _REGISTRY_LOCK:
                _REGISTRY[name] = backend
            return backend
    elif name.startswith("jax:"):
        # mesh-sharded device backend, e.g. "jax:dp4,sp2" / "jax:tp4"
        # (parallel/backend.py)
        from chunky_bits_tpu.parallel.backend import MeshJaxBackend

        backend = _build_device_backend(
            name, lambda: MeshJaxBackend(name[len("jax:"):]),
            f"mesh jax backend {name!r}")
        if not backend.name.startswith("jax"):
            # degraded: cache under the requested spelling only — never
            # clobber the registry's own "native"/"numpy" entries
            with _REGISTRY_LOCK:
                _REGISTRY[name] = backend
            return backend
        # Register the canonical resolved name AND the requested spelling
        # so repeat lookups under either hit the cache.
        register_backend(backend)
        if backend.name != name:
            with _REGISTRY_LOCK:
                _REGISTRY[name] = backend
        return backend
    elif name == "auto":
        try:
            from chunky_bits_tpu.ops.cpu_backend import NativeBackend

            backend = NativeBackend()
        # lint: broad-except-ok native build probe; numpy fallback is
        # byte-identical, only slower
        except Exception:
            backend = NumpyBackend()
        with _REGISTRY_LOCK:
            _REGISTRY["auto"] = backend
            _REGISTRY.setdefault(backend.name, backend)
        return backend
    else:
        raise ErasureError(f"unknown erasure backend {name!r}")
    register_backend(backend)
    return backend


def _hash_rows_hashlib(rows: np.ndarray, out: np.ndarray,
                       nthreads: int = 0) -> None:
    """out[..., 32] = sha256 of each row of uint8 rows[..., S].
    ``nthreads`` is accepted for signature parity with the native engine
    and ignored — hashlib runs row-at-a-time under the GIL here; callers
    wanting parallelism slice rows across the host pipeline's workers."""
    for idx in np.ndindex(rows.shape[:-1]):
        out[idx] = np.frombuffer(
            hashlib.sha256(np.ascontiguousarray(rows[idx])).digest(),
            dtype=np.uint8)


_ROW_HASHER = None


def row_hasher() -> Callable[..., None]:
    """Bulk shard-row hasher ``fn(rows[..., S], out[..., 32],
    nthreads=0)``: the native SHA-NI engine when it builds (GIL-free;
    ``nthreads`` caps its internal fan-out — the host pipeline passes 1
    per slice), else a hashlib loop computing identical digests."""
    global _ROW_HASHER
    if _ROW_HASHER is None:
        try:
            from chunky_bits_tpu.ops.cpu_backend import (sha256_buf,
                                                         sha256_rows)

            sha256_buf(b"")  # force the deferred C++ build now
            _ROW_HASHER = sha256_rows
        # lint: broad-except-ok native build probe; the hashlib loop
        # computes the identical digests, only slower
        except Exception:
            _ROW_HASHER = _hash_rows_hashlib
    return _ROW_HASHER


_CODER_CACHE: dict[tuple[int, int, str, str], "ErasureCoder"] = {}
_CODER_LOCK = threading.Lock()

#: the closed set of erasure codes a part may declare (file/chunk.py
#: ``code:`` field): classic Reed-Solomon and the product-matrix MSR
#: regenerating code (ops/pm_msr.py).  Anything else is a
#: newer/foreign writer — readers degrade to a clean error, never a
#: guess (a non-member code could be non-systematic, so even a
#: fully-healthy read must refuse rather than concatenate data chunks)
KNOWN_CODES = ("rs", "pm-msr")


class ErasureCoder:
    """Reed-Solomon codec for one (d, p) geometry — the ``ReedSolomon::new``
    equivalent (reference: src/file/file_part.rs:77).

    Batched variants take uint8 arrays shaped [B, shards, S]; the scalar
    variants mirror the crate's per-part API and are thin wrappers.
    """

    #: wire-format code name (file/chunk.py ``code:`` field); the
    #: product-matrix MSR subclass (ops/pm_msr.py) overrides
    code = "rs"
    #: whether the host pipeline's chunk-granular fused native ingest
    #: (parity_rows applied to [B, d, S] + per-stripe SHA in one pass)
    #: is valid for this code; sub-symbol codes take the decomposed path
    supports_fused_ingest = True

    def __init__(self, data: int, parity: int,
                 backend: Optional[ErasureBackend] = None) -> None:
        if data < 1:
            raise ErasureError("data shard count must be >= 1")
        if parity < 0:
            raise ErasureError("parity shard count must be >= 0")
        self.data = data
        self.parity = parity
        self.backend = backend or get_backend()
        self.encode_matrix = matrix.build_encode_matrix(data, parity)
        self.parity_rows = self.encode_matrix[data:]

    def shard_len(self, length: int) -> int:
        """Bytes per shard for a part holding ``length`` meaningful
        bytes — the reference's round-up split
        (src/file/file_part.rs:150-158).  Sub-symbol codes round up
        further so every chunk divides into equal stripes."""
        return (length + self.data - 1) // self.data if length > 0 else 0

    # ---- batched API (the TPU-friendly surface) ----

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """parity[B, p, S] from data[B, d, S] (crate: ``encode_sep``)."""
        if data.ndim != 3 or data.shape[1] != self.data:
            raise ErasureError(
                f"expected data shaped [B, {self.data}, S], got {data.shape}"
            )
        if self.parity == 0:
            b, _, s = data.shape
            return np.zeros((b, 0, s), dtype=np.uint8)
        return self.backend.apply_matrix(self.parity_rows, data)

    def encode_hash_batch(
        self, data: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Parity plus per-shard content hashes for a batch of parts —
        the ingest step's full compute (reference: encode at
        src/file/file_part.rs:161-165, per-shard sha256 at :185).

        Returns ``(parity[B, p, S], digests[B, d+p, 32])`` with digest
        rows ordered data shards then parity shards.  Backends exposing a
        fused ``encode_and_hash`` (the native C++ engine) do both in one
        cache-hot pass; otherwise parity comes from ``encode_batch`` and
        hashing falls back to hashlib.
        """
        if data.ndim != 3 or data.shape[1] != self.data:
            raise ErasureError(
                f"expected data shaped [B, {self.data}, S], got {data.shape}"
            )
        fused = (getattr(self.backend, "encode_and_hash", None)
                 if self.supports_fused_ingest else None)
        if fused is not None:
            return fused(self.parity_rows, np.ascontiguousarray(data))
        data = np.ascontiguousarray(data)
        b, _, _ = data.shape
        hash_rows = row_hasher()
        data_digests = np.empty((b, self.data, 32), dtype=np.uint8)
        if getattr(self.backend, "async_dispatch", False):
            # device backends (mesh): hash the data rows on the shared
            # host pipeline's daemon workers (sliced across them) while
            # the device computes parity — the same overlap the retired
            # 2-worker ThreadPoolExecutor provided, now on the bounded
            # CB103-clean executor every host path shares
            from chunky_bits_tpu.parallel.host_pipeline import (
                get_host_pipeline,
                join_jobs,
            )

            jobs = get_host_pipeline().hash_rows_jobs(data, data_digests)
            parity = self.encode_batch(data)
            join_jobs(jobs)
        else:
            parity = self.encode_batch(data)
            hash_rows(data, data_digests)
        if not self.parity:
            return parity, data_digests
        parity = np.ascontiguousarray(parity)
        parity_digests = np.empty((b, self.parity, 32), dtype=np.uint8)
        hash_rows(parity, parity_digests)
        return parity, np.concatenate([data_digests, parity_digests], axis=1)

    def encode_hash_batches(
        self, batches: Sequence[np.ndarray]
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Feed-ahead ingest for several same-geometry batches: one
        ``(parity, digests)`` pair per input batch, byte-identical to
        calling ``encode_hash_batch`` per batch.

        On backends exposing a ``submit_apply`` staging surface (the
        ``mesh`` backend's dispatch pipeline), EVERY batch's dispatch is
        staged before any is collected, so batch k+1's H2D and the host
        hash stage run while batch k computes — the batching layer
        (ops/batching.py) routes merged groups here instead of paying
        the concatenate-and-slice copy.  Other backends just loop.
        """
        submit = getattr(self.backend, "submit_apply", None)
        if (submit is None or not self.parity
                or type(self).encode_batch is not ErasureCoder.encode_batch):
            # no staging surface, nothing to overlap (p=0), or a
            # subclass with its own encode math (pm-msr decomposes into
            # sub-symbol applies — those pipeline at block level inside
            # apply_matrix instead)
            return [self.encode_hash_batch(b) for b in batches]
        from chunky_bits_tpu.parallel.host_pipeline import (
            get_host_pipeline,
            join_jobs,
        )

        pipe = get_host_pipeline()
        hash_rows = row_hasher()
        staged = []
        for data in batches:
            if data.ndim != 3 or data.shape[1] != self.data:
                raise ErasureError(
                    f"expected data shaped [B, {self.data}, S], "
                    f"got {data.shape}")
            data = np.ascontiguousarray(data)
            b = data.shape[0]
            data_digests = np.empty((b, self.data, 32), dtype=np.uint8)
            parity_digests = np.empty((b, self.parity, 32), dtype=np.uint8)
            jobs = list(pipe.hash_rows_jobs(data, data_digests))
            covered = np.zeros(b, dtype=bool)

            def on_block(lo, arr, jobs=jobs, covered=covered,
                         pd=parity_digests):
                covered[lo:lo + arr.shape[0]] = True
                jobs.extend(pipe.hash_rows_jobs(
                    arr, pd[lo:lo + arr.shape[0]]))

            ticket = submit(self.parity_rows, data, on_block=on_block)
            staged.append((ticket, jobs, covered, data_digests,
                           parity_digests))
        out = []
        for ticket, jobs, covered, data_digests, parity_digests in staged:
            parity = ticket.result()
            join_jobs(jobs)
            if not covered.all():
                # rows the callback never saw (mid-run degrade's CPU
                # recompute) hash from the parity actually returned
                idx = np.flatnonzero(~covered)
                rest = np.empty((len(idx), self.parity, 32),
                                dtype=np.uint8)
                hash_rows(np.ascontiguousarray(parity[idx]), rest)
                parity_digests[idx] = rest
            out.append((parity, np.concatenate(
                [data_digests, parity_digests], axis=1)))
        return out

    def reconstruct_batch(
        self, shards: np.ndarray, present: Sequence[int],
        wanted: Sequence[int],
    ) -> np.ndarray:
        """Rebuild ``wanted`` shard rows for a batch sharing one erasure
        pattern.  ``shards[B, d+p, S]`` need only be valid at ``present``
        rows.  Returns [B, len(wanted), S].
        """
        present = sorted(present)
        picked = shards[:, np.array(present[: self.data], dtype=np.intp), :]
        return self.reconstruct_batch_picked(picked, present, wanted)

    def reconstruct_batch_picked(
        self, picked: np.ndarray, present: Sequence[int],
        wanted: Sequence[int],
    ) -> np.ndarray:
        """Like ``reconstruct_batch`` but over shards already gathered in
        decode layout: ``picked[B, d, S]`` holds the rows at
        ``sorted(present)[:d]``, in that order.  Callers that assemble
        the batch themselves (ops/batching.py) stack straight into this
        layout, skipping the full [B, d+p, S] scatter plus the row-pick
        copy that reconstruct_batch would redo."""
        present = sorted(present)
        dec = matrix.decode_matrix(self.encode_matrix, list(present),
                                   list(wanted))
        return self.backend.apply_matrix(dec, picked)

    # ---- per-part API mirroring the crate ----

    def encode(self, data_shards: Sequence[np.ndarray]) -> list[np.ndarray]:
        """Parity shards for one part's data shards (equal-length rows)."""
        rows = [np.frombuffer(s, dtype=np.uint8)
                if not isinstance(s, np.ndarray) else s
                for s in data_shards]
        if len({len(r) for r in rows}) > 1:
            raise ErasureError("shards must be of equal length")
        stacked = np.stack(rows)[None, ...]
        return list(self.encode_batch(stacked)[0])

    def _reconstruct_impl(
        self, shards: list[Optional[np.ndarray]], data_only: bool
    ) -> list[Optional[np.ndarray]]:
        total = self.data + self.parity
        if len(shards) != total:
            raise ErasureError(
                f"expected {total} shard slots, got {len(shards)}"
            )
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) == total:
            return shards
        if len(present) < self.data:
            raise ErasureError(
                f"too few shards present: {len(present)} < {self.data}"
            )
        limit = self.data if data_only else total
        missing = [i for i in range(limit) if shards[i] is None]
        if not missing:
            return shards
        size = len(shards[present[0]])
        stacked = np.zeros((1, total, size), dtype=np.uint8)
        for i in present:
            row = shards[i]
            if not isinstance(row, np.ndarray):
                row = np.frombuffer(row, dtype=np.uint8)
            if len(row) != size:
                raise ErasureError("shards must be of equal length")
            stacked[0, i] = row
        rebuilt = self.reconstruct_batch(stacked, present, missing)[0]
        out = list(shards)
        for row, idx in zip(rebuilt, missing):
            out[idx] = row
        return out

    def reconstruct(
        self, shards: list[Optional[np.ndarray]]
    ) -> list[Optional[np.ndarray]]:
        """Fill every missing shard (crate: ``reconstruct``,
        reference call site src/file/file_part.rs:302-305)."""
        return self._reconstruct_impl(shards, data_only=False)

    def reconstruct_data(
        self, shards: list[Optional[np.ndarray]]
    ) -> list[Optional[np.ndarray]]:
        """Fill missing *data* shards only (crate: ``reconstruct_data``,
        reference call site src/file/file_part.rs:128)."""
        return self._reconstruct_impl(shards, data_only=True)


def get_coder(data: int, parity: int,
              backend: Optional[str] = None,
              code: str = "rs") -> ErasureCoder:
    """Cached coder lookup; matrices are rebuilt once per
    (d, p, backend, code).  ``code`` is the per-part wire-format value
    ("rs" — the default and the only value old references carry — or
    "pm-msr", the product-matrix MSR regenerating code); an unknown
    value raises ErasureError so callers degrade to a clean read error
    instead of guessing at a foreign writer's math."""
    if code not in KNOWN_CODES:
        raise ErasureError(
            f"unknown erasure code {code!r} (this reader knows "
            f"{', '.join(KNOWN_CODES)})")
    be = get_backend(backend)
    key = (data, parity, be.name, code)
    with _CODER_LOCK:
        coder = _CODER_CACHE.get(key)
        if coder is None:
            if code == "pm-msr":
                from chunky_bits_tpu.ops.pm_msr import PMMSRCoder

                coder = PMMSRCoder(data, parity, be)
            else:
                coder = ErasureCoder(data, parity, be)
            _CODER_CACHE[key] = coder
        return coder
