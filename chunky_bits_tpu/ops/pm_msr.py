"""Product-matrix MSR regenerating code over GF(2^8).

The construction is Rashmi-Shah-Kumar's product-matrix MSR code at the
``d = 2(k-1)`` point, in the systematic form the "Fast Product-Matrix
Regenerating Codes" line (PAPERS.md, arXiv:1412.3022) benchmarks, with
the polynomial-realization framing of arXiv:1312.5155 guiding the
implementation shape: every operation — encode, decode, helper
projection, repair combine — is a GF(2^8) matrix applied to stacked
sub-symbol stripes, so the whole code runs through the exact
``ErasureBackend.apply_matrix`` primitive the Reed-Solomon path uses
(bit-plane matmuls on device, table/XOR-schedule kernels on the host)
and is byte-identical across numpy/native/jax by the same argument.

**Shape.**  A part still has ``k`` data + ``p`` parity chunks behind
the same ``Chunk`` wire format; each chunk is additionally α = k-1
contiguous sub-symbol stripes (chunk bytes [j*S/α, (j+1)*S/α) form
stripe j — a plain C-order reshape).  Byte position t of every stripe
is one independent MSR codeword over GF(2^8):

    message matrix  M = [S1; S2]   (2α x α, S1/S2 symmetric — B = kα
                                    free symbols)
    encoding matrix Ψ = [Φ  ΛΦ]    (n x 2α Vandermonde on distinct
                                    x_i = g^i; Φ its first α columns,
                                    λ_i = x_i^α distinct)
    node i stores   ψ_i^T M        (α symbols)

Any 2α rows of Ψ are independent (Vandermonde), any α rows of Φ are
independent, and the λ_i are distinct — the three RSK conditions, so
any ``k`` nodes reconstruct and any single node regenerates exactly
from any ``d' = 2(k-1)`` helpers, each contributing ONE symbol
(β = chunk/α bytes): helper i ships ``ψ_i^T M φ_f``, the collector
inverts the helpers' Ψ rows to get ``M φ_f = [S1 φ_f; S2 φ_f]`` and
reads the lost row back off the symmetry of S1/S2.  Total repair
traffic ``d'·β = 2·chunk`` instead of Reed-Solomon's ``k·chunk``.

**Systematic remap.**  The raw construction is not systematic; because
the data-collection property makes ``message -> first-k-node contents``
a bijection, the code precomputes the linear map ``T`` (message to all
node contents), inverts its systematic block, and keeps the composite
generator ``G = T · T_sys^{-1}`` whose top ``kα`` rows are the
identity — data chunks store the user's bytes verbatim (old readers
and the interop decoder keep working), parity chunks are ``G``'s
bottom ``pα`` rows applied per stripe.  Node contents remain of the
form ``Ψ [S1; S2]`` for symmetric S1/S2 (the remap only re-chooses the
message), so the repair identities above hold unchanged.

**Geometry.**  ``k >= 2``, ``p >= k-1`` (so ``d' = 2(k-1) <= n-1``
helpers exist), ``n <= 255`` (distinct nonzero x_i), the λ_i must be
distinct (checked; fails only for α sharing a large factor with 255 at
very wide n), and chunk sizes must be α-divisible (the writer rounds
shard lengths up; power-of-two chunk sizes additionally need α to be a
power of two).  ``geometry_error`` is the one shared validator.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops import gf256, matrix
from chunky_bits_tpu.ops.backend import ErasureBackend, ErasureCoder

#: the wire-format name (file/chunk.py ``code:`` field, cluster profile
#: ``code`` knob) — THE closed-set value next to "rs"
CODE_NAME = "pm-msr"


def geometry_error(data: int, parity: int,
                   chunk_size: Optional[int] = None) -> Optional[str]:
    """Why (data, parity[, chunk_size]) cannot run pm-msr, or None.

    The one validator shared by profile parsing (loud SerdeError for an
    explicit YAML ``code: pm-msr``), the env-default leniency check
    (an env-requested default silently stays ``rs`` on unsupported
    geometry), and the coder constructor."""
    if data < 2:
        return "pm-msr needs data >= 2 (alpha = data-1 sub-symbols)"
    if parity < data - 1:
        return (f"pm-msr needs parity >= data-1 "
                f"({2 * (data - 1)} helpers must survive one loss); "
                f"got d={data} p={parity}")
    n = data + parity
    if n > 255:
        return f"pm-msr needs d+p <= 255 distinct GF(2^8) points, got {n}"
    alpha = data - 1
    lams = {gf256.gf_pow(gf256.gf_pow(gf256.GF_GEN, i), alpha)
            for i in range(n)}
    if len(lams) != n:
        return (f"pm-msr x_i^alpha collision at d={data} p={parity} "
                f"(alpha={alpha} shares a factor with 255 at this width)")
    if chunk_size is not None and chunk_size % alpha != 0:
        return (f"pm-msr needs chunk_size divisible by alpha={alpha}, "
                f"got {chunk_size}")
    return None


def _build_generator(data: int, parity: int
                     ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]:
    """(G [nα, kα], Φ [n, α], λ [n], Ψ [n, 2α]) for one geometry.

    ``G``'s top kα rows are asserted to be the identity (systematic);
    construction cost is O((nα)·(kα)) small-int GF ops — matrices are
    tiny (kα <= ~60 for realistic widths) and cached per geometry by
    ``get_coder``.
    """
    err = geometry_error(data, parity)
    if err is not None:
        raise ErasureError(err)
    alpha = data - 1
    dh = 2 * alpha
    n = data + parity
    xs = [gf256.gf_pow(gf256.GF_GEN, i) for i in range(n)]
    psi = np.zeros((n, dh), dtype=np.uint8)
    for i, x in enumerate(xs):
        for j in range(dh):
            psi[i, j] = gf256.gf_pow(x, j)
    phi = psi[:, :alpha].copy()
    lam = np.array([gf256.gf_pow(x, alpha) for x in xs], dtype=np.uint8)

    # message layout: S1's upper triangle then S2's — B = α(α+1) = kα
    tri = [(i, j) for i in range(alpha) for j in range(i, alpha)]
    b_syms = 2 * len(tri)
    assert b_syms == data * alpha

    t_mat = np.zeros((n * alpha, b_syms), dtype=np.uint8)
    for t in range(b_syms):
        s1 = np.zeros((alpha, alpha), dtype=np.uint8)
        s2 = np.zeros((alpha, alpha), dtype=np.uint8)
        if t < len(tri):
            i, j = tri[t]
            s1[i, j] = s1[j, i] = 1
        else:
            i, j = tri[t - len(tri)]
            s2[i, j] = s2[j, i] = 1
        m = np.concatenate([s1, s2], axis=0)  # [2α, α]
        t_mat[:, t] = matrix.gf_matmul(psi, m).reshape(-1)
    # data-collection property => the systematic block is invertible
    gen = matrix.gf_matmul(t_mat, matrix.gf_invert(t_mat[:data * alpha]))
    assert np.array_equal(gen[:data * alpha],
                          np.eye(data * alpha, dtype=np.uint8))
    return gen, phi, lam, psi


class PMMSRCoder(ErasureCoder):
    """The product-matrix MSR codec for one (k, p) geometry, presenting
    the same surface as the Reed-Solomon ``ErasureCoder`` (encode /
    reconstruct / batched variants) plus the regeneration surface the
    repair planner drives (``projection_matrix`` / ``repair_matrix`` /
    ``project_batch`` / ``repair_batch``).

    All shard/stripe math dispatches through ``backend.apply_matrix``,
    so the backend-identity and XOR-schedule paths cover this code with
    no new kernels.
    """

    code = CODE_NAME
    #: the host pipeline's per-stripe fused native ingest assumes the
    #: RS [p, d] parity map at chunk granularity; pm-msr's parity map is
    #: [pα, kα] over sub-stripes, so it takes the decomposed path
    supports_fused_ingest = False

    def __init__(self, data: int, parity: int,
                 backend: Optional[ErasureBackend] = None) -> None:
        # deliberately NOT calling super().__init__: the RS Vandermonde
        # encode matrix does not exist for this code, and leaving a
        # wrong-shaped ``parity_rows`` around would invite misuse
        from chunky_bits_tpu.ops.backend import get_backend

        self.data = data
        self.parity = parity
        self.backend = backend or get_backend()
        self.gen_matrix, self.phi, self.lam, self.psi = \
            _build_generator(data, parity)
        self.alpha = data - 1
        #: helpers a single-chunk regeneration needs (d' = 2(k-1))
        self.helpers = 2 * self.alpha

    # ---- geometry helpers ----

    def shard_len(self, length: int) -> int:
        """ceil(length/k) rounded up to an α multiple — every chunk
        must split into α equal stripes."""
        base = (length + self.data - 1) // self.data if length > 0 else 0
        return ((base + self.alpha - 1) // self.alpha) * self.alpha

    def beta_bytes(self, chunksize: int) -> int:
        """One helper's repair contribution for a ``chunksize`` chunk."""
        self._check_size(chunksize)
        return chunksize // self.alpha

    def _check_size(self, size: int) -> None:
        if size % self.alpha != 0:
            raise ErasureError(
                f"pm-msr shard length must be a multiple of "
                f"alpha={self.alpha}, got {size}")

    def _sub(self, shards: np.ndarray) -> np.ndarray:
        """[B, rows, S] -> [B, rows*α, S/α] (stripes are contiguous
        chunk segments, so this is a plain C-order reshape)."""
        b, rows, s = shards.shape
        self._check_size(s)
        return np.ascontiguousarray(shards).reshape(
            b, rows * self.alpha, s // self.alpha)

    # ---- batched codec surface (same contract as ErasureCoder) ----

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """parity[B, p, S] from data[B, k, S] (S % α == 0)."""
        if data.ndim != 3 or data.shape[1] != self.data:
            raise ErasureError(
                f"expected data shaped [B, {self.data}, S], "
                f"got {data.shape}")
        b, _, s = data.shape
        if s == 0:
            return np.zeros((b, self.parity, 0), dtype=np.uint8)
        out = self.backend.apply_matrix(
            self.gen_matrix[self.data * self.alpha:], self._sub(data))
        return out.reshape(b, self.parity, s)

    # encode_hash_batch is inherited: ``supports_fused_ingest = False``
    # makes the base method skip the backend's chunk-granular fused
    # pass (wrong matrix shape for a stripe-structured code) and run
    # this class's encode_batch + per-shard hashing — including the
    # hash-while-the-device-encodes overlap on async backends

    def reconstruct_batch_picked(
        self, picked: np.ndarray, present: Sequence[int],
        wanted: Sequence[int],
    ) -> np.ndarray:
        """Rebuild ``wanted`` chunk rows from ``picked[B, k, S]`` (the
        rows at ``sorted(present)[:k]``, in that order) — the decode
        layout ``ReconstructBatcher`` stacks straight into."""
        present = sorted(present)[:self.data]
        a = self.alpha
        pres_rows = np.array([ci * a + j for ci in present
                              for j in range(a)], dtype=np.intp)
        want_rows = np.array([ci * a + j for ci in wanted
                              for j in range(a)], dtype=np.intp)
        # any k chunks' stripe rows of G are invertible (the MDS /
        # data-collection property); gf_invert raises on the impossible
        dec = matrix.gf_matmul(
            self.gen_matrix[want_rows],
            matrix.gf_invert(self.gen_matrix[pres_rows]))
        b, _, s = picked.shape
        out = self.backend.apply_matrix(dec, self._sub(picked))
        return out.reshape(b, len(list(wanted)), s)

    # reconstruct_batch / reconstruct / reconstruct_data / encode are
    # inherited: they funnel into reconstruct_batch_picked/encode_batch

    # ---- the regeneration surface (cluster/repair.py drives this) ----

    def projection_matrix(self, failed: int) -> np.ndarray:
        """[1, α] helper projection coefficients for regenerating chunk
        ``failed``: every helper applies ``φ_failed`` to its own α
        stripes and ships the β-sized result.  Identical for all
        helpers — the failed node's Φ row, not the helper's."""
        self._check_index(failed)
        return self.phi[failed][None, :].copy()

    def repair_matrix(self, failed: int,
                      helpers: Sequence[int]) -> np.ndarray:
        """[α, d'] combine matrix: stacked helper projections (in
        ``helpers`` order) in, the failed chunk's α stripes out —
        ``[I | λ_f·I] · Ψ_H^{-1}`` (module docstring)."""
        self._check_index(failed)
        helpers = list(helpers)
        if len(helpers) != self.helpers:
            raise ErasureError(
                f"pm-msr repair needs exactly {self.helpers} helpers, "
                f"got {len(helpers)}")
        if failed in helpers or len(set(helpers)) != len(helpers):
            raise ErasureError(
                f"pm-msr helpers must be distinct and exclude the "
                f"failed chunk: failed={failed} helpers={helpers}")
        for h in helpers:
            self._check_index(h)
        psi_inv = matrix.gf_invert(
            self.psi[np.array(helpers, dtype=np.intp)])
        a = self.alpha
        lam_i = np.zeros((a, self.helpers), dtype=np.uint8)
        for j in range(a):
            lam_i[j, j] = 1
            lam_i[j, a + j] = int(self.lam[failed])
        return matrix.gf_matmul(lam_i, psi_inv)

    def project_batch(self, failed: int,
                      content: np.ndarray) -> np.ndarray:
        """Helper-side compute: ``content[B, S]`` (whole helper chunks,
        S % α == 0) -> ``[B, S/α]`` projections for ``failed``."""
        if content.ndim != 2:
            raise ErasureError(
                f"expected content [B, S], got {content.shape}")
        b, s = content.shape
        sub = self._sub(content.reshape(b, 1, s))
        out = self.backend.apply_matrix(self.projection_matrix(failed),
                                        sub)
        return out.reshape(b, s // self.alpha)

    def repair_batch(self, failed: int, helpers: Sequence[int],
                     projections: np.ndarray) -> np.ndarray:
        """Collector-side combine: ``projections[B, d', β]`` (row order
        = ``helpers`` order) -> the failed chunk's bytes ``[B, d'·β/2]``
        (= α·β = chunksize)."""
        if projections.ndim != 3 or projections.shape[1] != self.helpers:
            raise ErasureError(
                f"expected projections [B, {self.helpers}, beta], "
                f"got {projections.shape}")
        b, _, beta = projections.shape
        out = self.backend.apply_matrix(
            self.repair_matrix(failed, helpers),
            np.ascontiguousarray(projections))
        return out.reshape(b, self.alpha * beta)

    def _check_index(self, ci: int) -> None:
        if not 0 <= ci < self.data + self.parity:
            raise ErasureError(
                f"chunk index {ci} out of range for "
                f"d={self.data} p={self.parity}")
