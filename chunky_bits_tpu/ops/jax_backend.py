"""JAX/TPU erasure backend: GF(2^8) as batched bit-plane matmuls.

The idea (TPU-first, not a translation of the reference's byte-table SIMD):
GF(2^8) is an 8-dim vector space over GF(2), and multiplying by a constant is
GF(2)-linear.  Expanding the (d+p) x d byte matrix into an 8x-larger binary
matrix turns the whole Reed-Solomon transform into

    out_bits[B, r*8, S] = M2[r*8, k*8] @ bits[B, k*8, S]   (mod 2)

— a plain matmul with 0/1 operands, which is exactly what the MXU is for.
Products are 0/1 and the contraction length is k*8 <= 2048, so bf16 inputs
with f32 accumulation are exact; the mod-2 and the byte pack/unpack are cheap
VPU element-wise ops that XLA fuses around the matmul.

The same primitive serves encode (parity rows) and decode (host-inverted
rows), replacing the reference's CPU hot loops at
src/file/file_part.rs:161-165 (encode_sep) and :128,302 (reconstruct).

Multi-chip: parts are independent, so scaling is a shard_map over the batch
axis with the bit-matrix replicated (see chunky_bits_tpu/parallel once the
mesh layer lands); the only collective is the gather of parity shards back to
the host I/O engine.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from chunky_bits_tpu.ops import gf256
from chunky_bits_tpu.ops.backend import ErasureBackend

# Deferred jax import: the CLI must not pay jax start-up unless this backend
# is actually selected.
_jax = None
_jnp = None
_IMPORT_LOCK = threading.Lock()


def _ensure_jax():
    global _jax, _jnp
    if _jax is None:
        with _IMPORT_LOCK:
            if _jax is None:
                import jax
                import jax.numpy as jnp

                _jax, _jnp = jax, jnp
    return _jax, _jnp


_APPLY_FN = None


def _jitted_apply():
    """Build the jitted bit-plane transform once per process."""
    global _APPLY_FN
    if _APPLY_FN is not None:
        return _APPLY_FN
    jax, jnp = _ensure_jax()

    def apply(m2, shards):
        # m2: bf16 [r8, k8] of 0/1; shards: uint8 [B, k, S]
        b, k, s = shards.shape
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = (shards[:, :, None, :] >> shifts[None, None, :, None]) & 1
        bits = bits.reshape(b, k * 8, s).astype(jnp.bfloat16)
        acc = jnp.einsum(
            "rk,bks->brs", m2, bits, preferred_element_type=jnp.float32
        )
        out_bits = acc.astype(jnp.int32) & 1
        r8 = m2.shape[0]
        out_bits = out_bits.reshape(b, r8 // 8, 8, s)
        packed = jnp.sum(out_bits << shifts[None, None, :, None], axis=2)
        return packed.astype(jnp.uint8)

    _APPLY_FN = jax.jit(apply)
    return _APPLY_FN


class JaxBackend(ErasureBackend):
    """Erasure math on the default JAX device (TPU when present)."""

    name = "jax"

    #: cap device memory per dispatch: bits blow bytes up 8x as bf16 (16x B)
    max_block_bytes = 64 << 20

    #: decode matrices are one-per-erasure-pattern; bound the device cache so
    #: a long-running resilver over many patterns cannot grow memory forever.
    max_cached_matrices = 256

    def __init__(self) -> None:
        _ensure_jax()
        self._m2_cache: OrderedDict[bytes, object] = OrderedDict()
        self._lock = threading.Lock()

    def _bit_matrix(self, mat: np.ndarray):
        jax, jnp = _ensure_jax()
        key = mat.tobytes() + bytes(mat.shape[0:1])
        with self._lock:
            cached = self._m2_cache.get(key)
            if cached is not None:
                self._m2_cache.move_to_end(key)
                return cached
        m2 = gf256.expand_to_bit_matrix(mat).astype(np.float32)
        dev = jnp.asarray(m2, dtype=jnp.bfloat16)
        with self._lock:
            self._m2_cache[key] = dev
            while len(self._m2_cache) > self.max_cached_matrices:
                self._m2_cache.popitem(last=False)
        return dev

    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        jax, jnp = _ensure_jax()
        b, k, s = shards.shape
        r = mat.shape[0]
        if r == 0 or b == 0:
            return np.zeros((b, r, s), dtype=np.uint8)
        m2 = self._bit_matrix(mat)
        fn = _jitted_apply()
        # Block the batch axis so the 16x bit expansion fits device memory.
        per_item = k * s * 16
        block = max(1, self.max_block_bytes // max(per_item, 1))
        outs = []
        for lo in range(0, b, block):
            chunk = jnp.asarray(shards[lo:lo + block])
            outs.append(np.asarray(fn(m2, chunk)))
        return np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
