"""JAX/TPU erasure backend: GF(2^8) as batched bit-plane matmuls.

The idea (TPU-first, not a translation of the reference's byte-table SIMD):
GF(2^8) is an 8-dim vector space over GF(2), and multiplying by a constant is
GF(2)-linear.  Expanding the (d+p) x d byte matrix into an 8x-larger binary
matrix turns the whole Reed-Solomon transform into

    out_bits[B, r*8, S] = M2[r*8, k*8] @ bits[B, k*8, S]   (mod 2)

— a plain matmul with 0/1 operands, which is exactly what the MXU is for.
Products are 0/1 and the contraction length is k*8 <= 2048, so bf16 inputs
with f32 accumulation are exact; the mod-2 and the byte pack/unpack are cheap
VPU element-wise ops that XLA fuses around the matmul.

The same primitive serves encode (parity rows) and decode (host-inverted
rows), replacing the reference's CPU hot loops at
src/file/file_part.rs:161-165 (encode_sep) and :128,302 (reconstruct).

Multi-chip: parts are independent, so scaling is a shard_map over the batch
axis with the bit-matrix replicated (see chunky_bits_tpu/parallel once the
mesh layer lands); the only collective is the gather of parity shards back to
the host I/O engine.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from chunky_bits_tpu.ops import gf256
from chunky_bits_tpu.ops.backend import ErasureBackend

# Deferred jax import: the CLI must not pay jax start-up unless this backend
# is actually selected.
_jax = None
_jnp = None
_IMPORT_LOCK = threading.Lock()


def _ensure_jax():
    global _jax, _jnp
    if _jax is None:
        with _IMPORT_LOCK:
            if _jax is None:
                import jax
                import jax.numpy as jnp

                _jax, _jnp = jax, jnp
    return _jax, _jnp


#: env knob for the bounded device-init wait (seconds); 0 disables the
#: guard and waits indefinitely (the pre-round-5 behavior)
DEVICE_INIT_TIMEOUT_ENV = "CHUNKY_BITS_TPU_DEVICE_INIT_TIMEOUT"

#: test seam: replaced with a blocking callable to simulate a dead tunnel
#: without one (PJRT init can't be made to hang on the CPU platform)
_DEVICE_PROBE = None

_device_ready = False
_device_failed: Exception | None = None
_DEVICE_READY_LOCK = threading.Lock()


def await_device_init() -> None:
    """Bounded wait for PJRT device init.

    The tunneled dev chip's PJRT client blocks *indefinitely and
    uninterruptibly* when the tunnel endpoint is down (observed rounds
    3-5: multi-hour outages during which even ``jax.devices()`` never
    returns).  Production paths (``backend: jax`` in cluster.yaml) must
    degrade, not hang, so the first device touch runs in a watchdog
    thread with a deadline.  On timeout the worker thread stays parked
    inside PJRT (it cannot be cancelled) and :class:`DeviceInitTimeout`
    is raised — callers fall back to a CPU codec and never touch jax
    again in this process, so the leaked thread is inert.

    Scope: init-time outages.  A tunnel that dies after a successful
    init is caught separately, per dispatch, by
    :func:`run_bounded_dispatch` (the backends then degrade to the CPU
    codec mid-run).

    Outcomes are sticky for the process lifetime: a success skips all
    later checks, and a timeout fails every later call fast (a stalled
    PJRT client never recovers in-process, and without the sticky
    failure N concurrent ``get_backend("jax")`` callers would each
    serially re-pay the full wait behind the lock).
    ``$CHUNKY_BITS_TPU_DEVICE_INIT_TIMEOUT`` overrides the 120 s
    default; ``0`` waits indefinitely.  A malformed value raises plain
    :class:`ErasureError` — a config typo must fail the resolution
    loudly, not read as a device outage and silently degrade."""
    global _device_ready, _device_failed
    if _device_ready:
        return
    from chunky_bits_tpu.cluster.tunables import env_seconds
    from chunky_bits_tpu.errors import DeviceInitTimeout, ErasureError

    probe = _DEVICE_PROBE or (lambda: _ensure_jax()[0].devices())
    try:
        timeout = env_seconds(DEVICE_INIT_TIMEOUT_ENV, default=120.0)
    except ValueError as err:
        raise ErasureError(str(err)) from None
    with _DEVICE_READY_LOCK:
        if _device_ready:
            return
        if _device_failed is not None:
            raise _device_failed
        if timeout <= 0:
            probe()
            _device_ready = True
            return
        # A plain daemon thread, NOT a ThreadPoolExecutor: futures'
        # atexit hook joins its (non-daemon) workers, so a parked PJRT
        # probe would hang interpreter exit — the degraded process
        # must still be able to finish and quit.
        done = threading.Event()
        box: dict[str, BaseException] = {}

        def _run() -> None:
            try:
                probe()
            # lint: broad-except-ok relayed to the waiting caller via
            # box and re-raised there
            except BaseException as err:
                box["err"] = err
            finally:
                done.set()

        threading.Thread(target=_run, name="cb-devinit",
                         daemon=True).start()
        if not done.wait(timeout):
            _device_failed = DeviceInitTimeout(
                f"jax device init did not answer within {timeout:.0f}s "
                f"(device tunnel down?); raise or disable the bound via "
                f"${DEVICE_INIT_TIMEOUT_ENV}")
            raise _device_failed from None
        if "err" in box:
            raise box["err"]
        _device_ready = True


#: bounded wait for an in-flight device dispatch (seconds); 0 disables.
#: Generous by default: a legitimate multi-GiB dispatch over the ~50
#: MiB/s dev tunnel takes minutes, and a false positive costs a silent
#: CPU recompute of the rest of the job.
DISPATCH_TIMEOUT_ENV = "CHUNKY_BITS_TPU_DISPATCH_TIMEOUT"
_DISPATCH_TIMEOUT_DEFAULT = 600.0


def run_bounded_dispatch(fn, what: str):
    """Run ``fn`` (a blocking device dispatch + materialization) in a
    daemon thread with a deadline; raise :class:`DeviceDispatchTimeout`
    if the device never answers.  Same leaked-parked-thread contract as
    ``await_device_init``: callers go CPU-only afterwards, so the stuck
    thread is inert.  With the env knob at 0 the call runs inline
    (zero overhead, pre-round-5 behavior)."""
    from chunky_bits_tpu.cluster.tunables import env_seconds
    from chunky_bits_tpu.errors import DeviceDispatchTimeout, ErasureError

    try:
        timeout = env_seconds(DISPATCH_TIMEOUT_ENV,
                              default=_DISPATCH_TIMEOUT_DEFAULT)
    except ValueError as err:
        raise ErasureError(str(err)) from None
    if timeout <= 0:
        return fn()
    done = threading.Event()
    box: dict[str, object] = {}

    def _run() -> None:
        try:
            box["out"] = fn()
        # lint: broad-except-ok relayed to the waiting caller via box
        # and re-raised there
        except BaseException as err:
            box["err"] = err
        finally:
            done.set()

    threading.Thread(target=_run, name="cb-dispatch",
                     daemon=True).start()
    if not done.wait(timeout):
        raise DeviceDispatchTimeout(
            f"{what} did not answer within {timeout:.0f}s (device "
            f"tunnel died mid-run?); adjust via ${DISPATCH_TIMEOUT_ENV}")
    if "err" in box:
        raise box["err"]
    return box["out"]


class _CallbackGate:
    """Wrap a block callback so it can be revoked: after ``close()``
    (taken before a timeout degrade) no further invocation reaches the
    wrapped callback, including one already racing on the parked
    dispatch thread — close() serializes behind any in-flight call."""

    def __init__(self, cb):
        self._cb = cb
        self._lock = threading.Lock()
        self._open = True

    def __call__(self, lo, arr) -> None:
        with self._lock:
            if self._open:
                self._cb(lo, arr)

    def close(self) -> None:
        with self._lock:
            self._open = False


_APPLY_FN = None


def _jitted_apply():
    """Build the jitted bit-plane transform once per process."""
    global _APPLY_FN
    if _APPLY_FN is not None:
        return _APPLY_FN
    jax, _ = _ensure_jax()
    from chunky_bits_tpu.ops.bitplane import apply_bitplane

    _APPLY_FN = jax.jit(apply_bitplane)
    return _APPLY_FN


class JaxBackend(ErasureBackend):
    """Erasure math on the default JAX device (TPU when present)."""

    name = "jax"

    #: batchers should merge concurrent requests into one dispatch —
    #: per-dispatch overhead dwarfs the host-side concatenate copy
    prefers_merged_batches = True

    #: cap device memory per dispatch: bits blow bytes up 8x as bf16 (16x B)
    max_block_bytes = 64 << 20

    #: decode matrices are one-per-erasure-pattern; bound the device cache so
    #: a long-running resilver over many patterns cannot grow memory forever.
    max_cached_matrices = 256

    def __init__(self) -> None:
        await_device_init()
        jax, _ = _ensure_jax()
        self._m2_cache: OrderedDict[bytes, object] = OrderedDict()
        self._fused_cache: OrderedDict[tuple, object] = OrderedDict()
        #: sticky off-switch for the device-SHA path after a failure
        #: (mirrors the _on_tpu pallas fallback: a failing path must not
        #: re-pay trace/compile/fail on every subsequent dispatch)
        self._device_sha_ok = True
        #: sticky mid-run device death (dispatch timeout): all further
        #: work recomputes on the CPU fallback
        self._device_dead = False
        self._fallback = None
        self._lock = threading.Lock()
        # 128-aligned shard sizes on a TPU take the fused Pallas kernel
        # (ops/pallas_kernels.py — a TPU-only Mosaic kernel); everything
        # else, including GPU backends, takes the einsum path.
        self._on_tpu = jax.default_backend() in ("tpu", "axon")

    def _bit_matrix(self, mat: np.ndarray):
        jax, jnp = _ensure_jax()
        key = mat.tobytes() + bytes(mat.shape[0:1])
        with self._lock:
            cached = self._m2_cache.get(key)
            if cached is not None:
                self._m2_cache.move_to_end(key)
                return cached
        m2 = gf256.expand_to_bit_matrix(mat).astype(np.float32)
        dev = jnp.asarray(m2, dtype=jnp.bfloat16)
        with self._lock:
            self._m2_cache[key] = dev
            while len(self._m2_cache) > self.max_cached_matrices:
                self._m2_cache.popitem(last=False)
        return dev

    def _cpu_fallback(self) -> "ErasureBackend":
        """The backend used once the device is marked dead mid-run."""
        if self._fallback is None:
            from chunky_bits_tpu.ops.backend import cpu_fallback_backend

            self._fallback = cpu_fallback_backend()
        return self._fallback

    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray,
                     on_block=None) -> np.ndarray:
        """Bounded device dispatch: a tunnel that dies AFTER init would
        otherwise park this call forever inside PJRT.  On a dispatch
        timeout the device is dead for the process — every later call
        recomputes on the native CPU codec, byte-identically."""
        from chunky_bits_tpu.errors import DeviceDispatchTimeout

        if self._device_dead:
            out = self._cpu_fallback().apply_matrix(mat, shards)
            if on_block is not None:
                on_block(0, out)
            return out
        gate = _CallbackGate(on_block) if on_block is not None else None
        try:
            return run_bounded_dispatch(
                lambda: self._apply_matrix_device(mat, shards, gate),
                "erasure dispatch")
        except DeviceDispatchTimeout as err:
            import warnings

            # Close the gate BEFORE degrading: the parked dispatch
            # thread still holds the callback, and a tunnel answering
            # late must not write the abandoned attempt's digests into
            # the caller's state after reconciliation.
            if gate is not None:
                gate.close()
            self._device_dead = True
            self._on_tpu = False  # forces encode_and_hash's full rehash
            warnings.warn(
                f"{err}; DEGRADED to the native CPU codec for the rest "
                f"of this process (output stays byte-identical)",
                RuntimeWarning)
            # on_block deliberately NOT fired here: callers reconcile
            # never-covered rows themselves (encode_and_hash rehashes
            # everything once _on_tpu drops)
            return self._cpu_fallback().apply_matrix(mat, shards)

    def _apply_matrix_device(self, mat: np.ndarray, shards: np.ndarray,
                             on_block=None) -> np.ndarray:
        jax, jnp = _ensure_jax()
        b, k, s = shards.shape
        r = mat.shape[0]
        if r == 0 or b == 0:
            return np.zeros((b, r, s), dtype=np.uint8)
        if self._on_tpu and s % 128 == 0 and s >= 1024:
            try:
                return self._apply_pallas_blocked(mat, shards, on_block)
            # lint: broad-except-ok warned + recomputed via the einsum
            # path below; no result from the failed kernel is kept
            except Exception as err:
                # An unexpected Mosaic/compile failure would otherwise be
                # re-attempted (and re-compiled, seconds each) on every
                # dispatch; disable the fast path once and say so.
                import warnings

                warnings.warn(
                    f"pallas erasure kernel disabled after failure: {err}")
                self._on_tpu = False
                # blocks already delivered through on_block keep their
                # (valid) results; suppress the callback for the einsum
                # retry so those rows aren't re-fired concurrently —
                # encode_and_hash reconciles never-seen rows afterwards
                on_block = None
        m2 = self._bit_matrix(mat)
        fn = _jitted_apply()
        # Block the batch axis so the 16x bit expansion fits device memory
        # (halved: the double-buffered pipeline keeps 2 blocks in flight).
        per_item = k * s * 16
        block = max(1, self.max_block_bytes // 2 // max(per_item, 1))
        return self._pipelined_blocks(lambda dev: fn(m2, dev),
                                      shards, block, on_block)

    def _pipelined_blocks(self, dispatch, shards: np.ndarray,
                          block: int, on_block=None):
        """Run ``dispatch`` over batch blocks with H2D/compute overlap:
        jax dispatch is asynchronous, so issuing block N+1's device_put
        and kernel before materializing block N's result lets the next
        host->device transfer (and compute) proceed while the host blocks
        on the previous device->host copy.  Two blocks in flight — the
        classic double buffer.  ``on_block(lo, arr)`` fires as each
        output block materializes, so callers can overlap host
        post-processing (shard hashing) with the remaining device work —
        NOTE it fires on whatever thread runs the dispatch (the
        cb-dispatch watchdog thread when the dispatch bound is active,
        the caller's thread when $CHUNKY_BITS_TPU_DISPATCH_TIMEOUT=0).
        ``dispatch`` may return one array or a tuple of arrays (the
        fused encode+hash path); tuple outputs are concatenated per
        element, and ``on_block`` must be None for them."""
        jax, _ = _ensure_jax()

        def materialize(o):
            if isinstance(o, tuple):
                assert on_block is None
                return tuple(np.asarray(a) for a in o)
            return np.asarray(o)

        b = shards.shape[0]
        if b <= block:
            out = materialize(dispatch(jax.device_put(shards)))
            if on_block is not None:
                on_block(0, out)
            return out
        outs = []
        pending = []
        for lo in range(0, b, block):
            dev = jax.device_put(np.ascontiguousarray(shards[lo:lo + block]))
            pending.append(dispatch(dev))
            if len(pending) > 1:
                arr = materialize(pending.pop(0))
                if on_block is not None:
                    on_block(len(outs) * block, arr)
                outs.append(arr)
        for o in pending:
            arr = materialize(o)
            if on_block is not None:
                on_block(len(outs) * block, arr)
            outs.append(arr)
        if isinstance(outs[0], tuple):
            return tuple(np.concatenate([o[i] for o in outs], axis=0)
                         for i in range(len(outs[0])))
        return np.concatenate(outs, axis=0)

    #: the fused kernel keeps bits in VMEM, so its device footprint is just
    #: data + parity; a much larger per-dispatch budget applies.
    max_pallas_block_bytes = 2 << 30

    def _apply_pallas_blocked(self, mat: np.ndarray, shards,
                              on_block=None) -> np.ndarray:
        from chunky_bits_tpu.ops.pallas_kernels import apply_matrix_pallas

        b, k, s = shards.shape
        per_item = k * s * 2
        block = max(1, self.max_pallas_block_bytes // 2 // max(per_item, 1))
        return self._pipelined_blocks(
            lambda dev: apply_matrix_pallas(mat, dev), shards, block,
            on_block)

    @staticmethod
    def _device_sha_enabled() -> bool:
        """Opt-in for hashing shards on the device inside the encode
        dispatch ($CHUNKY_BITS_TPU_DEVICE_SHA=1) — default off until an
        on-chip A/B (exp_devsha.py) shows it beating host SHA x cores.
        Read at dispatch time, but jit caches bake the routing into
        compiled executables, so set it before the first encode (same
        caveat as the packed-kernel flag, PARITY.md).  Exactly ``"1"``
        enables — deliberately stricter than env_flag's truthiness,
        matching the documented opt-in spelling for a path still
        pending its on-chip A/B."""
        from chunky_bits_tpu.cluster.tunables import env_str

        return env_str("CHUNKY_BITS_TPU_DEVICE_SHA") == "1"

    def _fused_encode_hash_fn(self, mat: np.ndarray, s: int,
                              interpret: bool = False):
        """Jitted ``u8[B, k, S] -> (parity u8[B, r, S],
        digests u8[B, k+r, 32])`` — parity and ALL shard digests in one
        device dispatch: bytes cross host->device once and only parity
        + 32 B/row digests come back.  SHA runs on the VPU, the GF
        matmul on the MXU; XLA overlaps them freely.  ``interpret``
        runs the pallas kernel in interpret mode (CPU tests).  Cached
        per (matrix, S, interpret) so repeat ingests reuse the compiled
        executable instead of re-tracing every dispatch."""
        key = (mat.tobytes(), mat.shape, s, interpret)
        with self._lock:
            cached = self._fused_cache.get(key)
        if cached is not None:
            return cached
        jax, jnp = _ensure_jax()
        from chunky_bits_tpu.ops.pallas_kernels import apply_matrix_pallas
        from chunky_bits_tpu.ops.sha256_jax import make_sha256_aligned

        sha = make_sha256_aligned(s)
        r = mat.shape[0]

        def fused(dev):
            b, k, _ = dev.shape
            parity = apply_matrix_pallas(mat, dev, interpret=interpret)
            # lint: jit-hygiene-ok rows are s bytes with s % 128 == 0
            # (the pallas-path gate), so the concat is lane-aligned
            digests = sha(jnp.concatenate(
                [dev, parity], axis=1).reshape(b * (k + r), s))
            return parity, digests.reshape(b, k + r, 32)

        fn = jax.jit(fused)
        with self._lock:
            self._fused_cache[key] = fn
            while len(self._fused_cache) > self.max_cached_matrices:
                self._fused_cache.popitem(last=False)
        return fn

    def _encode_and_hash_device(
        self, mat: np.ndarray, shards: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """The all-on-device ingest: the shared double-buffered block
        walk, each dispatch returning (parity, digests)."""
        b, k, s = shards.shape
        r = mat.shape[0]
        fn = self._fused_encode_hash_fn(mat, s)
        # resident per item: data + parity + the concatenated copy the
        # SHA hashes over = 2*(k+r)*s bytes (vs k*s*2 on the plain
        # parity path)
        per_item = 2 * (k + r) * s
        block = max(1, self.max_pallas_block_bytes // 2 // per_item)
        return self._pipelined_blocks(fn, shards, block)

    def encode_and_hash(
        self, mat: np.ndarray, shards: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Overlapped ingest: the device computes parity while the host
        hashes the data rows, and each parity block is hashed as it lands
        while later blocks are still in flight.  The generic fallback
        (ops/backend.py) runs encode-then-hash strictly serially, leaving
        the host idle during device compute — the reference's CPU path is
        serial too (src/file/file_part.rs:161,185).  Output is identical
        to the fused native engine's, bit for bit.

        With $CHUNKY_BITS_TPU_DEVICE_SHA=1 (and a 64-aligned shard size
        on the pallas path) the digests are computed ON the device in
        the same dispatch as the parity — the host's per-core SHA bound
        drops out of the pipeline entirely."""
        from chunky_bits_tpu.ops.backend import row_hasher

        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        b, k, s = shards.shape
        r = mat.shape[0]
        hash_rows = row_hasher()
        data_digests = np.empty((b, k, 32), dtype=np.uint8)
        parity_digests = np.empty((b, r, 32), dtype=np.uint8)
        if b == 0 or s == 0 or r == 0:
            parity = np.zeros((b, r, s), dtype=np.uint8)
            hash_rows(shards, data_digests)
            hash_rows(parity, parity_digests)
            return parity, np.concatenate(
                [data_digests, parity_digests], axis=1)
        if (self._device_sha_ok and self._device_sha_enabled()
                and self._on_tpu and not self._device_dead
                and s % 128 == 0 and s >= 1024):
            # same eligibility gate as the pallas parity path, so the
            # fused dispatch never mixes kernels mid-batch
            from chunky_bits_tpu.errors import DeviceDispatchTimeout

            try:
                return run_bounded_dispatch(
                    lambda: self._encode_and_hash_device(mat, shards),
                    "fused encode+hash dispatch")
            except DeviceDispatchTimeout as err:
                import warnings

                # the device is gone, not just this path: skip straight
                # to CPU instead of re-paying the timeout on the plain
                # parity dispatch below
                self._device_sha_ok = False
                self._device_dead = True
                self._on_tpu = False
                warnings.warn(
                    f"{err}; DEGRADED to the native CPU codec for the "
                    f"rest of this process", RuntimeWarning)
            # lint: broad-except-ok warned + fully recomputed below:
            # parity re-dispatches and every digest is re-hashed on the
            # host, so the failed fused attempt contributes nothing
            except Exception as err:
                import warnings

                self._device_sha_ok = False
                warnings.warn(
                    f"device SHA path disabled after failure: {err}")
        # host hashing overlaps the in-flight device dispatch on the
        # shared host pipeline's daemon workers (sliced across them),
        # the same overlap the retired 2-worker ThreadPoolExecutor
        # provided — CB103-clean and observable in the stage counters
        from chunky_bits_tpu.parallel.host_pipeline import (
            get_host_pipeline,
            join_jobs,
        )

        pipe = get_host_pipeline()
        jobs = list(pipe.hash_rows_jobs(shards, data_digests))
        covered = np.zeros(b, dtype=bool)

        def on_block(lo, arr):
            # axis-0 slices of the C-contiguous digest array are
            # contiguous, so the hasher can write in place
            covered[lo:lo + arr.shape[0]] = True
            jobs.extend(pipe.hash_rows_jobs(
                arr, parity_digests[lo:lo + arr.shape[0]]))

        was_on_tpu = self._on_tpu
        parity = self.apply_matrix(mat, shards, on_block=on_block)
        join_jobs(jobs)
        if was_on_tpu and not self._on_tpu:
            # A mid-run pallas failure fell back to einsum: the RETURNED
            # parity is the einsum recomputation, but digests hashed from
            # blocks the failed pallas attempt delivered would describe
            # that attempt's bytes.  The fallback fires exactly when the
            # kernel is misbehaving, so none of its output is trusted —
            # rehash every parity row from the parity actually returned.
            covered[:] = False
        if not covered.all():
            # also: the fallback suppresses the callback for its einsum
            # retry, so rows delivered by no callback are hashed here
            idx = np.flatnonzero(~covered)
            rest = np.empty((len(idx), r, 32), dtype=np.uint8)
            hash_rows(np.ascontiguousarray(parity[idx]), rest)
            parity_digests[idx] = rest
        return parity, np.concatenate([data_digests, parity_digests],
                                      axis=1)
