"""Fused Pallas TPU kernel for the GF(2^8) bit-plane transform.

The jnp einsum path (ops/jax_backend.py) materializes the bit expansion in
HBM: for every data byte it writes + reads 16 bytes of bf16 bit-planes, so
encode is HBM-bound at ~16x amplification.  This kernel fuses
unpack -> MXU matmul -> mod-2 -> pack inside VMEM, so HBM traffic drops to
read(data) + write(parity) — the roofline for this op.

Layout trick: bit-rows are ordered bit-major (row ``k*K + j`` = bit k of
shard j) so the unpack is 8 static sublane-slice stores and the pack is 8
static sublane-slice reads — no in-register transpose.  The host-side
matrix builder permutes the GF bit-matrix into this order.

Kernel math (per grid cell, shapes static):
    bits[K8, TS]  = unpack(data[K, TS])          (VPU shifts/ands)
    acc [R8, TS]  = m2[R8, K8] @ bits            (MXU, int8 -> int32 exact)
    out [R, TS]   = pack(acc & 1)                (VPU shifts/ors)

The matmul runs on the int8 MXU path (v5e executes int8 at 2x the bf16
rate, and the int8 bit-planes halve VMEM traffic vs bf16).  Hoist-proof
marginal measurement (bench.py method) on one v5e chip at d=10 p=4,
1 MiB chunks, batch 128: ~55-60 GiB/s sustained (two parts per grid
cell; tile/bblock swept on-chip), ~10% above the bf16 variant.  Variants
tried and rejected as slower on-chip: packed-word unpack via sublane
bitcast (~53), Kronecker-segmented matmul filling the MXU M dimension
(~53); int4 operands are unsupported by the runtime.  Round-4 re-sweep
(tile 8/16/32 KiB x bblock 1/2/4): flat plateau 51.5-54.6 with the
current (32 KiB, 2) at the top — no headroom left in these knobs; the
M=R8 dimension (32 rows at p=4) structurally caps MXU row utilization,
and block-diagonal multi-part stacking trades utilization for zero
FLOPs one-for-one, so it was not pursued.

Why ~13% MFU is the ceiling for this geometry, not a kernel defect:
the stationary weight tile is [K8, R8] = [80, 32] of the 128x128 MXU
array — 15.6% cell occupancy — and the measured 54 GiB/s is ~13.5% of
the int8 bound, i.e. the kernel runs the array at essentially full
streaming rate for the cells the math can occupy.  Transposing the
operands just moves the 32 to the other MXU dimension; padding K8/R8
to 128 adds zero-FLOP cells one-for-one with occupancy.  Only a wider
geometry fills it (d=16 -> K8=128; p=16 -> R8=128): at d=10,p=4 the
HBM roofline (~585 GiB/s data-rate at 14/10 traffic amplification) is
not the binding constraint, the weight aspect ratio is.
Accumulation is exact — each dot sums at most K8 ones, far below 2^31.
"""

from __future__ import annotations

import functools

import numpy as np

from chunky_bits_tpu.ops import gf256

# import jax lazily via function call to keep CLI startup light
_jax = None


def _jx():
    global _jax
    if _jax is None:
        import jax

        _jax = jax
    return _jax


def bit_matrix_bitmajor(mat: np.ndarray) -> np.ndarray:
    """Expand GF matrix [R, K] to GF(2) matrix [R*8, K*8] with bit-major
    row/col ordering: row ``b*R + i`` is bit b of output byte-row i, col
    ``b*K + j`` is bit b of input byte-row j."""
    r, k = mat.shape
    std = gf256.expand_to_bit_matrix(mat)  # row i*8+b, col j*8+b
    # new[b*r + i] = std[i*8 + b]; new[:, b*k + j] = std[:, j*8 + b]
    row_src = np.array([i * 8 + b for b in range(8) for i in range(r)])
    col_src = np.array([j * 8 + b for b in range(8) for j in range(k)])
    return std[row_src][:, col_src]


@functools.lru_cache(maxsize=256)
def _host_matrix(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    """Bit-major host matrix, cached per GF matrix so hot encode loops
    don't rebuild the expansion.  Only the (tiny, ~KBs) host->device copy
    happens per eager call — caching the *device* array here would leak
    tracers whenever the first call happens under a jit trace."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return bit_matrix_bitmajor(mat).astype(np.int8)


@functools.lru_cache(maxsize=64)
def _build_packed_kernel(r: int, k: int, tile_s: int, bblock: int,
                         interpret: bool):
    """Field-multiplexed variant of the fused kernel: two data columns
    share one int8 MXU element.

    Column ``t`` of the tile's left half and column ``t + TS/2`` of its
    right half pack into one bit-plane element at bit offsets 0 and 6,
    and the contraction is split in half (block-diagonal weight
    ``[2*R8, K8]``), so each field's popcount stays <= ceil(K8/2) <= 63
    and the two fields never collide inside the int32 accumulator
    (``acc = P_lo + 64*P_hi`` exactly).  The dot then streams TS/2
    columns instead of TS through the MXU — at encode geometry
    (R8=32) the array spends half the column-passes of the standard
    kernel for the same math, and the bit-plane scratch halves too.
    Field extraction is exact: ``acc >> 6 == P_hi`` because
    ``P_lo < 64``, and ``(x + y) & 1 == (x ^ y) & 1`` recombines the
    two contraction halves' parities without a carry chain.

    Only valid when ``2*R8 <= 128`` (the doubled output keeps to one
    MXU weight tile — true for parity encode, p <= 8) and
    ``K8 <= 126`` (field popcounts fit 6 bits — d <= 15); callers gate
    and fall back to the standard kernel otherwise.
    """
    jax = _jx()
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r8, k8 = r * 8, k * 8
    kc = k8 // 2
    h = tile_s // 2

    def kernel(m2p_ref, data_ref, out_ref, bits_ref):
        for bi in range(bblock):
            data = data_ref[bi].astype(jnp.int32)  # [K, TS]
            lo = data[:, :h]
            hi = data[:, h:]
            for b in range(8):
                bits_ref[b * k:(b + 1) * k, :] = (
                    ((lo >> b) & 1) | (((hi >> b) & 1) << 6)
                ).astype(jnp.int8)
            acc = jax.lax.dot_general(
                m2p_ref[...], bits_ref[...],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [2*R8, h]
            a0 = acc[0:r8, :]
            a1 = acc[r8:2 * r8, :]
            lo_bits = (a0 ^ a1) & 1
            hi_bits = ((a0 >> 6) ^ (a1 >> 6)) & 1
            plo = lo_bits[0:r, :]
            phi = hi_bits[0:r, :]
            for b in range(1, 8):
                plo = plo | (lo_bits[b * r:(b + 1) * r, :] << b)
                phi = phi | (hi_bits[b * r:(b + 1) * r, :] << b)
            out_ref[bi, :, 0:h] = plo.astype(jnp.uint8)
            out_ref[bi, :, h:tile_s] = phi.astype(jnp.uint8)

    def call(m2, data):
        batch, _k, s = data.shape
        # block-diagonal split of the contraction: rows 0..R8 see the
        # first kc bit-columns, rows R8..2*R8 the rest
        col = jnp.arange(k8, dtype=jnp.int32)[None, :]
        m2p = jnp.concatenate(
            [jnp.where(col < kc, m2, 0), jnp.where(col >= kc, m2, 0)],
            axis=0)  # [2*R8, K8] int8
        grid = (batch // bblock, s // tile_s)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((2 * r8, k8), lambda b, j: (0, 0)),
                pl.BlockSpec((bblock, k, tile_s), lambda b, j: (b, 0, j)),
            ],
            out_specs=pl.BlockSpec((bblock, r, tile_s),
                                   lambda b, j: (b, 0, j)),
            out_shape=jax.ShapeDtypeStruct((batch, r, s), jnp.uint8),
            scratch_shapes=[pltpu.VMEM((k8, h), jnp.int8)],
            interpret=interpret,
        )(m2p, data)

    return jax.jit(call)


@functools.lru_cache(maxsize=64)
def _build_kernel(r: int, k: int, tile_s: int, bblock: int, interpret: bool,
                  pack: bool = True):
    """``pack=True`` emits packed parity bytes [B, R, S] (the fused
    single-chip transform).  ``pack=False`` stops before the mod-2/pack
    and emits the raw popcount accumulator [B, R8, S] — the per-chip
    half of the contraction-sharded (tp) mesh path: partial popcounts
    from different chips *add* (GF(2^8) addition is XOR), so the mesh
    layer can ``psum`` them over ICI and apply one mod-2/pack after the
    collective (parallel/mesh.py).  The accumulator is int16: the MXU
    still accumulates in exact int32, but the global popcount is at most
    K8 <= 2048 ones, so narrowing before the HBM store halves both the
    accumulator's HBM traffic and the ICI bytes the tp psum moves."""
    jax = _jx()
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r8, k8 = r * 8, k * 8

    def kernel(m2_ref, data_ref, out_ref, bits_ref):
        # ``bblock`` parts per grid cell, reusing one bits scratch:
        # amortizes per-cell overhead (measured +5% at bblock=2 vs 1).
        for bi in range(bblock):
            data = data_ref[bi].astype(jnp.int32)  # [K, TS]
            for b in range(8):
                bits_ref[b * k:(b + 1) * k, :] = (
                    (data >> b) & 1
                ).astype(jnp.int8)
            acc = jax.lax.dot_general(
                m2_ref[...], bits_ref[...],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [R8, TS]
            if not pack:
                out_ref[bi] = acc.astype(jnp.int16)
                continue
            acc = acc & 1
            packed = acc[0:r, :]
            for b in range(1, 8):
                packed = packed | (acc[b * r:(b + 1) * r, :] << b)
            out_ref[bi] = packed.astype(jnp.uint8)

    out_rows, out_dtype = (r, jnp.uint8) if pack else (r8, jnp.int16)

    def call(m2, data):
        batch, _k, s = data.shape
        grid = (batch // bblock, s // tile_s)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((r8, k8), lambda b, j: (0, 0)),
                pl.BlockSpec((bblock, k, tile_s), lambda b, j: (b, 0, j)),
            ],
            out_specs=pl.BlockSpec((bblock, out_rows, tile_s),
                                   lambda b, j: (b, 0, j)),
            out_shape=jax.ShapeDtypeStruct((batch, out_rows, s), out_dtype),
            scratch_shapes=[pltpu.VMEM((k8, tile_s), jnp.int8)],
            interpret=interpret,
        )(m2, data)

    return jax.jit(call)


def _pick_tile(s: int, k: int, row_bytes: int = 0) -> int:
    """Largest power-of-two tile dividing s, capped so the int8 bit-plane
    scratch (k*8 rows x tile lanes) stays within ~4 MiB of VMEM (s must be
    a multiple of 128 for the fast path; 32 KiB tiles measured fastest at
    d=10).  ``row_bytes`` adds a per-lane VMEM cost for the acc kernel's
    dot intermediate (int32, regardless of stored dtype), capped at
    ~6 MiB."""
    tile = 32768
    while tile > 128 and tile * k * 8 > (4 << 20):
        tile //= 2
    while tile > 128 and row_bytes and tile * row_bytes > (6 << 20):
        tile //= 2
    while tile > 128 and s % tile != 0:
        tile //= 2
    return tile if s % tile == 0 else 0


#: default for the field-multiplexed kernel at gated geometries — flip
#: after the real-chip A/B (exp_packed.py) shows a win; until then the
#: opt-in is $CHUNKY_BITS_TPU_PACKED_KERNEL=1
_PACKED_DEFAULT = False


def _packed_enabled() -> bool:
    """Standard env-flag parsing (cluster/tunables.env_flag): unset
    falls back to the process default; "", "0", "false", "no", "off"
    mean off.  Read at first dispatch and baked into jit caches — set
    before the first encode (PARITY.md)."""
    from chunky_bits_tpu.cluster.tunables import env_flag

    return env_flag("CHUNKY_BITS_TPU_PACKED_KERNEL",
                    default=_PACKED_DEFAULT)


def apply_m2_bitmajor(m2, shards, *, interpret: bool = False,
                      packed: bool | None = None):
    """Fused transform over an already-built bit-major int8 device matrix.

    The traceable core of ``apply_matrix_pallas``: usable inside
    ``shard_map`` local functions (parallel/mesh.py), where the matrix
    arrives as a device argument and shapes are static at trace time.
    ``m2`` is int8 [R*8, K*8] from ``bit_matrix_bitmajor``; ``shards`` is
    uint8 [B, K, S].  Raises ValueError when shapes don't fit the fast
    path.  ``packed`` selects the field-multiplexed kernel (None = the
    process default when the geometry is gated; selection is static at
    trace time).
    """
    r8, k8 = m2.shape
    r, k = r8 // 8, k8 // 8
    b, k2, s = shards.shape
    assert k2 == k
    if packed is None:
        packed = _packed_enabled() and packed_geometry_ok(r, k, s)
    if packed:
        return apply_m2_bitmajor_packed(m2, shards, interpret=interpret)
    tile = _pick_tile(s, k)
    if tile == 0 or r == 0:
        raise ValueError(f"shard size {s} not tileable for pallas path")
    bblock = 2 if b % 2 == 0 else 1
    fn = _build_kernel(r, k, tile, bblock, interpret)
    return fn(m2, shards)


def packed_geometry_ok(r: int, k: int, s: int) -> bool:
    """Gate for the field-multiplexed kernel: doubled output rows must
    keep to one MXU weight tile (2*R8 <= 128, i.e. r <= 8) and per-field
    popcounts must fit 6 bits (ceil(K8/2) <= 63, i.e. k <= 15); the
    column split needs lane-aligned tile halves (s a multiple of 256)."""
    return 0 < r <= 8 and 0 < k <= 15 and s % 256 == 0


def apply_m2_bitmajor_packed(m2, shards, *, interpret: bool = False):
    """Field-multiplexed fused transform (see ``_build_packed_kernel``):
    same contract as ``apply_m2_bitmajor``, restricted to geometries
    where ``packed_geometry_ok`` holds.  Raises ValueError otherwise."""
    r8, k8 = m2.shape
    r, k = r8 // 8, k8 // 8
    b, k2, s = shards.shape
    assert k2 == k
    if not packed_geometry_ok(r, k, s):
        raise ValueError(
            f"geometry r={r} k={k} s={s} outside the packed kernel's gate")
    # _pick_tile's VMEM budget is conservative here (the packed scratch
    # is [K8, tile/2], half the standard kernel's); tile halves stay
    # lane-aligned because the gate requires s % 256 == 0
    tile = _pick_tile(s, k)
    if tile < 256:
        raise ValueError(f"shard size {s} not tileable for packed path")
    bblock = 2 if b % 2 == 0 else 1
    fn = _build_packed_kernel(r, k, tile, bblock, interpret)
    return fn(m2, shards)


def acc_m2_bitmajor(m2, shards, *, interpret: bool = False):
    """Partial bit-plane accumulation (pre mod-2), bit-major rows:
    int16 [B, R*8, S] (exact — the global popcount is <= K8 <= 2048).
    Per-chip half of the tp-sharded mesh encode."""
    r8, k8 = m2.shape
    r, k = r8 // 8, k8 // 8
    b, k2, s = shards.shape
    assert k2 == k
    bblock = 2 if b % 2 == 0 else 1
    # budget at int32 cost: the dot intermediate is int32 in VMEM even
    # though the stored accumulator is int16
    tile = _pick_tile(s, k, row_bytes=r8 * 4 * bblock)
    if tile == 0 or r == 0:
        raise ValueError(f"shard size {s} not tileable for pallas path")
    fn = _build_kernel(r, k, tile, bblock, interpret, pack=False)
    return fn(m2, shards)


def pack_acc_bitmajor(acc):
    """Pack bit-major popcounts [B, R*8, S] (any integer dtype) into
    bytes [B, R, S]: row ``b*R + i`` is bit b of output byte-row i (the
    layout ``bit_matrix_bitmajor`` produces), so the mod-2 bits of plane
    b land at bit position b of byte i."""
    import jax.numpy as jnp

    b, r8, s = acc.shape
    r = r8 // 8
    bits = (acc & 1).astype(jnp.int32).reshape(b, 8, r, s)
    shifts = jnp.arange(8, dtype=jnp.int32)
    return jnp.sum(bits << shifts[None, :, None, None],
                   axis=1).astype(jnp.uint8)


def bitmajor_device_matrix(mat: np.ndarray):
    """The int8 bit-major device matrix for a GF matrix [R, K] (host
    expansion cached; the tiny host->device copy happens per call)."""
    import jax.numpy as jnp

    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    return jnp.asarray(_host_matrix(mat.tobytes(), *mat.shape),
                       dtype=jnp.int8)


def apply_matrix_pallas(mat: np.ndarray, shards, *, interpret: bool = False):
    """Device-side bit-plane transform via the fused kernel.

    ``mat`` is the GF(2^8) matrix [R, K]; ``shards`` is a jax or numpy
    uint8 array [B, K, S] with S a multiple of 128.  Returns a jax uint8
    array [B, R, S].  Raises ValueError when shapes don't fit the fast
    path (caller falls back to the einsum path).
    """
    import jax.numpy as jnp

    return apply_m2_bitmajor(bitmajor_device_matrix(mat),
                             jnp.asarray(shards), interpret=interpret)
