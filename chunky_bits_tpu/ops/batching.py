"""Coalesce concurrent Reed-Solomon reconstructions into batched dispatches.

The reference rebuilds one part at a time on the blocking pool
(src/file/file_part.rs:128,302-305).  That shape wastes a TPU: resilver
keeps 10 parts in flight (src/file/file_reference.rs:110), a degraded read
prefetches 5 (src/file/reader.rs:96), and the parts of one file almost
always share an erasure pattern — the node that lost shard *i* of one part
lost shard *i* of every part.  The batcher collects whatever reconstruction
requests are in flight at the same moment, groups them by (geometry,
erasure pattern, shard length, data-only), and rebuilds each group in a
single ``[B, d+p, S]`` dispatch through ``ErasureCoder.reconstruct_batch``
— one device call (or one threaded native call) instead of B.

Requests that arrive while a dispatch is running accumulate and form the
next batch, so batching emerges from concurrency without added latency:
a lone request is dispatched immediately.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops.backend import get_coder


class ReconstructBatcher:
    """Shared per-pipeline reconstruction front-end.

    One instance is created per read stream / resilver run and passed down
    to the parts; it must be used from a single event loop.
    """

    def __init__(self, backend: Optional[str] = None, max_batch: int = 128):
        self.backend = backend
        self.max_batch = max_batch
        self._pending: list[tuple[tuple, list, asyncio.Future]] = []
        self._task: Optional[asyncio.Task] = None
        self.dispatches = 0  # observability + tests

    async def reconstruct(
        self, d: int, p: int, arrays: Sequence[Optional[np.ndarray]],
        data_only: bool = False,
    ) -> list[Optional[np.ndarray]]:
        """Async equivalent of ``ErasureCoder.reconstruct`` /
        ``reconstruct_data`` (crate call sites file_part.rs:128,302-305):
        fill the ``None`` rows of ``arrays`` (all d+p slots, data first).
        """
        total = d + p
        if len(arrays) != total:
            raise ErasureError(
                f"expected {total} shard slots, got {len(arrays)}")
        arrays = list(arrays)
        present = tuple(i for i, a in enumerate(arrays) if a is not None)
        if len(present) == total:
            return arrays
        if len(present) < d:
            raise ErasureError(
                f"too few shards present: {len(present)} < {d}")
        limit = d if data_only else total
        wanted = tuple(i for i in range(limit) if arrays[i] is None)
        if not wanted:
            return arrays
        size = len(arrays[present[0]])
        key = (d, p, present, wanted, size)
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((key, arrays, fut))
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._drain())
        return await fut

    async def _drain(self) -> None:
        # Yield once so callers scheduled in the same tick can enqueue
        # before the first dispatch.
        await asyncio.sleep(0)
        while self._pending:
            pending, self._pending = self._pending, []
            groups: dict[tuple, list] = {}
            for item in pending:
                groups.setdefault(item[0], []).append(item)
            # Distinct erasure patterns are independent work: dispatch
            # every group concurrently (a degraded read's random chunk
            # selection yields varying `present` sets — serializing the
            # groups would be slower than the unbatched path it replaces).
            jobs = []
            for key, items in groups.items():
                for i in range(0, len(items), self.max_batch):
                    jobs.append(
                        self._dispatch(key, items[i:i + self.max_batch]))
            await asyncio.gather(*jobs)

    async def _dispatch(self, key: tuple, group: list) -> None:
        try:
            results = await asyncio.to_thread(
                self._run_group, key, [g[1] for g in group])
        except BaseException as err:
            for _, _, fut in group:
                if not fut.done():
                    fut.set_exception(err)
            if isinstance(err, asyncio.CancelledError):
                raise
        else:
            for (_, _, fut), res in zip(group, results):
                if not fut.done():
                    fut.set_result(res)

    def _run_group(self, key: tuple, requests: list[list]) -> list[list]:
        d, p, present, wanted, size = key
        self.dispatches += 1
        coder = get_coder(d, p, self.backend)
        stacked = np.zeros((len(requests), d + p, size), dtype=np.uint8)
        for bi, arrays in enumerate(requests):
            for i in present:
                row = arrays[i]
                if len(row) != size:
                    raise ErasureError("shards must be of equal length")
                stacked[bi, i] = row
        rebuilt = coder.reconstruct_batch(stacked, list(present),
                                          list(wanted))
        out: list[list] = []
        for bi, arrays in enumerate(requests):
            filled = list(arrays)
            for wi, i in enumerate(wanted):
                filled[i] = rebuilt[bi, wi]
            out.append(filled)
        return out
