"""Coalesce concurrent erasure-codec calls into batched device dispatches.

The reference runs one codec call per part on the blocking pool — encode at
src/file/file_part.rs:161-165, reconstruct at :128,302-305.  That shape
wastes a TPU: dispatch overhead dominates small calls, while the kernel
itself is throughput-bound and loves batch.  Concurrency that already
exists in the pipelines (resilver keeps 10 parts in flight
src/file/file_reference.rs:110, reads prefetch 5 src/file/reader.rs:96,
the gateway serves many PUTs at once) is turned into batch here: whatever
requests are in flight at the same moment are grouped by compatible shape
and executed as one ``[B, ...]`` dispatch.

Requests that arrive while a dispatch is running accumulate and form the
next batch, so batching emerges from concurrency without added latency: a
lone request is dispatched immediately.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops.backend import get_coder


class _GroupItemError:
    """Per-item failure marker in a ``_run_group`` result list: lets a
    group deliver a mix of results and exceptions, so one bad batch in
    an UNMERGED group fails only its own waiter (a merged dispatch has
    no such boundary — every contributing waiter shares its fate)."""

    __slots__ = ("err",)

    def __init__(self, err: BaseException):
        self.err = err


class _CoalescingBatcher:
    """Group concurrent requests by key and dispatch each group once.

    Instances are per-pipeline (one read stream, one resilver run, one
    cluster ingest scope) and must be used from a single event loop.
    Subclasses implement ``_run_group(key, payloads) -> results`` (called
    in a worker thread).
    """

    #: ``_pending``/``_task``/``_inflight`` bookkeeping is lock-free
    #: because it never leaves the owning loop's thread; the CB204
    #: cross-plane rule reads this tag and flags calls into a batcher
    #: from HostPipeline-worker-reachable code that skip the
    #: call_soon_threadsafe/run_coroutine_threadsafe doors (subclasses
    #: inherit the tag by base-name resolution)
    LOOP_BOUND = True

    def __init__(self, backend: Optional[str] = None, max_batch: int = 128):
        self.backend = backend
        self.max_batch = max_batch
        self._pending: list[tuple[tuple, object, asyncio.Future]] = []
        self._task: Optional[asyncio.Task] = None
        self._inflight: set[asyncio.Task] = set()
        #: codec dispatches issued (merged batches count once; unmerged
        #: CPU batches count each)
        self.dispatches = 0
        #: coalesced groups executed (one per _run_group call) — the
        #: request-grouping factor independent of the merge policy
        self.groups = 0

    async def _submit(self, key: tuple, payload):
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((key, payload, fut))
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._drain())
        # lint: unbounded-await-ok resolved (result or exception) by
        # _dispatch in every outcome, and the device work underneath is
        # bounded by run_bounded_dispatch's deadline
        return await fut

    async def _drain(self) -> None:
        # Yield once so callers scheduled in the same tick can enqueue
        # before the first dispatch.
        await asyncio.sleep(0)
        pending, self._pending = self._pending, []
        groups: dict[tuple, list] = {}
        for item in pending:
            groups.setdefault(item[0], []).append(item)
        # Distinct keys are independent work, and nothing waits on anyone
        # else's group: each dispatch is fired as its own task (no barrier
        # — a slow group must not stall either the other groups' results
        # or the next round of arrivals, which simply start a new drain).
        for key, items in groups.items():
            for i in range(0, len(items), self.max_batch):
                task = asyncio.create_task(
                    self._dispatch(key, items[i:i + self.max_batch]))
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)

    async def _dispatch(self, key: tuple, group: list) -> None:
        self.groups += 1
        try:
            results = await asyncio.to_thread(
                self._run_group, key, [g[1] for g in group])
        # lint: broad-except-ok delivered to every waiter via
        # fut.set_exception; CancelledError additionally re-raised
        except BaseException as err:
            for _, _, fut in group:
                if not fut.done():
                    fut.set_exception(err)
            if isinstance(err, asyncio.CancelledError):
                raise
        else:
            for (_, _, fut), res in zip(group, results):
                if not fut.done():
                    if isinstance(res, _GroupItemError):
                        fut.set_exception(res.err)
                    else:
                        fut.set_result(res)

    async def aclose(self) -> None:
        """Drain: await the pending collection task and every in-flight
        dispatch, so owners tearing down (end of a read stream, resilver
        run, or event loop) never abandon waiter futures mid-flight.
        Dispatch errors are delivered to their waiters, not raised here."""
        while True:
            tasks = set(self._inflight)
            if self._task is not None and not self._task.done():
                tasks.add(self._task)
            if not tasks:
                return
            await asyncio.gather(*tasks, return_exceptions=True)

    def _run_group(self, key: tuple, payloads: list) -> list:
        raise NotImplementedError


class ReconstructBatcher(_CoalescingBatcher):
    """Batched decode front-end for the read and resilver paths.

    Groups by (geometry, erasure pattern, shard length): the parts of one
    file degraded by the same node loss share a pattern and rebuild in one
    ``[B, d+p, S]`` dispatch through ``ErasureCoder.reconstruct_batch``.
    """

    async def reconstruct(
        self, d: int, p: int, arrays: Sequence[Optional[np.ndarray]],
        data_only: bool = False, code: str = "rs",
    ) -> list[Optional[np.ndarray]]:
        """Async equivalent of ``ErasureCoder.reconstruct`` /
        ``reconstruct_data`` (crate call sites file_part.rs:128,302-305):
        fill the ``None`` rows of ``arrays`` (all d+p slots, data first).
        ``code`` is the part's wire-format erasure code — requests only
        coalesce within a code (the decode matrices differ).
        """
        total = d + p
        if len(arrays) != total:
            raise ErasureError(
                f"expected {total} shard slots, got {len(arrays)}")
        arrays = list(arrays)
        present = tuple(i for i, a in enumerate(arrays) if a is not None)
        if len(present) == total:
            return arrays
        if len(present) < d:
            raise ErasureError(
                f"too few shards present: {len(present)} < {d}")
        limit = d if data_only else total
        wanted = tuple(i for i in range(limit) if arrays[i] is None)
        if not wanted:
            return arrays
        size = len(arrays[present[0]])
        # Validate before coalescing: a malformed request must fail alone,
        # not poison the whole group it would have joined.
        for i in present[1:]:
            if len(arrays[i]) != size:
                raise ErasureError("shards must be of equal length")
        key = (d, p, present, wanted, size, code)
        return await self._submit(key, arrays)

    def _run_group(self, key: tuple, requests: list[list]) -> list[list]:
        d, p, present, wanted, size, code = key
        self.dispatches += 1
        coder = get_coder(d, p, self.backend, code)
        # stack straight into decode layout (the first d present rows,
        # ascending) — one gather pass instead of a full [B, d+p, S]
        # scatter followed by reconstruct_batch's row-pick copy
        use = sorted(present)[:d]
        picked = np.empty((len(requests), d, size), dtype=np.uint8)
        for bi, arrays in enumerate(requests):
            for j, i in enumerate(use):
                picked[bi, j] = arrays[i]
        rebuilt = coder.reconstruct_batch_picked(picked, list(present),
                                                 list(wanted))
        out: list[list] = []
        for bi, arrays in enumerate(requests):
            filled = list(arrays)
            for wi, i in enumerate(wanted):
                filled[i] = rebuilt[bi, wi]
            out.append(filled)
        return out


class EncodeHashBatcher(_CoalescingBatcher):
    """Batched encode+hash front-end for the ingest path.

    One large file already batches its own parts (writer.py staging); this
    batcher coalesces *across* concurrent writes — the many-small-objects
    regime (e.g. parallel HTTP-gateway PUTs), where each write has a
    single sub-batch part and per-dispatch overhead would dominate.
    Grouped by (d, p, shard length).

    Whether a group's batches are additionally merged into one
    ``[ΣB, d, S]`` dispatch follows the backend's
    ``prefers_merged_batches`` policy (see ``_run_group``): device
    backends earn the merge's extra concatenate copy back in saved
    per-dispatch RPC; CPU backends run the group's batches back-to-back
    unmerged.  Backends exposing a ``submit_apply`` staging surface (the
    ``mesh`` backend's double-buffered dispatch pipeline) supersede the
    merge entirely: the group routes through
    ``ErasureCoder.encode_hash_batches``, which stages every batch's
    dispatch ahead of collection — the same saved per-dispatch RPC
    without paying the concatenate memcpy.  The cluster wires a shared
    instance only for device backends — CPU writes already amortize
    per-part overhead through the writer's zero-copy staging.

    ``host_pipeline`` (a parallel.host_pipeline.HostPipeline) routes each
    dispatch's host compute through the shared multi-core executor —
    per-stripe fused encode+hash sliced across its workers — instead of
    one ``coder.encode_hash_batch`` call; None keeps the direct call
    (whose device-backend path already overlaps hashing on the shared
    pipeline internally).
    """

    def __init__(self, backend: Optional[str] = None, max_batch: int = 128,
                 host_pipeline: Optional[object] = None):
        super().__init__(backend, max_batch)
        self.host_pipeline = host_pipeline

    async def encode_hash(
        self, d: int, p: int, stacked: np.ndarray, code: str = "rs",
    ) -> tuple[np.ndarray, np.ndarray]:
        """Async equivalent of ``ErasureCoder.encode_hash_batch`` for one
        staged part batch ``stacked[B, d, S]``: returns
        ``(parity[B, p, S], digests[B, d+p, 32])``.  ``code`` selects
        the erasure code; batches only merge within a code."""
        if stacked.ndim != 3 or stacked.shape[1] != d:
            raise ErasureError(
                f"expected stacked [B, {d}, S], got {stacked.shape}")
        b, _, size = stacked.shape
        if b == 0:
            return (np.zeros((0, p, size), dtype=np.uint8),
                    np.zeros((0, d + p, 32), dtype=np.uint8))
        key = (d, p, size, code)
        return await self._submit(key, stacked)

    def _encode(self, coder, stacked: np.ndarray):
        """The per-dispatch codec call: ``(parity, digests)`` for one
        (possibly merged) ``[B, d, S]`` batch.  The merge policy, dispatch
        counting, and slice-back in ``_run_group`` are shared — variants
        (e.g. bench.py's hash-free pipeline probe) override only this."""
        if self.host_pipeline is not None:
            return self.host_pipeline.encode_hash_sync(coder, stacked)
        return coder.encode_hash_batch(stacked)

    def _run_group(self, key: tuple, batches: list[np.ndarray]) -> list:
        d, p, _size, code = key
        coder = get_coder(d, p, self.backend, code)
        # Merging pending batches into one [ΣB, d, S] dispatch costs a
        # full extra memcpy (the concatenate).  Device backends earn it
        # back many times over in saved per-dispatch RPC; the CPU
        # backends loop over parts either way, so for them the copy is
        # pure loss (measured: the merge halved config-2 throughput on a
        # 1-core host) — run their batches back-to-back unmerged.
        merge = getattr(coder.backend, "prefers_merged_batches", False)
        if merge and getattr(coder.backend, "submit_apply", None) is not None:
            # Feed-ahead: every batch's dispatch is staged into the
            # backend's bounded window before any is collected, so the
            # device chews batch k+1 while the host hashes batch k.
            # Same shared-fate contract as the merged dispatch below
            # (one failure fails the group), minus the concatenate
            # memcpy; host hashing overlaps inside
            # encode_hash_batches, so the host_pipeline slicing path
            # is deliberately bypassed here.
            self.dispatches += len(batches)
            return coder.encode_hash_batches(batches)
        if not merge or len(batches) == 1:
            # Unmerged batches are independent dispatches that happen to
            # share a drain tick: a failure belongs to its own waiter
            # only, and later batches in the group must still encode.
            out = []
            for b in batches:
                self.dispatches += 1
                try:
                    out.append(self._encode(coder, b))
                # lint: broad-except-ok re-raised at the owning waiter
                # through _GroupItemError; other batches must proceed
                except Exception as err:
                    out.append(_GroupItemError(err))
            return out
        self.dispatches += 1
        merged = np.concatenate(batches, axis=0)
        parity, digests = self._encode(coder, merged)
        out = []
        lo = 0
        for batch in batches:
            hi = lo + batch.shape[0]
            out.append((parity[lo:hi], digests[lo:hi]))
            lo = hi
        return out
