"""Compute plane: GF(2^8) math and the pluggable erasure backends."""

from chunky_bits_tpu.ops.backend import (  # noqa: F401
    ErasureBackend,
    ErasureCoder,
    get_backend,
    get_coder,
    register_backend,
)
