import sys

from chunky_bits_tpu.cli.main import main

sys.exit(main())
