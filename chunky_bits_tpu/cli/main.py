"""``chunky-bits`` CLI: coreutils-like commands for files and clusters.

Mirrors src/bin/chunky-bits/main.rs: global overrides ``--config``,
``--chunk-size``, ``--data-chunks``, ``--parity-chunks`` (:76-93) and the 14
subcommands (:96-177): cat, config-info, cluster-info, cp, decode-shards,
encode-shards, file-info, find-unused-hashes, get-hashes, http-gateway, ls,
migrate, resilver, verify — plus the TPU-repo extensions: scrub, stats,
and meta-compact (cluster/meta_log.py maintenance + the
``--from-path-store`` migration into the indexed metadata plane).

Cluster locations are formatted ``cluster-name#path/to/file``; a location
for the cluster definition may be used instead of a name
(``./cluster.yaml#path``); ``@#location`` addresses a file reference;
``-`` is stdio.
"""

from __future__ import annotations

import argparse
import asyncio
import errno
import json
import os
import sys


from chunky_bits_tpu.cli.cluster_location import ClusterLocation
from chunky_bits_tpu.cli.config import Config
from chunky_bits_tpu.errors import ChunkyBitsError, LocationError
from chunky_bits_tpu.file import AnyHash, Location
from chunky_bits_tpu.ops import get_coder
from chunky_bits_tpu.utils import aio
from chunky_bits_tpu.utils.yamlio import yaml_dump


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="chunky-bits",
        description="An interface for Chunky Bits files and clusters "
                    "(TPU-native implementation).",
    )
    parser.add_argument("--config", help="Location for the config file")
    parser.add_argument("--chunk-size", type=int,
                        help="Default chunk size (log2) for non-cluster "
                             "destinations")
    parser.add_argument("--data-chunks", type=int,
                        help="Default data chunks for non-cluster "
                             "destinations")
    parser.add_argument("--parity-chunks", type=int,
                        help="Default parity chunks for non-cluster "
                             "destinations")
    parser.add_argument("--backend",
                        help="Erasure backend (numpy, native, jax)")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("cat", help="Concatenate files together")
    p.add_argument("targets", nargs="+")

    p = sub.add_parser("config-info",
                       help="Show the parsed configuration definition")
    p.add_argument("--json", action="store_true")

    p = sub.add_parser("cluster-info",
                       help="Show the parsed cluster definition")
    p.add_argument("--json", action="store_true")
    p.add_argument("cluster")

    p = sub.add_parser("cp", help="Copy file from source to destination")
    p.add_argument("source")
    p.add_argument("destination")

    p = sub.add_parser("decode-shards",
                       help="Reassemble data from d-of-n shard files")
    p.add_argument("targets", nargs="+")

    p = sub.add_parser("encode-shards",
                       help="Split a source into d+p shard files")
    p.add_argument("source")
    p.add_argument("targets", nargs="+")

    p = sub.add_parser("file-info", help="Show a file reference")
    p.add_argument("--json", action="store_true")
    p.add_argument("source")

    p = sub.add_parser("find-unused-hashes",
                       help="Find all hashes that are not referenced")
    p.add_argument("--batch-size", type=int, default=100000)
    p.add_argument("-r", "--remove", action="store_true")
    p.add_argument("--grace-seconds", type=float, default=60.0,
                   help="skip chunk files younger than this (an in-flight"
                        " write stages chunks before publishing the"
                        " metadata that references them; 0 disables)")
    p.add_argument("source", nargs="+",
                   help="cluster/file-ref locations that define liveness")
    p.add_argument("hashes", nargs="*", default=[],
                   help="local hash directories to scan (after --)")

    p = sub.add_parser("get-hashes",
                       help="Get all the known hashes for a location")
    p.add_argument("-d", "--dedup", action="store_true",
                   dest="deduplicate")
    p.add_argument("-s", "--sort", action="store_true")
    p.add_argument("target")

    p = sub.add_parser("http-gateway",
                       help="Provide a HTTP Gateway for a cluster")
    p.add_argument("cluster")
    p.add_argument("-l", "--listen-addr", default="127.0.0.1:8000")
    p.add_argument("--max-put-size", type=int, default=None,
                   help="Reject PUT bodies larger than this many bytes")
    p.add_argument("--max-concurrent-puts", type=int, default=32,
                   help="Bound concurrent PUT ingests; 0 means unbounded "
                        "(default 32)")
    p.add_argument("--min-put-rate", type=int, default=256,
                   help="Abort PUTs averaging below this many bytes/sec "
                        "after a grace period; 0 disables (default 256)")
    p.add_argument("--max-concurrent-gets", type=int, default=256,
                   help="Shed GETs beyond this many in flight with "
                        "503 + Retry-After (per worker); 0 means "
                        "unbounded (default 256)")
    p.add_argument("--workers", type=int, default=None,
                   help="Serve with N pre-forked SO_REUSEPORT worker "
                        "processes (default: "
                        "$CHUNKY_BITS_TPU_GATEWAY_WORKERS, else 1)")

    p = sub.add_parser("ls", help="List the files in a cluster directory")
    p.add_argument("-r", "--recursive", action="store_true")
    p.add_argument("target")

    p = sub.add_parser(
        "meta-compact",
        help="Compact a cluster's meta-log metadata store (reclaim "
             "dead ref bytes, drop tombstones), optionally migrating "
             "a file-per-ref tree into the log first")
    p.add_argument("cluster")
    p.add_argument(
        "--from-path-store", metavar="DIR", default=None,
        help="before compacting, import every ref file under DIR "
             "(a 'type: path' metadata root) into the cluster's "
             "meta-log store, byte-for-byte; names already live in "
             "the log are skipped, so an interrupted migration simply "
             "re-runs")

    p = sub.add_parser(
        "migrate",
        help="Reference the file in its existing location and add parity")
    p.add_argument("source")
    p.add_argument("destination")

    p = sub.add_parser("resilver", help="Resilver a cluster file")
    p.add_argument("target")

    p = sub.add_parser(
        "scrub",
        help="Continuously verify cluster chunks against their golden "
             "digests and repair damaged parts")
    p.add_argument("cluster")
    p.add_argument("--once", action="store_true",
                   help="one full pass, print the report, exit "
                        "(default: run forever)")
    p.add_argument("--bytes-per-sec", type=float, default=None,
                   help="byte-rate bound for scrub reads (default: the "
                        "cluster's scrub_bytes_per_sec tunable / "
                        "$CHUNKY_BITS_TPU_SCRUB_BYTES_PER_SEC; --once "
                        "runs unthrottled when neither is set)")
    p.add_argument("--interval", type=float, default=60.0,
                   help="idle seconds between passes (default 60)")
    p.add_argument("--no-repair", action="store_true",
                   help="detect and report only; never resilver")

    p = sub.add_parser(
        "stats",
        help="Fetch a running gateway's /stats, /healthz, /scrub/status,"
             " /alerts and /metrics and render a one-screen summary")
    p.add_argument("--json", action="store_true",
                   help="emit the combined raw JSON payloads instead")
    p.add_argument("--watch", type=float, default=0.0, metavar="N",
                   help="redraw every N seconds until ctrl-c (a live "
                        "operator console; 0 = one shot, the default)")
    p.add_argument("url", help="gateway base URL (host:port or http://…)")

    p = sub.add_parser("verify", help="Verify a cluster file")
    p.add_argument("target")

    return parser


def _dump(obj, as_json: bool) -> None:
    if as_json:
        json.dump(obj, sys.stdout, indent=2)
        print()
    else:
        yaml_dump(obj, sys.stdout, sort_keys=False)


def _shard_geometry(args, targets: list) -> tuple[int, int]:
    """Infer (d, p) for the standalone shard codec (main.rs:521-559)."""
    if args.parity_chunks is None:
        raise ChunkyBitsError(
            "Parity Chunk Count must be known to decode shards")
    p = args.parity_chunks
    if args.data_chunks is not None:
        d = args.data_chunks
        if len(targets) != d + p:
            raise ChunkyBitsError(
                f"Invalid targets: Expected {d + p} targets but got "
                f"{len(targets)}")
        return d, p
    if len(targets) <= p:
        raise ChunkyBitsError(
            f"Invalid targets: Expected more than {p} targets but got "
            f"{len(targets)}")
    return len(targets) - p, p


async def run(args) -> int:
    if args.backend:
        # a WRITE, not a read: the CLI flag travels to ops/backend's
        # first-dispatch resolution through the env handoff (read back
        # via tunables.env_str — lint rule CB102 governs the read side)
        from chunky_bits_tpu.cluster.tunables import BACKEND_ENV

        os.environ[BACKEND_ENV] = args.backend
    config = await Config.load_or_default(
        args.config, chunk_size=args.chunk_size,
        data_chunks=args.data_chunks, parity_chunks=args.parity_chunks)
    try:
        return await _run_command(args, config)
    finally:
        # Close loop-bound aiohttp sessions before the loop shuts down, or
        # aiohttp warns "Unclosed client session" at interpreter exit.
        from chunky_bits_tpu.file.location import default_context

        await config.aclose()
        await default_context().aclose()


async def _run_command(args, config) -> int:
    cmd = args.command
    if cmd == "cat":
        destination = ClusterLocation.parse("-")
        for target in args.targets:
            reader = await ClusterLocation.parse(target).get_reader(config)
            await destination.write_from_reader(config, reader)
    elif cmd == "config-info":
        _dump(config.to_obj(), args.json)
    elif cmd == "cluster-info":
        cluster = await config.get_cluster(args.cluster)
        _dump(cluster.to_obj(), args.json)
    elif cmd == "cp":
        source = ClusterLocation.parse(args.source)
        destination = ClusterLocation.parse(args.destination)
        reader = await source.get_reader(config)
        await destination.write_from_reader(config, reader)
    elif cmd == "decode-shards":
        targets = [ClusterLocation.parse(t) for t in args.targets]
        d, p = _shard_geometry(args, targets)
        coder = get_coder(d, p)
        shards = []
        for target in targets:
            try:
                reader = await target.get_reader(config)
                shards.append(await _read_all(reader))
            except (ChunkyBitsError, OSError) as err:
                print(f"Error {target}: {err}", file=sys.stderr)
                shards.append(None)
        import numpy as np

        arrays = [np.frombuffer(s, dtype=np.uint8) if s is not None
                  else None for s in shards]
        arrays = coder.reconstruct_data(arrays)
        out = sys.stdout.buffer
        for arr in arrays[:d]:
            if arr is not None:
                out.write(bytes(arr))
        out.flush()
    elif cmd == "encode-shards":
        targets = [ClusterLocation.parse(t) for t in args.targets]
        d, p = _shard_geometry(args, targets)
        coder = get_coder(d, p)
        source = ClusterLocation.parse(args.source)
        data_buf = await _read_all(await source.get_reader(config))
        from chunky_bits_tpu.file.file_part import split_into_shards

        shards, _len = split_into_shards(data_buf, len(data_buf), d)
        import numpy as np

        parity = coder.encode([np.frombuffer(s, dtype=np.uint8)
                               for s in shards]) if p else []
        payloads = [bytes(s) for s in shards] + [bytes(x) for x in parity]
        for target, payload in zip(targets, payloads):
            try:
                await target.write_from_reader(
                    config, aio.BytesReader(payload))
            except (ChunkyBitsError, OSError) as err:
                print(f"Error {target}: {err}", file=sys.stderr)
    elif cmd == "file-info":
        source = ClusterLocation.parse(args.source)
        file_ref = await source.get_file_reference(
            config,
            await config.get_default_data_chunks(),
            await config.get_default_parity_chunks(),
            await config.get_default_chunk_size())
        _dump(file_ref.to_obj(), args.json)
    elif cmd == "find-unused-hashes":
        await find_unused_hashes(config, args)
    elif cmd == "get-hashes":
        target = ClusterLocation.parse(args.target)
        hashes = []
        async for h in target.get_hashes_rec(config):
            if args.sort or args.deduplicate:
                hashes.append(h)
            else:
                print(h)
        if args.sort:
            for h in sorted(set(hashes), key=str):
                print(h)
        elif args.deduplicate:
            for h in dict.fromkeys(hashes):
                print(h)
    elif cmd == "http-gateway":
        from chunky_bits_tpu.gateway import serve

        cluster = await config.get_cluster(args.cluster)
        host, sep, port = args.listen_addr.rpartition(":")
        if not sep or not port.isdigit():
            raise ChunkyBitsError(
                f"invalid --listen-addr {args.listen_addr!r} "
                "(expected host:port)")
        await serve(cluster, host or "127.0.0.1", int(port),
                    max_put_bytes=args.max_put_size,
                    max_concurrent_puts=args.max_concurrent_puts,
                    min_put_rate=args.min_put_rate,
                    max_concurrent_gets=args.max_concurrent_gets,
                    workers=args.workers)
    elif cmd == "ls":
        target = ClusterLocation.parse(args.target)
        if args.recursive:
            async for entry in target.list_files_recursive(config):
                print(entry)
        else:
            for entry in await target.list_files(config):
                print(entry)
    elif cmd == "meta-compact":
        await meta_compact(config, args)
    elif cmd == "migrate":
        source = ClusterLocation.parse(args.source)
        destination = ClusterLocation.parse(args.destination)
        await source.migrate(config, destination)
    elif cmd == "resilver":
        target = ClusterLocation.parse(args.target)
        report = await target.resilver(config)
        print(report.display_full_report())
    elif cmd == "scrub":
        from chunky_bits_tpu.cluster.scrub import ScrubDaemon

        cluster = await config.get_cluster(args.cluster)
        daemon = ScrubDaemon(
            cluster, bytes_per_sec=args.bytes_per_sec,
            interval_seconds=args.interval, repair=not args.no_repair)
        if args.once:
            stats = await daemon.run_once()
            print(stats)
        else:
            # run until ctrl-c; print one stats line per pass so an
            # operator tailing the log sees progress
            try:
                while True:
                    stats = await daemon.run_once()
                    print(stats, flush=True)
                    await asyncio.sleep(max(args.interval, 0.0))
            # lint: cancel-safety-ok top-level ctrl-c handler of the
            # scrub command: asyncio.run is tearing this coroutine down
            # right after — nothing awaits it as a child task
            except (KeyboardInterrupt, asyncio.CancelledError):
                pass
    elif cmd == "stats":
        from chunky_bits_tpu.cli.stats import stats_command

        return await stats_command(args.url, args.json,
                                   watch_s=max(args.watch, 0.0))
    elif cmd == "verify":
        target = ClusterLocation.parse(args.target)
        report = await target.verify(config)
        print(report.display_full_report())
    return 0


async def _read_all(reader: aio.AsyncByteReader) -> bytes:
    chunks = []
    while True:
        data = await reader.read(1 << 20)
        if not data:
            break
        chunks.append(data)
    return b"".join(chunks)


async def meta_compact(config, args) -> None:
    """``meta-compact``: maintenance for the indexed metadata plane
    (cluster/meta_log.py).  Compacts the cluster's meta-log store —
    live refs copied into fresh log files, dead bytes reclaimed,
    tombstones dropped, the journal swapped atomically — and, with
    ``--from-path-store DIR``, first imports a file-per-ref metadata
    tree into the log: every ref's bytes are appended EXACTLY as the
    file holds them (byte identity across stores is the golden-pinned
    contract), with the index projection extracted from the parsed
    payload so the scrub/GC fast paths work for migrated refs too.
    The import is idempotent — names already live in the log are
    skipped — so an interrupted migration simply re-runs; unparseable
    files are surfaced on stderr and skipped like every walk in this
    CLI treats foreign entries."""
    from chunky_bits_tpu.cluster.meta_log import (
        MetadataLog,
        extract_index_meta,
        norm_name,
    )
    from chunky_bits_tpu.file.location import is_publish_temp

    cluster = await config.get_cluster(args.cluster)
    metadata = cluster.metadata
    if not isinstance(metadata, MetadataLog):
        raise ChunkyBitsError(
            f"cluster {args.cluster!r} metadata is not a meta-log "
            "store (set `metadata: {type: meta-log, ...}` in the "
            "cluster config first)")
    if args.from_path_store:
        root = args.from_path_store
        loads = metadata.format.loader()

        def _walk() -> list[tuple[str, str]]:
            out = []
            for dirpath, _dirs, files in os.walk(root):
                for fname in files:
                    if is_publish_temp(fname):
                        continue  # a crashed path-store writer's temp
                    full = os.path.join(dirpath, fname)
                    out.append(
                        (norm_name(os.path.relpath(full, root)), full))
            out.sort()
            return out

        def _import_one(name: str, full: str) -> bool:
            if metadata.store.lookup(name) is not None:
                return False  # already migrated: idempotent re-run
            with open(full, "rb") as f:
                data = f.read()
            try:
                payload = loads(data)
            except Exception as err:  # noqa: BLE001 — a foreign file
                # in the tree must not abort the migration
                print(f"Skipping unparseable {full}: {err}",
                      file=sys.stderr)
                return False
            hashes, nodes = extract_index_meta(payload)
            metadata.store.append(name, data,
                                  hashes=hashes, nodes=nodes)
            return True

        migrated = 0
        for name, full in await asyncio.to_thread(_walk):
            if await asyncio.to_thread(_import_one, name, full):
                migrated += 1
        print(f"Migrated {migrated} refs from {root}", file=sys.stderr)
    report = await metadata.compact()
    print(json.dumps(report))


async def find_unused_hashes(config, args) -> None:
    """GC: list hash files under local dirs, subtract hashes referenced by
    the sources, print/remove the orphans; batched (main.rs:329-435).

    Safe against concurrent ingest where the reference is not: a ``cp``
    stages chunk files BEFORE publishing the metadata that references
    them, so a racing GC would list the new chunk, find no reference,
    and delete it out from under the imminent publish.  Chunk files
    younger than ``--grace-seconds`` (measured against GC start) are
    therefore never candidates; the reference runs the same scan with no
    such guard (main.rs:329-435).  tests/test_gc_race.py interleaves
    GC batches with live writes to pin the guarantee.

    Packed destinations (``slab:/dir``, file/slab.py) take the index
    fast path: candidates come from one scan of the store's index —
    O(live chunks), no dirent walk at all — with the publish timestamp
    recorded in each journal line standing in for the file mtime, and
    removal marks the extent dead for compaction instead of unlinking
    anything.  The dirent walk below is kept for legacy path
    destinations."""
    import time as _time

    from chunky_bits_tpu.file import slab as slab_mod

    sources = [ClusterLocation.parse(s) for s in args.source]
    for s in sources:
        if s.kind not in ("cluster", "file_ref"):
            raise ChunkyBitsError(f"Unsupported source location: {s}")
    hash_dirs = [ClusterLocation.parse(h) for h in args.hashes]
    for h in hash_dirs:
        if h.kind != "other" or not (h.location.is_local()
                                     or h.location.is_slab()):
            raise ChunkyBitsError(f"Unsupported hashes location: {h}")
    cutoff = _time.time() - args.grace_seconds

    async def _age_of(path: str) -> str:
        """``"old"`` (a GC candidate), ``"fresh"`` (inside the grace
        window — an in-flight write may be about to reference it), or
        ``"gone"`` (vanished mid-scan).  stat runs off-loop like the
        listing's own metadata calls; slab candidates consult the
        extent's journal-recorded publish time instead of a stat."""
        if path.startswith("slab:"):
            loc = Location.parse(path)
            root, name = os.path.split(loc.target)
            store = slab_mod.get_store(root)
            ext = await asyncio.to_thread(store.lookup, name)
            if ext is None:
                return "gone"
            if args.grace_seconds <= 0:
                return "old"
            return "old" if ext.published < cutoff else "fresh"
        if args.grace_seconds <= 0:
            return "old"
        try:
            st = await asyncio.to_thread(os.stat, path)
        except OSError:
            return "gone"
        return "old" if st.st_mtime < cutoff else "fresh"

    # Atomic local publication stages temp files and renames in
    # (location.is_publish_temp defines the format next to its
    # producer); a writer killed hard leaves the temp behind with no
    # other reclamation path.  A temp is invisible until renamed, so
    # any one older than the grace window is dead — remove it here
    # (the scan ignores other unknown names, as the reference does,
    # main.rs:372-377).
    from chunky_bits_tpu.file.location import is_publish_temp

    async def _reap_stale_temp(path: str) -> bool:
        if not is_publish_temp(os.path.basename(path)):
            return False
        if args.grace_seconds > 0 and await _age_of(path) != "old":
            return True  # a live writer's temp: skip, don't report
        print(f"Stale publish temp: {path}", file=sys.stderr)
        if args.remove:
            try:
                await Location.local(path).delete()
            except LocationError as err:
                # only the missing-file race is benign (renamed or
                # reaped concurrently); EACCES/EROFS etc. must surface
                # like the ordinary chunk path's failures do
                cause = err.__cause__
                if not (isinstance(cause, OSError)
                        and cause.errno == errno.ENOENT):
                    raise
        return True

    async def hash_files():
        for hash_dir in hash_dirs:
            if hash_dir.location.is_slab():
                # index fast path: ONE scan of the packed store's
                # index — O(live chunks), zero dirents, and the grace
                # check filters on the extents already in hand (each
                # journal line carries its publish time) instead of a
                # per-name lookup; the last-moment re-check before a
                # delete stays in _age_of
                root = hash_dir.location.target.rstrip("/")
                store = slab_mod.get_store(root)
                extents = await asyncio.to_thread(store.live_extents)
                for name, ext in extents:
                    if args.grace_seconds > 0 \
                            and ext.published >= cutoff:
                        continue
                    yield f"slab:{os.path.join(root, name)}"
                continue
            async for entry in hash_dir.list_files_recursive(config):
                if not entry.is_file():
                    continue
                if await _reap_stale_temp(entry.path):
                    continue
                if await _age_of(entry.path) == "old":
                    yield entry.path

    files_iter = hash_files()
    done = False
    while not done:
        existing: dict[str, list[str]] = {}
        while len(existing) < args.batch_size:
            try:
                path = await files_iter.__anext__()
            except StopAsyncIteration:
                done = True
                break
            name = os.path.basename(path)
            try:
                hash_ = AnyHash.parse(name)
            except ChunkyBitsError:
                print(f"Unknown hash: {name}", file=sys.stderr)
                continue
            existing.setdefault(str(hash_), []).append(path)
        if not existing:
            break
        for source in sources:
            async for hash_ in source.get_hashes_rec(config):
                existing.pop(str(hash_), None)
        for hash_str, paths in existing.items():
            if not args.remove:
                print(hash_str)
                continue
            removed = False
            for path in paths:
                # Re-check age at the last moment: a concurrent ingest
                # can re-write a listed orphan (same content hash =>
                # same path) between the batch scan and this delete; a
                # fresh mtime means someone wants it again.
                age = await _age_of(path)
                if age == "gone":
                    continue  # someone else removed it — goal achieved
                if age == "fresh":
                    print(f"Skipping recently re-written {path}",
                          file=sys.stderr)
                    continue
                print(f"Removing {path}", file=sys.stderr)
                # a slab path marks the extent dead for compaction
                # (Location.delete's slab branch); plain paths unlink
                loc = (Location.parse(path) if path.startswith("slab:")
                       else Location.local(path))
                await loc.delete()
                removed = True
            if removed:
                # in remove mode the stdout line means "collected", so
                # a hash whose every path was spared is not printed
                print(hash_str)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # `find-unused-hashes SOURCES -- HASH_DIRS`: split at the separator
    # ourselves (argparse cannot split two variadic positionals).
    tail: list[str] = []
    if "--" in argv:
        idx = argv.index("--")
        argv, tail = argv[:idx], argv[idx + 1:]
    args = build_parser().parse_args(argv)
    if tail:
        if args.command != "find-unused-hashes":
            print("unexpected arguments after --", file=sys.stderr)
            return 2
        args.hashes = tail
    if args.command == "find-unused-hashes" and not args.hashes:
        print("find-unused-hashes requires hash directories after --",
              file=sys.stderr)
        return 2
    try:
        return asyncio.run(run(args))
    except ChunkyBitsError as err:
        print(err, file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 130
    except BrokenPipeError:
        # Downstream closed (e.g. `cat ... | head`): die quietly like a
        # coreutils tool.  Point stdout at devnull so the interpreter's
        # exit-time flush doesn't raise a second time.  stdout may not be
        # backed by a real fd (captured/replaced in embedding harnesses);
        # still exit quietly then.
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, sys.stdout.fileno())
        except (OSError, ValueError):
            # fileno() raises ValueError/io.UnsupportedOperation on a
            # replaced stdout; everything else here raises OSError
            pass
        return 141  # 128 + SIGPIPE


if __name__ == "__main__":
    sys.exit(main())
