"""``chunky-bits stats [--json] [--watch N] <gateway-url>`` —
one-screen gateway observability summary.

Fetches the observability surface of a running gateway (``/stats``,
``/healthz``, ``/scrub/status``, ``/alerts`` and — as a grammar check —
``/metrics``) and renders it for a human: request percentiles (computed
server-side by the same ``request_stats``/``percentile`` code in
file/profiler.py that bench --config 9 uses), cache hit rates, pipeline
saturation, per-node health, scrub progress, SLO alert states
(obs/slo.py — firing rules first, with their windowed values against
their objectives), and the event-loop lag histogram's tail
(``obs.metrics.histogram_quantile`` over the scraped buckets).
``--json`` emits the combined raw payloads for machine consumers;
``--watch N`` redraws every N seconds (clock-seam timed, so the one
tool works under a virtual clock too) — a live operator console
without an external scraper.

No reference counterpart (the reference has no metrics surface); a
TPU-repo extension documented in PARITY.md.
"""

from __future__ import annotations

import json
import sys
from typing import Optional, TextIO

from chunky_bits_tpu.errors import ChunkyBitsError
from chunky_bits_tpu.obs import metrics as obs_metrics

#: the clock seam (canonical surface cluster/clock.py; utils-side
#: import for cycle hygiene) — the --watch redraw cadence follows the
#: active clock like every other timed policy
from chunky_bits_tpu.utils import clock as _clock


#: family-by-name lookup — the shared scan in obs/metrics.py
_family = obs_metrics.find_family


def _scalar_total(snapshot: dict, name: str) -> float:
    fam = _family(snapshot, name)
    if fam is None:
        return 0.0
    return sum(s.get("value", 0.0) for s in fam.get("samples", ()))


def render_summary(stats: dict, healthz: dict, scrub: dict,
                   out: TextIO,
                   alerts: Optional[dict] = None) -> None:
    """The one-screen human rendering (pure function of the fetched
    payloads so tests can pin it without a socket)."""
    snap = stats.get("metrics", {"families": []})
    req = stats.get("requests", {})
    print(f"worker {stats.get('worker', '?')} "
          f"status={healthz.get('status', '?')} "
          f"uptime={healthz.get('uptime_s', 0.0):.0f}s", file=out)
    print(f"requests: n={req.get('count', 0)} "
          f"errors={req.get('errors', 0)} "
          f"bytes={req.get('total_bytes', 0)} "
          f"p50={req.get('p50_ms', 0.0):.2f}ms "
          f"p99={req.get('p99_ms', 0.0):.2f}ms "
          f"p999={req.get('p999_ms', 0.0):.2f}ms", file=out)
    dropped = {k: v for k, v in stats.get("dropped", {}).items() if v}
    if dropped:
        print(f"dropped log entries: {dropped}", file=out)
    hits = _scalar_total(snap, "cb_cache_hits_total")
    misses = _scalar_total(snap, "cb_cache_misses_total")
    if hits or misses:
        rate = 100.0 * hits / max(hits + misses, 1.0)
        print(f"cache: hits={hits:.0f} misses={misses:.0f} "
              f"({rate:.1f}% hit) "
              f"bytes={_scalar_total(snap, 'cb_cache_size_bytes'):.0f}/"
              f"{_scalar_total(snap, 'cb_cache_capacity_bytes'):.0f}",
              file=out)
    busy_fam = _family(snap, "cb_pipeline_busy_seconds_total")
    if busy_fam is not None:
        stages = ", ".join(
            f"{s['labels'].get('stage', '?')}={s['value']:.2f}s"
            for s in busy_fam.get("samples", ()))
        print(f"pipeline: threads="
              f"{_scalar_total(snap, 'cb_pipeline_threads'):.0f} "
              f"busy[{stages}] "
              f"idle={_scalar_total(snap, 'cb_pipeline_idle_seconds_total'):.1f}s",
              file=out)
    err_fam = _family(snap, "cb_node_errors_total")
    comp_fam = _family(snap, "cb_node_completions_total")
    if comp_fam is not None:
        errors = {tuple(sorted(s["labels"].items())): s["value"]
                  for s in (err_fam or {}).get("samples", ())}
        for s in comp_fam.get("samples", ()):
            node = s["labels"].get("node", "?")
            err = errors.get(tuple(sorted(s["labels"].items())), 0.0)
            print(f"node {node}: completions={s['value']:.0f} "
                  f"errors={err:.0f}", file=out)
    lag_fam = _family(snap, "cb_eventloop_lag_seconds")
    if lag_fam is not None and lag_fam.get("samples"):
        s = lag_fam["samples"][0]
        p99 = obs_metrics.histogram_quantile(
            lag_fam.get("buckets", []), s.get("counts", []), 99.0)
        print(f"event loop: lag p99~{p99 * 1000.0:.2f}ms "
              f"(n={s.get('count', 0)})", file=out)
    if scrub.get("enabled"):
        print(f"scrub: passes={scrub.get('passes', 0)} "
              f"verified={scrub.get('bytes_verified', 0)}B "
              f"corrupt={scrub.get('corrupt', 0)} "
              f"repaired={scrub.get('repaired', 0)} "
              f"running={scrub.get('running', False)}", file=out)
        repair = scrub.get("repair")
        if repair is not None:
            # the planner's counters (cluster/repair.py RepairStats —
            # the same numbers behind the cb_repair_* families);
            # msr = pm-msr β-projection regenerations (ops/pm_msr.py)
            def helper_bytes(row: dict) -> int:
                return (row.get("helper_bytes_replica", 0)
                        + row.get("helper_bytes_decode", 0)
                        + row.get("helper_bytes_msr", 0))

            helper = helper_bytes(repair)
            ratio = repair.get("helper_bytes_per_rebuilt_byte")
            line = (f"repair: plans copy={repair.get('plans_copy', 0)} "
                    f"decode={repair.get('plans_decode', 0)} "
                    f"msr={repair.get('plans_msr', 0)} "
                    f"fallback={repair.get('plans_fallback', 0)} "
                    f"helperB={helper} "
                    f"rebuiltB={repair.get('bytes_rebuilt', 0)}")
            if ratio is not None:
                line += f" helperB/rebuiltB={ratio:.2f}"
            print(line, file=out)
            by_code = repair.get("by_code") or {}
            active = {c: v for c, v in sorted(by_code.items())
                      if any(v.get(k, 0) for k in v)}
            if len(active) > 1 or (active and "rs" not in active):
                for code_name, v in active.items():
                    print(f"repair[{code_name}]: "
                          f"helperB={helper_bytes(v)} "
                          f"rebuiltB={v.get('bytes_rebuilt', 0)}",
                          file=out)
    else:
        print("scrub: disabled", file=out)
    alerts = alerts if alerts is not None else {"enabled": False}
    if not alerts.get("enabled"):
        print("slo: disabled", file=out)
    else:
        firing = alerts.get("firing", [])
        fleet = alerts.get("fleet") or {}
        fleet_firing = fleet.get("firing", [])
        header = (f"slo: {len(firing)} firing "
                  f"(evals={alerts.get('evaluations', 0)})")
        if fleet:
            header += f" fleet-firing={len(fleet_firing)}"
        print(header, file=out)
        # firing rules first (the operator's first question), then
        # pending; quiet rules stay off the screen
        rows = sorted(alerts.get("alerts", ()),
                      key=lambda a: (a.get("state") != "firing",
                                     a.get("state") != "pending",
                                     a.get("rule", "")))
        for a in rows:
            if a.get("state") == "inactive":
                continue
            fast = a.get("value_fast")
            fast_s = "-" if fast is None else f"{fast:.4g}"
            print(f"  alert {a.get('rule')}: {a.get('state')} "
                  f"value={fast_s} threshold={a.get('threshold')} "
                  f"fired_count={a.get('fired_count', 0)}", file=out)


async def fetch_once(base: str) -> tuple[dict, dict, dict, dict]:
    """One round of the gateway's observability surface:
    (stats, healthz, scrub, alerts) — with the /metrics exposition
    grammar gate riding along (the same parser the tests and CI scrape
    step use).  Raises ChunkyBitsError on an unreachable or defective
    gateway — a stats tool must not silently summarize garbage."""
    import aiohttp

    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{base}/stats") as resp:
                if resp.status != 200:
                    raise ChunkyBitsError(
                        f"GET /stats returned {resp.status}")
                stats = await resp.json()
            async with session.get(f"{base}/healthz") as resp:
                healthz = await resp.json()
            async with session.get(f"{base}/scrub/status") as resp:
                scrub = await resp.json()
            async with session.get(f"{base}/alerts") as resp:
                if resp.status == 200:
                    alerts = await resp.json()
                else:
                    # a pre-SLO gateway 404s here (the catch-all
                    # treats "alerts" as an object name): render the
                    # rest of the stats surface with the slo stanza
                    # disabled instead of failing the whole command —
                    # mixed-version fleets are a normal rollout state
                    alerts = {"enabled": False}
            async with session.get(f"{base}/metrics") as resp:
                metrics_text = await resp.text()
    except aiohttp.ClientError as err:
        raise ChunkyBitsError(f"cannot reach gateway {base}: {err}") \
            from err
    try:
        obs_metrics.parse_exposition(metrics_text)
    except obs_metrics.ExpositionError as err:
        # surfaced as the CLI's one-line error, not a traceback: a
        # proxy answering /metrics with HTML is an operator problem to
        # report, not a crash
        raise ChunkyBitsError(
            f"{base}/metrics is not valid exposition: {err}") from err
    return stats, healthz, scrub, alerts


async def stats_command(url: str, as_json: bool,
                        out: Optional[TextIO] = None,
                        watch_s: float = 0.0) -> int:
    """Fetch + render; the ``chunky-bits stats`` body.  ``watch_s`` > 0
    loops forever, redrawing every that-many seconds (timed through the
    clock seam) with a timestamped separator between frames — the live
    operator console for the alert/SLO stanza.  Ctrl-C exits the loop
    cleanly (the CLI's standard 130)."""
    out = out if out is not None else sys.stdout
    base = url.rstrip("/")
    if "://" not in base:
        base = f"http://{base}"
    frame = 0
    while True:
        stats, healthz, scrub, alerts = await fetch_once(base)
        if as_json:
            json.dump({"stats": stats, "healthz": healthz,
                       "scrub": scrub, "alerts": alerts},
                      out, indent=2)
            print(file=out)
        else:
            if watch_s > 0:
                print(f"--- frame {frame} "
                      f"(every {watch_s:g}s, ctrl-c to stop) ---",
                      file=out)
            render_summary(stats, healthz, scrub, out, alerts=alerts)
        if watch_s <= 0:
            return 0
        frame += 1
        out.flush()
        await _clock.sleep(watch_s)
