"""The CLI's universal URI: ``cluster#path``, ``cluster[profile]#path``,
``@#file-ref-location``, a bare location, or ``-`` for stdio.

Mirrors src/bin/chunky-bits/cluster_location.rs: parser (:650-684), display
(:686-705), readers/writers (:101-180), listing (:182-353), resilver/verify
(:355-402), hash streaming (:404-515), and migrate — referencing a file
in-place via range-sliced locations without copying data (:517-620).
"""

from __future__ import annotations

import asyncio
import os
import sys
from dataclasses import dataclass
from typing import AsyncIterator, Optional
from urllib.parse import urlsplit

from chunky_bits_tpu.cluster import Cluster, ClusterProfile, FileOrDirectory
from chunky_bits_tpu.errors import ChunkyBitsError, SerdeError  # noqa: F401
from chunky_bits_tpu.file import (
    AnyHash,
    FileReadBuilder,
    FileReference,
    Location,
)
from chunky_bits_tpu.utils import aio
from chunky_bits_tpu.utils.yamlio import yaml_load

_warned_once: set[str] = set()


def _warn_once(key: str, message: str) -> None:
    if key not in _warned_once:
        _warned_once.add(key)
        print(message, file=sys.stderr)


class _StdinReader:
    async def read(self, n: int = -1) -> bytes:
        return await asyncio.to_thread(sys.stdin.buffer.read, n)


@dataclass(frozen=True)
class ClusterLocation:
    kind: str  # "cluster" | "file_ref" | "other" | "stdio"
    cluster: Optional[str] = None
    profile: Optional[str] = None
    path: Optional[str] = None
    location: Optional[Location] = None

    # ---- parse / display (cluster_location.rs:650-705) ----

    @staticmethod
    def parse(s: str) -> "ClusterLocation":
        if s == "-":
            return ClusterLocation("stdio")
        prefix, sep, path = s.partition("#")
        if sep and "#" in path:
            raise SerdeError(f"invalid cluster location format: {s}")
        if not sep:
            return ClusterLocation("other", location=Location.parse(s))
        if prefix == "@":
            return ClusterLocation(
                "file_ref", location=Location.parse(path))
        if prefix.endswith("]") and "[" in prefix:
            idx = prefix.rfind("[")
            cluster, profile = prefix[:idx], prefix[idx + 1:-1]
            return ClusterLocation(
                "cluster", cluster=cluster, profile=profile, path=path)
        if prefix and prefix[-1].isalnum():
            return ClusterLocation("cluster", cluster=prefix, path=path)
        raise SerdeError(f"invalid cluster name/file: {prefix}")

    def __str__(self) -> str:
        if self.kind == "stdio":
            return "-"
        if self.kind == "cluster":
            if self.profile is not None:
                return f"{self.cluster}[{self.profile}]#{self.path}"
            return f"{self.cluster}#{self.path}"
        if self.kind == "file_ref":
            return f"@#{self.location}"
        return str(self.location)

    # ---- cluster/profile resolution (cluster_location.rs:622-647) ----

    async def get_cluster_with_profile(
        self, config
    ) -> tuple[Cluster, ClusterProfile]:
        if self.kind != "cluster":
            raise ChunkyBitsError("not a cluster location")
        cluster = await config.get_cluster(self.cluster)
        profile_name = self.profile
        if profile_name is None:
            profile_name = config.get_profile(self.cluster)
        profile = cluster.get_profile(profile_name)
        if profile is None:
            raise ChunkyBitsError(f"Profile not found: {profile_name}")
        return cluster, profile

    async def _load_file_ref(self, config) -> FileReference:
        if self.kind == "cluster":
            cluster = await config.get_cluster(self.cluster)
            return await cluster.get_file_ref(self.path)
        if self.kind == "file_ref":
            import yaml

            data = await self.location.read()
            try:
                obj = yaml_load(data)
            except yaml.YAMLError as err:
                raise SerdeError(
                    f"invalid file reference at {self.location}: {err}"
                ) from err
            return FileReference.from_obj(obj)
        raise ChunkyBitsError(f"no file reference for {self}")

    # ---- read / write (cluster_location.rs:101-180) ----

    async def get_reader(self, config) -> aio.AsyncByteReader:
        if self.kind in ("cluster", "file_ref"):
            file_ref = await self._load_file_ref(config)
            if self.kind == "cluster":
                # the cluster's serve-path builder: shared reconstruct
                # batcher + (when tuned on) the content-addressed cache
                cluster = await config.get_cluster(self.cluster)
                return cluster.file_read_builder(file_ref).reader()
            return FileReadBuilder(file_ref).reader()
        if self.kind == "other":
            return await self.location.reader()
        return _StdinReader()

    async def write_from_reader(self, config, reader: aio.AsyncByteReader
                                ) -> int:
        if self.kind == "cluster":
            cluster, profile = await self.get_cluster_with_profile(config)
            file_ref = await cluster.get_file_writer(profile).write(reader)
            await cluster.write_file_ref(self.path, file_ref)
            return file_ref.len_bytes()
        if self.kind == "file_ref":
            import json

            destination = await config.get_default_destination()
            d = await config.get_default_data_chunks()
            p = await config.get_default_parity_chunks()
            cs = await config.get_default_chunk_size()
            _warn_once(
                "default-destination",
                f"Warning: Writing using default destination data = {d}, "
                f"parity = {p}, chunk_size = 2^{cs}",
            )
            file_ref = await (
                FileReference.write_builder()
                .with_destination(destination)
                .with_data_chunks(d)
                .with_parity_chunks(p)
                .with_chunk_size(1 << cs)
                .write(reader)
            )
            await self.location.write(
                json.dumps(file_ref.to_obj(), indent=2).encode())
            return file_ref.len_bytes()
        if self.kind == "other":
            return await self.location.write_from_reader(reader)
        # stdio: writes block in a worker thread (a slow pipe consumer
        # must not stall the read pipeline's event loop), but hopping
        # threads per 1 MiB chunk costs ~2-4 ms each on a small host —
        # several seconds per GiB of pure scheduling.  Batch chunks to
        # 8 MiB per hop (one extra memcpy, ~50x cheaper than the hops),
        # with a 0.25 s age bound so a slow producer still streams
        # progressively to the consumer instead of freezing per batch.
        import time as _time

        total = 0
        buf = bytearray()
        buf_born = 0.0

        async def flush_buf():
            nonlocal buf
            if buf:
                out, buf = buf, bytearray()
                await asyncio.to_thread(sys.stdout.buffer.write, out)

        while True:
            data = await reader.read(1 << 20)
            if not data:
                break
            total += len(data)
            if not buf:
                buf_born = _time.monotonic()
            buf += data
            if (len(buf) >= (8 << 20)
                    or _time.monotonic() - buf_born > 0.25):
                await flush_buf()
        await flush_buf()
        await asyncio.to_thread(sys.stdout.buffer.flush)
        return total

    # ---- listing (cluster_location.rs:182-353) ----

    async def list_files(self, config) -> list[FileOrDirectory]:
        if self.kind == "cluster":
            cluster = await config.get_cluster(self.cluster)
            return await cluster.list_files(self.path)
        if self.kind == "stdio":
            return [FileOrDirectory("file", "-")]
        loc = self.location
        if loc.is_local():
            entries = await FileOrDirectory.list(loc.target)
            return entries
        # HTTP locations list as a single file (the path component)
        return [FileOrDirectory("file", urlsplit(loc.target).path)]

    async def list_files_recursive(self, config
                                   ) -> AsyncIterator[FileOrDirectory]:
        entries = await self.list_files(config)
        if not entries:
            return
        yield entries[0]
        for entry in entries[1:]:
            if entry.is_directory():
                sub = self.make_sub_location(entry.path)
                async for item in sub.list_files_recursive(config):
                    yield item
            else:
                yield entry

    def make_sub_location(self, new_path: str) -> "ClusterLocation":
        """Rebase this location onto a (possibly absolute) listed path
        (cluster_location.rs:253-335)."""
        if self.kind == "cluster":
            return ClusterLocation("cluster", cluster=self.cluster,
                                   profile=self.profile, path=new_path)
        if self.kind == "stdio":
            return self
        loc = self.location
        sub_parts = [p for p in new_path.split("/")
                     if p not in ("", ".", "..")]
        if loc.is_local():
            parent_parts = [p for p in loc.target.split("/")
                            if p not in ("", ".", "..")]
        else:
            parent_parts = [p for p in urlsplit(loc.target).path.split("/")
                            if p]
        i = 0
        for parent_part in parent_parts:
            if i < len(sub_parts) and parent_part == sub_parts[i]:
                i += 1
            else:
                break
        remaining = sub_parts[i:]
        if loc.is_local():
            new_loc = Location.local(
                os.path.join(loc.target, *remaining)
                if remaining else loc.target)
        else:
            new_loc = loc
            for part in remaining:
                new_loc = new_loc.child(part)
        return ClusterLocation(self.kind, location=new_loc)

    async def list_cluster_locations(self, config
                                     ) -> AsyncIterator["ClusterLocation"]:
        async for entry in self.list_files_recursive(config):
            if entry.is_file():
                yield self.make_sub_location(entry.path)

    # ---- verify / resilver (cluster_location.rs:355-402) ----

    async def resilver(self, config):
        if self.kind == "cluster":
            cluster, profile = await self.get_cluster_with_profile(config)
            destination = cluster.get_destination(profile)
            file_ref = await cluster.get_file_ref(self.path)
            report = await file_ref.resilver(
                destination, backend=cluster.tunables.backend)
            await cluster.write_file_ref(self.path, file_ref)
            return report
        if self.kind == "file_ref":
            import json

            file_ref = await self._load_file_ref(config)
            destination = await config.get_default_destination()
            report = await file_ref.resilver(destination)
            await self.location.write(
                json.dumps(file_ref.to_obj(), indent=2).encode())
            return report
        raise ChunkyBitsError("Resilver is only supported on cluster files")

    async def verify(self, config):
        if self.kind in ("cluster", "file_ref"):
            file_ref = await self._load_file_ref(config)
            cx = None
            if self.kind == "cluster":
                cluster = await config.get_cluster(self.cluster)
                cx = cluster.tunables.location_context()
            return await file_ref.verify(cx)
        raise ChunkyBitsError("Verify is only supported on files")

    # ---- hashes (cluster_location.rs:404-515) ----

    async def get_hashes(self, config) -> list[AnyHash]:
        if self.kind in ("cluster", "file_ref"):
            file_ref = await self._load_file_ref(config)
            return [
                chunk.hash
                for part in file_ref.parts
                for chunk in part.data + part.parity
            ]
        # raw data: hash through the codec without storing
        d = await config.get_default_data_chunks()
        p = await config.get_default_parity_chunks()
        cs = await config.get_default_chunk_size()
        _warn_once(
            "hashes-binary",
            f"Warning: Hashes generated from binary data using data = {d}, "
            f"parity = {p}, chunk_size = 2^{cs}",
        )
        reader = await self.get_reader(config)
        file_ref = await (
            FileReference.write_builder()
            .with_data_chunks(d)
            .with_parity_chunks(p)
            .with_chunk_size(1 << cs)
            .write(reader)
        )
        return [
            chunk.hash
            for part in file_ref.parts
            for chunk in part.data + part.parity
        ]

    async def _get_hashes_snapshot(self, metadata
                                   ) -> Optional[list[str]]:
        """Meta-log fast path for the liveness walk: every referenced
        hash under this location, in display form (``sha256-<hex>`` —
        both consumers of ``get_hashes_rec`` key on ``str(hash)``, so
        handing strings skips 10^5 ``AnyHash`` constructions per 10^4
        refs).  Tries the pure INDEX scan first (``namespace_hashes``:
        publish-time hash projections, zero ref reads, zero parses),
        then one ``namespace_snapshot()`` batch read+parse; either way
        no recursive listing and no per-file metadata round-trips.
        None when neither surface is available (the caller runs the
        legacy walk); per-ref parse failures on the snapshot path are
        surfaced on stderr and skipped, exactly like the legacy walk's
        per-file failures."""
        from chunky_bits_tpu.cluster.meta_log import norm_name

        want = norm_name(self.path or "")
        prefix = want + "/" if want else ""

        def _mine(name: str) -> bool:
            return not prefix or name == want or name.startswith(prefix)

        index = getattr(metadata, "namespace_hashes", None)
        if index is not None:
            try:
                rows = await index()
            except ChunkyBitsError:
                rows = None
            if rows is not None:
                return [h for name, hashes in rows if _mine(name)
                        for h in hashes]
        try:
            entries = await metadata.namespace_snapshot()
        except ChunkyBitsError:
            # a poisoned batched read: the per-file walk isolates the
            # bad entry and surfaces it individually
            return None
        out: list[str] = []
        for name, obj in entries:
            if not _mine(name):
                continue
            try:
                ref = FileReference.from_obj(obj)
            except ChunkyBitsError as err:
                print(f"{self.cluster}#{name}: {err}", file=sys.stderr)
                continue
            for part in ref.parts:
                for chunk in part.data + part.parity:
                    out.append(str(chunk.hash))
        return out

    async def get_hashes_rec(self, config) -> AsyncIterator:
        """One task per file, mpsc fan-in (cluster_location.rs:478-515).
        Every per-file failure is surfaced on stderr — a swallowed error
        here could misclassify live chunks as garbage downstream.

        A cluster source over a meta-log metadata store short-circuits
        through ``_get_hashes_snapshot`` (an index scan, or one batched
        namespace read — no per-file tasks), which yields hash display
        STRINGS; the fan-in below is the universal path and yields
        ``AnyHash``.  Both consumers key on ``str(hash)``, which is
        identical either way."""
        if self.kind == "cluster":
            cluster = await config.get_cluster(self.cluster)
            if hasattr(cluster.metadata, "namespace_snapshot"):
                hashes = await self._get_hashes_snapshot(cluster.metadata)
                if hashes is not None:
                    for h in hashes:
                        yield h
                    return
        queue: asyncio.Queue = asyncio.Queue(50)
        tasks = []
        _DONE = object()

        async def hash_one(loc: "ClusterLocation") -> None:
            try:
                for h in await loc.get_hashes(config):
                    await queue.put(("ok", h))
            except Exception as err:  # noqa: BLE001 — must never swallow
                await queue.put(("err", f"{loc}: {err}"))

        async for loc in self.list_cluster_locations(config):
            tasks.append(asyncio.ensure_future(hash_one(loc)))

        async def watcher() -> None:
            await asyncio.gather(*tasks, return_exceptions=True)
            await queue.put(_DONE)

        pending = asyncio.ensure_future(watcher())
        try:
            while tasks:
                item = await queue.get()
                if item is _DONE:
                    break
                kind, value = item
                if kind == "ok":
                    yield value
                else:
                    print(value, file=sys.stderr)
        finally:
            await pending

    # ---- migrate (cluster_location.rs:517-620) ----

    async def get_file_reference(self, config, data: int, parity: int,
                                 chunk_size_log2: int) -> FileReference:
        """For ``other`` locations, build a reference whose data chunks are
        range-sliced views of the original file (no copy), with parity
        written through the normal path."""
        if self.kind == "cluster" or self.kind == "file_ref":
            return await self._load_file_ref(config)
        if self.kind != "other":
            raise ChunkyBitsError(f"Cannot get a file reference for {self}")
        location = self.location
        reader = await self.get_reader(config)
        file_ref = await (
            FileReference.write_builder()
            .with_data_chunks(data)
            .with_parity_chunks(parity)
            .with_chunk_size(1 << chunk_size_log2)
            .write(reader)
        )
        bytes_seen = 0
        from chunky_bits_tpu.file.location import Range

        last_chunk = None
        for part in file_ref.parts:
            for chunk in part.data:
                chunk.locations.append(location.with_range(
                    Range(bytes_seen, part.chunksize, False)))
                bytes_seen += part.chunksize
                last_chunk = chunk
        if last_chunk is not None:
            rng = last_chunk.locations[-1].range
            last_chunk.locations[-1] = last_chunk.locations[-1].with_range(
                Range(rng.start, rng.length, True))
        return file_ref

    async def migrate(self, config, destination: "ClusterLocation") -> None:
        import json

        if destination.kind == "cluster":
            cluster, profile = \
                await destination.get_cluster_with_profile(config)
            file_ref = await self.get_file_reference(
                config, profile.get_data_chunks(),
                profile.get_parity_chunks(), profile.chunk_size)
            await cluster.write_file_ref(destination.path, file_ref)
        elif destination.kind == "file_ref":
            file_ref = await self.get_file_reference(
                config,
                await config.get_default_data_chunks(),
                await config.get_default_parity_chunks(),
                await config.get_default_chunk_size())
            await destination.location.write(
                json.dumps(file_ref.to_obj(), indent=2).encode())
        else:
            raise ChunkyBitsError(f"Cannot migrate to {destination}")
