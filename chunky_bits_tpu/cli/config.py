"""User configuration: named clusters and defaults.

Mirrors src/bin/chunky-bits/config.rs: default path ``/etc/chunky-bits.yaml``
(missing file tolerated unless ``--config`` was given, :231-249); named
clusters inline or by-location (:65-70); per-cluster + global
``default_profile``; an async cluster cache (:54-56,77-111); default
d/p/chunk-size resolved through the default destination (:146-188); CLI-flag
overlay (:252-290).
"""

from __future__ import annotations

import asyncio
from typing import Optional

import yaml

from chunky_bits_tpu.cli.any_destination import AnyDestinationRef
from chunky_bits_tpu.cluster import Cluster, ClusterProfile, sized_int
from chunky_bits_tpu.errors import ChunkyBitsError, SerdeError
from chunky_bits_tpu.utils.yamlio import yaml_load

DEFAULT_CONFIG_PATH = "/etc/chunky-bits.yaml"
_KNOWN_FIELDS = {"clusters", "default_destination", "default_profile"}


class Config:
    def __init__(self, clusters: Optional[dict] = None,
                 default_destination: Optional[AnyDestinationRef] = None,
                 default_profile: Optional[str] = None):
        # clusters: name -> {"cluster": Cluster|Location-str,
        #                    "default_profile": Optional[str]}
        self.clusters = clusters or {}
        self.default_destination = default_destination or AnyDestinationRef()
        self.default_profile = default_profile
        self._cache: dict[str, Cluster] = {}
        self._cache_lock = asyncio.Lock()

    async def aclose(self) -> None:
        """Close every cached cluster's HTTP session for this loop (the
        reference's reqwest clients drop implicitly; aiohttp wants an
        explicit close or it warns at interpreter exit)."""
        async with self._cache_lock:
            clusters = list(self._cache.values())
        for cluster in clusters:
            await cluster.tunables.location_context().aclose()

    # ---- loading ----

    @classmethod
    def from_obj(cls, obj: dict) -> "Config":
        if not isinstance(obj, dict):
            raise SerdeError("config must be a mapping")
        unknown = set(obj) - _KNOWN_FIELDS
        if unknown:
            raise SerdeError(f"unknown config fields: {sorted(unknown)}")
        clusters = {}
        for name, spec in (obj.get("clusters") or {}).items():
            if not isinstance(spec, dict):
                raise SerdeError(f"cluster {name!r} must be a mapping")
            if "location" in spec:
                cluster = spec["location"]  # lazy: load on first use
            elif "inline" in spec:
                cluster = Cluster.from_obj(spec["inline"])
            else:
                raise SerdeError(
                    f"cluster {name!r} needs 'inline' or 'location'")
            clusters[name] = {
                "cluster": cluster,
                "default_profile": spec.get("default_profile"),
            }
        return cls(
            clusters=clusters,
            default_destination=AnyDestinationRef.from_obj(
                obj.get("default_destination")),
            default_profile=obj.get("default_profile"),
        )

    @classmethod
    async def load(cls, path: Optional[str] = None) -> "Config":
        target = path or DEFAULT_CONFIG_PATH

        def _read() -> bytes:
            with open(target, "rb") as f:
                return f.read()

        data = await asyncio.to_thread(_read)
        try:
            obj = yaml_load(data)
        except yaml.YAMLError as err:
            raise SerdeError(f"invalid config {target}: {err}") from err
        return cls.from_obj(obj or {})

    @classmethod
    async def load_or_default(cls, path: Optional[str] = None,
                              chunk_size: Optional[int] = None,
                              data_chunks: Optional[int] = None,
                              parity_chunks: Optional[int] = None
                              ) -> "Config":
        """Load, tolerating a missing default config; then overlay CLI
        flags over the default destination's geometry."""
        if path is not None:
            try:
                config = await cls.load(path)
            except OSError as err:
                raise ChunkyBitsError(
                    f"cannot read config {path}: {err}") from err
        else:
            try:
                config = await cls.load(None)
            except (OSError, SerdeError):
                config = cls()
        dest = config.default_destination
        if dest.type in ("void", "locations"):
            if chunk_size is not None:
                dest.chunk_size = sized_int.chunk_size(chunk_size)
            if data_chunks is not None:
                dest.data = sized_int.data_chunk_count(data_chunks)
            if parity_chunks is not None:
                dest.parity = sized_int.parity_chunk_count(parity_chunks)
        return config

    def to_obj(self) -> dict:
        clusters = {}
        for name, spec in self.clusters.items():
            cluster = spec["cluster"]
            if isinstance(cluster, Cluster):
                entry: dict = {"inline": cluster.to_obj()}
            else:
                entry = {"location": str(cluster)}
            if spec.get("default_profile"):
                entry["default_profile"] = spec["default_profile"]
            clusters[name] = entry
        return {
            "clusters": clusters,
            "default_destination": self.default_destination.to_obj(),
            "default_profile": self.default_profile,
        }

    # ---- cluster resolution (config.rs:77-111) ----

    async def get_cluster(self, target: str) -> Cluster:
        async with self._cache_lock:
            if target in self._cache:
                return self._cache[target]
        is_local_name = all(
            c in "_-" or c.isascii() and c.isalnum() for c in target
        )
        if is_local_name:
            spec = self.clusters.get(target)
            if spec is None:
                raise ChunkyBitsError(
                    f"Cluster not defined in configuration: {target}")
            cluster = spec["cluster"]
            if not isinstance(cluster, Cluster):
                cluster = await Cluster.from_location(str(cluster))
        else:
            cluster = await Cluster.from_location(target)
        async with self._cache_lock:
            self._cache[target] = cluster
        return cluster

    def get_profile(self, target: str) -> Optional[str]:
        spec = self.clusters.get(target)
        if spec is not None and spec.get("default_profile"):
            return spec["default_profile"]
        return self.default_profile

    # ---- defaults through the destination ref (config.rs:120-188) ----

    async def get_default_destination(self):
        destination = await self.default_destination.get_destination(self)
        if self.default_destination.is_void():
            import sys

            print("Warning: Using void destination", file=sys.stderr)
        return destination

    async def _default_cluster_profile(self) -> ClusterProfile:
        ref = self.default_destination
        cluster = await self.get_cluster(ref.cluster)
        name = ref.profile if ref.profile is not None \
            else self.get_profile(ref.cluster)
        profile = cluster.get_profile(name)
        if profile is None:
            profile = cluster.get_profile(None)
        return profile

    async def get_default_chunk_size(self) -> int:
        if self.default_destination.type == "cluster":
            return (await self._default_cluster_profile()).chunk_size
        return self.default_destination.chunk_size

    async def get_default_data_chunks(self) -> int:
        if self.default_destination.type == "cluster":
            return (await self._default_cluster_profile()).data_chunks
        return self.default_destination.data

    async def get_default_parity_chunks(self) -> int:
        if self.default_destination.type == "cluster":
            return (await self._default_cluster_profile()).parity_chunks
        return self.default_destination.parity
