"""Config-declared default destination.

Mirrors src/bin/chunky-bits/any_destination.rs:33-156: ``type: cluster``
(named cluster + profile), ``type: locations`` (weighted location list with
inline d/p/chunk-size), or ``type: void`` (the default — discard).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from chunky_bits_tpu.cluster import sized_int
from chunky_bits_tpu.errors import ChunkyBitsError, SerdeError
from chunky_bits_tpu.file import (
    VoidDestination,
    WeightedLocation,
    WeightedLocationsDestination,
)


@dataclass
class AnyDestinationRef:
    type: str = "void"  # "cluster" | "locations" | "void"
    cluster: Optional[str] = None
    profile: Optional[str] = None
    data: int = sized_int.DATA_DEFAULT
    parity: int = sized_int.PARITY_DEFAULT
    chunk_size: int = sized_int.CHUNK_SIZE_DEFAULT
    locations: list[WeightedLocation] = field(default_factory=list)

    def is_void(self) -> bool:
        return self.type == "void"

    @classmethod
    def from_obj(cls, obj) -> "AnyDestinationRef":
        if obj is None:
            return cls()
        if not isinstance(obj, dict) or "type" not in obj:
            raise SerdeError("destination must be a mapping with 'type'")
        kind = obj["type"]
        if kind == "cluster":
            if "cluster" not in obj:
                raise SerdeError("cluster destination missing 'cluster'")
            return cls(type="cluster", cluster=obj["cluster"],
                       profile=obj.get("profile"))
        if kind in ("locations", "void"):
            out = cls(type=kind)
            if "data" in obj:
                out.data = sized_int.data_chunk_count(obj["data"])
            if "parity" in obj:
                out.parity = sized_int.parity_chunk_count(obj["parity"])
            if "chunk_size" in obj:
                out.chunk_size = sized_int.chunk_size(obj["chunk_size"])
            if kind == "locations":
                out.locations = [WeightedLocation.from_obj(o)
                                 for o in obj.get("locations", [])]
            return out
        raise SerdeError(f"unknown destination type {kind!r}")

    def to_obj(self) -> dict:
        if self.type == "cluster":
            return {"type": "cluster", "cluster": self.cluster,
                    "profile": self.profile}
        obj = {"type": self.type, "data": self.data,
               "parity": self.parity, "chunk_size": self.chunk_size}
        if self.type == "locations":
            obj["locations"] = [wl.to_obj() for wl in self.locations]
        return obj

    async def get_destination(self, config):
        if self.type == "cluster":
            cluster = await config.get_cluster(self.cluster)
            profile_name = self.profile
            if profile_name is None:
                profile_name = config.get_profile(self.cluster)
            profile = cluster.get_profile(profile_name)
            if profile is None:
                raise ChunkyBitsError(f"Profile not found: {profile_name}")
            return cluster.get_destination(profile)
        if self.type == "locations":
            return WeightedLocationsDestination(self.locations)
        return VoidDestination()
