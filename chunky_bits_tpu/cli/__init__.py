"""Coreutils-style CLI (the reference's src/bin/chunky-bits/)."""

from chunky_bits_tpu.cli.cluster_location import ClusterLocation  # noqa: F401
from chunky_bits_tpu.cli.config import Config  # noqa: F401
