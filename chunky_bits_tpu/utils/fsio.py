"""The storage plane's filesystem seam (implementation half) — the
disk twin of the clock seam (``utils/clock.py``).

Every durability-relevant filesystem op on the storage plane — the
write-mode ``open``, ``replace`` (atomic publication), ``unlink``,
``truncate``, ``makedirs``, ``fsync`` (file data barrier) and
``fsync_dir`` (directory-entry barrier) — resolves through the active
:class:`FsProvider` instead of calling ``os``/``open`` directly.  In
production nothing changes: the default provider delegates straight to
the OS primitives at the cost of one extra function call (measured
within noise on bench configs 2 and 10, BASELINE.md), and its
``open`` returns the plain builtin file object — no wrapper on the hot
path.  The crash-consistency harness (``chunky_bits_tpu/sim/crash.py``)
swaps in a :class:`RecordingFsProvider` to capture the exact op stream
of a mutation (slab append + journal commit, compaction, chunk and
metadata publication, repair's in-place rewrite) and deterministically
replays every prefix "crash at op k" into a cloned directory; tests
swap in a :class:`FaultyFsProvider` to script EIO/ENOSPC/short-write
and failed-fsync faults against the LIVE code paths.

**Why this module lives in utils/ and not file/:** the canonical seam
surface IS ``chunky_bits_tpu/file/fsio.py`` (it re-exports everything
here, and lint rule CB109 names the seam as the one sanctioned route
for direct durability ops in the storage-plane modules) — but the
``file/`` modules that adopt the seam must be importable without
triggering package ``__init__`` cycles, the same import-cycle hygiene
that keeps the clock implementation in ``utils/clock.py``.  This
module imports stdlib only.

**Thread-safety:** the storage plane calls these functions from event
loops AND host-pipeline / ``asyncio.to_thread`` workers (slab appends
hop off-loop).  The active-provider swap is a single attribute rebind
(GIL-atomic); :class:`RecordingFsProvider` guards its op list with a
lock so multi-threaded mutations record a coherent stream.

**The op model** (what the recorder captures, what the replayer
understands — see ``sim/crash.py`` for the crash-image semantics):

* handle ops — ``open`` (create/truncate/append flags), ``write``
  (payload bytes), ``flush`` (process buffer -> OS), ``fsync`` (OS ->
  platter: the *data* barrier), ``close``;
* name ops — ``replace``, ``unlink``, ``mkdir``, and ``fsync_dir``
  (the *directory-entry* barrier: without it a completed ``replace``
  is not power-loss durable — the satellite fix this seam exists to
  prove).
"""

from __future__ import annotations

import builtins
import errno as _errno
import os
import threading
from typing import IO, Any, NamedTuple, Optional

__all__ = [
    "FaultyFsProvider",
    "FsOp",
    "FsProvider",
    "RecordingFsProvider",
    "active",
    "fsync",
    "fsync_dir",
    "install",
    "makedirs",
    "open",
    "replace",
    "system_provider",
    "truncate",
    "unlink",
]


class FsProvider:
    """Direct passthrough to the OS: the zero-surprise default.  Each
    method is one extra call frame over the primitive it wraps;
    ``open`` returns the builtin file object itself so the hot write
    paths carry no wrapper."""

    def open(self, path: str, mode: str = "r", **kwargs: Any) -> IO[Any]:
        return builtins.open(path, mode, **kwargs)

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def unlink(self, path: str) -> None:
        os.unlink(path)

    def truncate(self, path: str, length: int) -> None:
        os.truncate(path, length)

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        os.makedirs(path, exist_ok=exist_ok)

    def fsync(self, f: IO[Any]) -> None:
        """Flush-then-fsync: the file *data* durability barrier.  A
        raised error here means the bytes may NOT be durable — callers
        must abort the publication they were about to make, never
        swallow it and publish anyway (failed-fsync poisoning;
        sim/crash.py scripts this exact fault)."""
        f.flush()
        os.fsync(f.fileno())

    def fsync_dir(self, path: str) -> None:
        """Directory-entry durability barrier: fsync the directory so
        a completed rename/create inside it survives power loss.  The
        storage plane runs this after metadata publication and the
        compaction journal swap (acknowledged-write durability); the
        per-chunk publication path deliberately does NOT (flush-only —
        file/slab.py's documented tradeoff)."""
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


class FsOp(NamedTuple):
    """One recorded durability op.  ``fid`` identifies the open handle
    (inode identity across renames — a write after a dropped rename
    must land on the inode it was issued against, not whatever the
    name points at in the crash image); name ops carry ``fid=-1``."""

    op: str          # open|write|flush|fsync|close|replace|unlink|
    #                  mkdir|fsync_dir|truncate
    path: str        # recording-root-relative posix path (dst for
    #                  replace)
    fid: int         # handle id for handle ops, -1 for name ops
    data: bytes      # write payload ('' otherwise)
    aux: str         # open: flags 'c'(reate)/'t'(runcate)/'a'(ppend);
    #                  replace: src relpath; truncate: str(length)


def _mode_flags(mode: str) -> tuple[bool, str]:
    """(is_write_mode, open-op aux flags) for a builtin-open mode."""
    write = any(c in mode for c in "wax+")
    flags = ""
    if any(c in mode for c in "wax"):
        flags += "c"
    if "w" in mode:
        flags += "t"
    if "a" in mode:
        flags += "a"
    return write, flags


class _RecordingFile:
    """Wraps a real file handle, mirroring writes/flushes into the
    recorder's op stream.  Reads/seeks/tells delegate untouched (the
    journal's torn-tail probe seeks and reads through its append
    handle).  Text-mode payloads are recorded encoded."""

    def __init__(self, real: IO[Any], provider: "RecordingFsProvider",
                 fid: int, rel: str) -> None:
        self._real = real
        self._provider = provider
        self._fid = fid
        self._rel = rel

    # ---- mirrored ops ----

    def write(self, data: Any) -> int:
        payload = data.encode("utf-8") if isinstance(data, str) \
            else bytes(data)
        n = self._real.write(data)
        self._provider.record(
            FsOp("write", self._rel, self._fid, payload, ""))
        return n

    def flush(self) -> None:
        self._real.flush()
        self._provider.record(
            FsOp("flush", self._rel, self._fid, b"", ""))

    def truncate(self, size: Optional[int] = None) -> int:
        n = self._real.truncate(size)
        self._provider.record(FsOp("truncate", self._rel, self._fid,
                                   b"", str(n)))
        return n

    def close(self) -> None:
        if not self._real.closed:
            self._real.close()
            self._provider.record(
                FsOp("close", self._rel, self._fid, b"", ""))

    # ---- delegation ----

    def read(self, *a: Any) -> Any:
        return self._real.read(*a)

    def seek(self, *a: Any) -> int:
        return self._real.seek(*a)

    def tell(self) -> int:
        return self._real.tell()

    def fileno(self) -> int:
        return self._real.fileno()

    def writable(self) -> bool:
        return self._real.writable()

    def readable(self) -> bool:
        return self._real.readable()

    @property
    def closed(self) -> bool:
        return self._real.closed

    @property
    def name(self) -> Any:
        return self._real.name

    def __enter__(self) -> "_RecordingFile":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


class RecordingFsProvider(FsProvider):
    """Captures the durability-op stream of every seam call under
    ``root``; ops outside ``root`` pass through unrecorded (a cluster
    mutation records one simulated "node" — the other destinations
    stay real, so a crash image rolls back exactly one failure
    domain).  Thread-safe: slab appends ride ``asyncio.to_thread``."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.ops: list[FsOp] = []
        self._lock = threading.Lock()
        self._next_fid = 0

    def _rel(self, path: str) -> Optional[str]:
        """Recording-root-relative posix path, or None when outside."""
        abspath = os.path.abspath(path)
        if abspath == self.root:
            return "."
        prefix = self.root + os.sep
        if not abspath.startswith(prefix):
            return None
        return abspath[len(prefix):].replace(os.sep, "/")

    def record(self, op: FsOp) -> None:
        with self._lock:
            self.ops.append(op)

    # ---- provider surface ----

    def open(self, path: str, mode: str = "r", **kwargs: Any) -> IO[Any]:
        real = builtins.open(path, mode, **kwargs)
        rel = self._rel(path)
        write, flags = _mode_flags(mode)
        if rel is None or not write:
            return real
        with self._lock:
            fid = self._next_fid
            self._next_fid += 1
            self.ops.append(FsOp("open", rel, fid, b"", flags))
        return _RecordingFile(real, self, fid, rel)  # type: ignore[return-value]

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)
        rel_src, rel_dst = self._rel(src), self._rel(dst)
        if rel_src is not None and rel_dst is not None:
            self.record(FsOp("replace", rel_dst, -1, b"", rel_src))

    def unlink(self, path: str) -> None:
        os.unlink(path)
        rel = self._rel(path)
        if rel is not None:
            self.record(FsOp("unlink", rel, -1, b"", ""))

    def truncate(self, path: str, length: int) -> None:
        os.truncate(path, length)
        rel = self._rel(path)
        if rel is not None:
            self.record(FsOp("truncate", rel, -1, b"", str(length)))

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        os.makedirs(path, exist_ok=exist_ok)
        rel = self._rel(path)
        if rel is not None:
            self.record(FsOp("mkdir", rel, -1, b"", ""))

    def fsync(self, f: IO[Any]) -> None:
        if isinstance(f, _RecordingFile):
            f.flush()  # records the flush half
            os.fsync(f.fileno())
            self.record(FsOp("fsync", f._rel, f._fid, b"", ""))
        else:
            super().fsync(f)

    def fsync_dir(self, path: str) -> None:
        super().fsync_dir(path)
        rel = self._rel(path)
        if rel is not None:
            self.record(FsOp("fsync_dir", rel, -1, b"", ""))


class _FaultyFile:
    """Wraps a real file so write/flush can be scripted to fail; the
    short-write fault lands a real partial tail first (the ENOSPC
    shape: some bytes reached the file, then the disk filled)."""

    def __init__(self, real: IO[Any], provider: "FaultyFsProvider",
                 path: str) -> None:
        self._real = real
        self._provider = provider
        self._path = path

    def write(self, data: Any) -> int:
        # a firing short-write fault lands the partial tail on the real
        # file inside check(), then raises — so reaching the next line
        # means no fault fired
        self._provider.check("write", self._path, payload=data,
                             real=self._real)
        return self._real.write(data)

    def flush(self) -> None:
        self._provider.check("flush", self._path)
        self._real.flush()

    def truncate(self, size: Optional[int] = None) -> int:
        self._provider.check("truncate", self._path)
        return self._real.truncate(size)

    def close(self) -> None:
        self._real.close()

    def read(self, *a: Any) -> Any:
        return self._real.read(*a)

    def seek(self, *a: Any) -> int:
        return self._real.seek(*a)

    def tell(self) -> int:
        return self._real.tell()

    def fileno(self) -> int:
        return self._real.fileno()

    @property
    def closed(self) -> bool:
        return self._real.closed

    def __enter__(self) -> "_FaultyFile":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


class FaultyFsProvider(FsProvider):
    """Scripted disk faults against LIVE code paths: the ``fail_op``-th
    matching op raises ``OSError(errno_code)``; a ``short_bytes`` write
    fault lands that many real bytes first, then raises — the
    ENOSPC-mid-write shape the slab append must truncate away.  A
    failed ``fsync`` raising here is the poisoning probe: the caller
    must abort its publication, never report success."""

    def __init__(self, fail_op: str, *, path_suffix: str = "",
                 errno_code: int = _errno.EIO, skip: int = 0,
                 short_bytes: Optional[int] = None) -> None:
        self.fail_op = fail_op
        self.path_suffix = path_suffix
        self.errno_code = errno_code
        self.skip = skip
        self.short_bytes = short_bytes
        self.fired = 0

    def check(self, op: str, path: str, payload: Any = None,
              real: Optional[IO[Any]] = None) -> None:
        """Raise the scripted fault when (op, path) matches — for a
        short write, landing the partial tail on ``real`` first (the
        ENOSPC-mid-write shape); returns normally only when no fault
        fires."""
        if op != self.fail_op:
            return
        if self.path_suffix and not str(path).endswith(self.path_suffix):
            return
        if self.skip > 0:
            self.skip -= 1
            return
        self.fired += 1
        if self.short_bytes is not None and op == "write" \
                and real is not None and payload is not None:
            raw = payload.encode("utf-8") if isinstance(payload, str) \
                else bytes(payload)
            real.write(raw[:self.short_bytes])
            real.flush()
        raise OSError(self.errno_code,
                      f"injected {os.strerror(self.errno_code)} on "
                      f"{op} {path}")

    def open(self, path: str, mode: str = "r", **kwargs: Any) -> IO[Any]:
        self.check("open", path)
        real = builtins.open(path, mode, **kwargs)
        write, _flags = _mode_flags(mode)
        if not write:
            return real
        return _FaultyFile(real, self, path)  # type: ignore[return-value]

    def replace(self, src: str, dst: str) -> None:
        self.check("replace", dst)
        os.replace(src, dst)

    def unlink(self, path: str) -> None:
        self.check("unlink", path)
        os.unlink(path)

    def truncate(self, path: str, length: int) -> None:
        self.check("truncate", path)
        os.truncate(path, length)

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        self.check("mkdir", path)
        os.makedirs(path, exist_ok=exist_ok)

    def fsync(self, f: IO[Any]) -> None:
        name = getattr(f, "name", "")
        if isinstance(f, _FaultyFile):
            name = f._path
        self.check("fsync", str(name))
        f.flush()
        os.fsync(f.fileno())

    def fsync_dir(self, path: str) -> None:
        self.check("fsync_dir", path)
        super().fsync_dir(path)


_SYSTEM = FsProvider()
_ACTIVE: FsProvider = _SYSTEM


def system_provider() -> FsProvider:
    """The always-direct passthrough provider."""
    return _SYSTEM


def active() -> FsProvider:
    """The currently installed provider (passthrough by default)."""
    return _ACTIVE


def install(provider: Optional[FsProvider]) -> FsProvider:
    """Swap the process-wide active provider; returns the previous one
    so callers can restore it (``install(None)`` restores the
    passthrough).  The crash harness brackets every recorded mutation
    with ``prev = install(RecordingFsProvider(root))`` /
    ``install(prev)`` — production code never calls this."""
    global _ACTIVE
    previous = _ACTIVE
    _ACTIVE = provider if provider is not None else _SYSTEM
    return previous


def open(path: str, mode: str = "r", **kwargs: Any) -> IO[Any]:
    """Seam-routed ``open`` — THE open every storage-plane write path
    uses (lint rule CB109 flags direct write-mode ``open`` calls in
    those modules).  Read-mode opens may use it too; only write modes
    are recorded."""
    return _ACTIVE.open(path, mode, **kwargs)


def replace(src: str, dst: str) -> None:
    """Seam-routed ``os.replace`` (atomic publication rename)."""
    _ACTIVE.replace(src, dst)


def unlink(path: str) -> None:
    """Seam-routed ``os.unlink``."""
    _ACTIVE.unlink(path)


def truncate(path: str, length: int) -> None:
    """Seam-routed ``os.truncate`` (the short-write tail reclaim)."""
    _ACTIVE.truncate(path, length)


def makedirs(path: str, exist_ok: bool = True) -> None:
    """Seam-routed ``os.makedirs``."""
    _ACTIVE.makedirs(path, exist_ok=exist_ok)


def fsync(f: IO[Any]) -> None:
    """Seam-routed flush+fsync data barrier; see
    :meth:`FsProvider.fsync` for the abort-on-failure contract."""
    _ACTIVE.fsync(f)


def fsync_dir(path: str) -> None:
    """Seam-routed directory-entry barrier; see
    :meth:`FsProvider.fsync_dir`."""
    _ACTIVE.fsync_dir(path)
