"""The process-wide clock seam (implementation half).

Every time-sensitive policy in the cluster/file planes — EWMA decay and
breaker cooldowns (cluster/health.py), the scrub token bucket and pass
interval (cluster/scrub.py), hedge/straggler delays (file/file_part.py),
retry jitter backoff and health latency samples (file/location.py,
cluster/destination.py), profiler I/O spans (file/profiler.py) — reads
time through :func:`monotonic` / :func:`sleep` instead of
``time.monotonic`` / ``asyncio.sleep`` directly.  In production nothing
changes: the default :class:`Clock` delegates straight to the system
primitives at the cost of one extra function call (measured within
noise on bench configs 2 and 8, BASELINE.md).  The deterministic
cluster simulator (``chunky_bits_tpu/sim``) swaps in a
:class:`VirtualClock` bound to its virtual-time event loop, so a
60-minute scrub pass runs in milliseconds of wall time with every
latency sample, cooldown, and budget accrual agreeing on the same
virtual timebase.

**Why this module lives in utils/ and not cluster/:** the canonical
seam surface IS ``chunky_bits_tpu/cluster/clock.py`` (it re-exports
everything here, and lint rule CB108 names it as the one sanctioned
home for direct time reads) — but ``file/`` modules must be importable
without triggering the ``cluster`` package ``__init__`` (which imports
``cluster.py`` -> ``destination.py`` -> ``file.location`` and would
cycle), the same import-cycle hygiene that keeps
``TRANSIENT_HTTP_STATUSES`` in ``errors.py``.  This module imports
stdlib only.

**Thread-safety:** :func:`monotonic` is called from event-loop
callbacks AND host-pipeline worker threads (the health scoreboard
records completions from both).  The active-clock swap is a single
attribute rebind (GIL-atomic); ``Clock.monotonic`` and
``VirtualClock.monotonic`` are both safe from any thread
(``time.monotonic`` trivially; the virtual loop's ``time()`` reads one
float).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

__all__ = [
    "Clock",
    "VirtualClock",
    "active",
    "install",
    "monotonic",
    "sleep",
    "system_clock",
]


class Clock:
    """The system clock: the zero-surprise default.  ``monotonic`` is
    ``time.monotonic``; ``sleep`` is ``asyncio.sleep`` on the running
    loop.  Subclasses (the simulator's :class:`VirtualClock`) override
    ``monotonic`` to read a virtual timebase."""

    def monotonic(self) -> float:
        return time.monotonic()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(seconds)

    def call_later(self, loop: asyncio.AbstractEventLoop,
                   delay: float, callback, *args) -> asyncio.TimerHandle:
        """``loop.call_later`` adapter: timer scheduling goes through
        the loop either way (a virtual loop's timers ARE virtual), so
        this exists for seam completeness — callers that schedule
        timers by hand stay on the one clock surface."""
        return loop.call_later(delay, callback, *args)


class VirtualClock(Clock):
    """A clock slaved to a virtual-time event loop (``sim/loop.py``):
    ``monotonic()`` returns the loop's virtual ``time()`` from any
    thread, so durations measured across an await agree exactly with
    the loop's timer plane.  ``sleep`` stays ``asyncio.sleep`` — on a
    virtual loop the timer it arms IS virtual, and compression happens
    in the loop, not here."""

    def __init__(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop

    def monotonic(self) -> float:
        # AbstractEventLoop.time() on the sim loop reads one float
        # (virtual now) — safe cross-thread, never touches loop state
        return self._loop.time()


_SYSTEM = Clock()
_ACTIVE: Clock = _SYSTEM


def system_clock() -> Clock:
    """The always-real system clock (bench/profiling callers that must
    measure WALL time even inside a simulation use this explicitly)."""
    return _SYSTEM


def active() -> Clock:
    """The currently installed clock (the system clock by default)."""
    return _ACTIVE


def install(clock: Optional[Clock]) -> Clock:
    """Swap the process-wide active clock; returns the previous one so
    callers can restore it (``install(None)`` restores the system
    clock).  The simulator brackets every run with
    ``prev = install(VirtualClock(loop))`` / ``install(prev)`` —
    production code never calls this."""
    global _ACTIVE
    previous = _ACTIVE
    _ACTIVE = clock if clock is not None else _SYSTEM
    return previous


def monotonic() -> float:
    """Monotonic seconds on the active clock — THE read every
    cluster/file-plane duration, cooldown, and budget computation goes
    through (lint rule CB108 flags direct ``time.monotonic()`` reads
    in those planes)."""
    return _ACTIVE.monotonic()


async def sleep(seconds: float) -> None:
    """``asyncio.sleep`` on the active clock.  On the simulator's
    virtual loop the armed timer is virtual, so a 60 s scrub interval
    costs microseconds of wall time."""
    await _ACTIVE.sleep(seconds)
