"""Host-plane utilities: async byte streams, serde helpers."""
