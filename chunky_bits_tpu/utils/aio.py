"""Async byte-stream primitives for the host I/O plane.

The reference's concurrency substrate is tokio (AsyncRead/AsyncWrite +
blocking pool; reference: src/bin/chunky-bits/util.rs:14-59 for the
double-buffered copy).  Here the substrate is asyncio: filesystem work hops
to threads (the blocking-pool analogue), and byte streams are objects with
``async read(n)``.
"""

from __future__ import annotations

import asyncio
import io
import os
from typing import AsyncIterator, Optional, Protocol, runtime_checkable

from chunky_bits_tpu.utils import fsio as _fsio


def mmap_opted_out() -> bool:
    """True when ``CHUNKY_BITS_TPU_NO_MMAP`` is set to a truthy value
    (standard env-flag parsing — cluster/tunables.env_flag: unset, "",
    "0", "false", "no", "off" all mean the zero-copy mmap paths stay
    ON).  Read per call, at the moment each read path picks its
    strategy — the import is local because tunables sits above this
    module in the layering (tunables -> location -> aio)."""
    from chunky_bits_tpu.cluster.tunables import env_flag

    return env_flag("CHUNKY_BITS_TPU_NO_MMAP")


@runtime_checkable
class AsyncByteReader(Protocol):
    """Anything with ``async read(n) -> bytes-like`` (b'' at EOF).

    Contract across the module: ``read(-1)`` drains to EOF; ``read(n)``
    may return fewer than n bytes (never zero before EOF); the value is
    bytes-like — bytes, bytearray, or memoryview (the zero-copy read
    pipeline yields page-cache views) — so consumers must treat it as a
    buffer, not assume ``bytes`` methods."""

    async def read(self, n: int = -1) -> bytes:  # pragma: no cover
        ...


class BytesReader:
    """In-memory reader."""

    def __init__(self, data: bytes):
        self._buf = io.BytesIO(data)

    async def read(self, n: int = -1) -> bytes:
        return self._buf.read(n)

    async def readinto(self, mem: memoryview) -> int:
        return self._buf.readinto(mem)


async def open_in_thread(opener, closer):
    """``asyncio.to_thread(opener)`` with a cancellation guarantee: the
    opened resource never leaks.  ``to_thread`` alone has a window —
    cancel the awaiting task while the thread is mid-``open()`` and the
    handle it returns belongs to nobody, surfacing later as a GC-time
    ResourceWarning (scrub rolling restarts and hedge losers cancel
    reads exactly there; tests/test_chaos.py caught it).  The open runs
    shielded; if the awaiting task is cancelled anyway, ``closer``
    reaps the orphaned result the moment the thread finishes.  Opener
    errors propagate unchanged (a failed open returns nothing to
    close — openers must release partial state themselves)."""
    t = asyncio.ensure_future(asyncio.to_thread(opener))
    try:
        return await asyncio.shield(t)
    except asyncio.CancelledError:
        def _reap(task: "asyncio.Task") -> None:
            if task.cancelled() or task.exception() is not None:
                return  # retrieving the exception also silences asyncio
            try:
                closer(task.result())
            except Exception:  # lint: broad-except-ok reaping an orphan
                pass  # nobody is left to hear about a failed close
        if t.done():
            _reap(t)
        else:
            t.add_done_callback(_reap)
        raise


class FileReader:
    """Thread-offloaded file reader (the spawn_blocking analogue).

    Regular files additionally expose ``view_parts``: zero-copy
    page-cache views for the ingest staging path (writer.py), so a local
    ``cp`` source skips the read() memcpy entirely — the erasure coder
    and the shard hasher consume the mapped bytes in place."""

    _NO_MAP = object()  # sentinel: mapping attempted and unavailable

    def __init__(self, path: str, offset: int = 0,
                 fileobj: Optional[io.BufferedReader] = None):
        self._path = path
        self._f = fileobj
        self._offset = offset
        self._mm = None  # lazy mmap; _NO_MAP when the source can't map

    async def _ensure(self) -> io.BufferedReader:
        if self._f is None:
            def _open() -> io.BufferedReader:
                f = open(self._path, "rb")
                try:
                    if self._offset:
                        f.seek(self._offset)
                except BaseException:
                    f.close()
                    raise
                return f

            self._f = await open_in_thread(_open, lambda f: f.close())
        return self._f

    async def read(self, n: int = -1) -> bytes:
        f = await self._ensure()
        return await asyncio.to_thread(f.read, n)

    async def readinto(self, mem: memoryview) -> int:
        f = await self._ensure()
        return await asyncio.to_thread(f.readinto, mem)

    def _view_parts_sync(self, f, part_bytes: int, max_parts: int):
        if self._mm is None:
            import mmap

            if mmap_opted_out():
                # opt-out for sources that may be truncated concurrently
                # (see view_parts docstring)
                self._mm = self._NO_MAP
                return None
            try:
                self._mm = mmap.mmap(f.fileno(), 0,
                                     access=mmap.ACCESS_READ)
            except (ValueError, OSError, io.UnsupportedOperation,
                    AttributeError):
                # empty file, pipe/char device, or no fileno
                self._mm = self._NO_MAP
        if self._mm is self._NO_MAP:
            return None
        pos = f.tell()
        k = min(max_parts, (len(self._mm) - pos) // part_bytes)
        if k <= 0:
            return None
        f.seek(pos + k * part_bytes)
        return memoryview(self._mm)[pos:pos + k * part_bytes]

    async def view_parts(self, part_bytes: int,
                         max_parts: int) -> Optional[memoryview]:
        """Zero-copy staging view of the next k = min(``max_parts``,
        full parts remaining) * ``part_bytes`` bytes, advancing the
        stream position past them; ``None`` when no full part remains
        (tail bytes flow through read()/readinto()) or the source isn't
        mappable.  The view aliases the page cache via a lazily-created
        private read-only mmap and stays valid for the reader's
        lifetime (numpy consumers hold a buffer reference, so even a
        GC'd reader keeps the pages alive).

        Caveat (the usual mmap tradeoff, same as git's pack access): if
        another process truncates the file mid-ingest, touching a mapped
        page past the new EOF raises SIGBUS instead of the copy path's
        graceful short read.  Sources subject to concurrent truncation
        should set ``CHUNKY_BITS_TPU_NO_MMAP=1``, which keeps every part
        on the readinto path."""
        f = await self._ensure()
        return await asyncio.to_thread(
            self._view_parts_sync, f, part_bytes, max_parts)

    async def close(self) -> None:
        if self._mm is not None and self._mm is not self._NO_MAP:
            try:
                self._mm.close()
            except BufferError:
                pass  # exported views outlive us; GC reclaims the map
            self._mm = None
        if self._f is not None:
            await asyncio.to_thread(self._f.close)
            self._f = None


async def close_reader(reader) -> None:
    """Close a reader if it supports closing (releases pooled HTTP
    connections for consumers that stop before EOF)."""
    close = getattr(reader, "close", None)
    if close is not None:
        result = close()
        if hasattr(result, "__await__"):
            # lint: unbounded-deadline-ok reader close releases local
            # fds / returns pooled connections — no network round-trip;
            # bounding it would strand the resource it exists to free
            await result


class CountingReader:
    """Pass-through reader that counts bytes consumed (``.total``); used
    to profile partial progress of failed streaming writes and to enforce
    ingest byte limits.  Ownership of the base reader stays with the
    caller (no close).  With ``max_bytes`` set, a read pushing the count
    past the limit raises ``exc_factory()``."""

    def __init__(self, base, max_bytes=None, exc_factory=None):
        self._base = base
        self._max_bytes = max_bytes
        self._exc_factory = exc_factory or (
            lambda: ValueError("byte limit exceeded"))
        self.total = 0

    async def read(self, n: int = -1) -> bytes:
        data = await self._base.read(n)
        self.total += len(data)
        if self._max_bytes is not None and self.total > self._max_bytes:
            raise self._exc_factory()
        return data


async def gather_or_cancel(awaitables):
    """``asyncio.gather`` with fail-fast cleanup: on the first error (or
    outer cancellation) cancel the sibling tasks and await them, so no
    task keeps running in the background with its exception never
    retrieved.  Accepts coroutines or tasks; returns results in order."""
    tasks = [asyncio.ensure_future(a) for a in awaitables]
    try:
        return await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise


class TakeReader:
    """Limit an underlying reader to ``length`` bytes (tokio's ``take``).
    Closes the inner reader once the limit is reached, since the consumer
    will never drive it to EOF."""

    def __init__(self, inner: AsyncByteReader, length: int):
        self._inner = inner
        self._remaining = length

    async def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if n < 0 or n > self._remaining:
            n = self._remaining
        data = await self._inner.read(n)
        self._remaining -= len(data)
        if self._remaining <= 0 or not data:
            await close_reader(self._inner)
        return data

    async def close(self) -> None:
        await close_reader(self._inner)


class ZeroExtendReader:
    """After EOF on the inner reader, keep yielding zeros up to ``length``
    total bytes (the reference's ``chain(repeat(0)).take(len)`` —
    src/file/location.rs:128)."""

    def __init__(self, inner: AsyncByteReader, length: int):
        self._inner = inner
        self._remaining = length
        self._eof = False

    async def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if n < 0 or n > self._remaining:
            n = self._remaining
        if not self._eof:
            data = await self._inner.read(n)
            if data:
                self._remaining -= len(data)
                if self._remaining <= 0:
                    await close_reader(self._inner)
                return data
            self._eof = True
            await close_reader(self._inner)
        out = b"\0" * n
        self._remaining -= n
        return out

    async def close(self) -> None:
        await close_reader(self._inner)


class IterReader:
    """Adapt an async iterator of byte chunks into a reader.

    Chunks may be any bytes-like object (the read pipeline yields
    zero-copy page-cache views); a chunk that satisfies a read(n) whole
    is passed through uncopied, so the dominant cat/gateway path moves
    buffers from storage to the consumer with no accumulation copy.
    read(n) may return fewer than ``n`` bytes (but never zero before
    EOF); read(-1) drains to EOF and returns joined bytes — the
    module-wide slurp contract."""

    def __init__(self, it: AsyncIterator[bytes]):
        self._it = it
        self._pending = b""
        self._eof = False

    async def read(self, n: int = -1) -> bytes:
        if n < 0:
            parts = [self._pending] if self._pending else []
            self._pending = b""
            while not self._eof:
                try:
                    parts.append(await self._it.__anext__())
                except StopAsyncIteration:
                    self._eof = True
            return b"".join(parts)
        if self._pending:
            if len(self._pending) <= n:  # n < 0 already drained above
                out, self._pending = self._pending, b""
            else:
                out, self._pending = self._pending[:n], self._pending[n:]
            return out
        if self._eof:
            return b""
        try:
            chunk = await self._it.__anext__()
        except StopAsyncIteration:
            self._eof = True
            return b""
        if len(chunk) <= n:
            return chunk  # pass through, no copy
        view = memoryview(chunk)
        self._pending = view[n:]
        return view[:n]


async def read_exact_into(reader: AsyncByteReader, mem: memoryview) -> int:
    """Fill ``mem`` until full or EOF; returns bytes filled.

    The reference's read-exact-but-handle-EOF loop
    (src/file/writer.rs:175-193), zero-extra-copy: a reader exposing
    ``async readinto(mem) -> int`` lands bytes directly in the caller's
    buffer (the writer's staging block); otherwise each ``read()`` chunk
    is copied straight into position — one pass either way, where a
    read-then-join shape would cost a join pass plus the caller's
    restage pass."""
    n = len(mem)
    got = 0
    readinto = getattr(reader, "readinto", None)
    if readinto is not None:
        while got < n:
            filled = await readinto(mem[got:])
            if not filled:
                break
            got += filled
        return got
    while got < n:
        data = await reader.read(n - got)
        if not data:
            break
        mem[got:got + len(data)] = data
        got += len(data)
    return got


async def copy_reader_to_file(reader: AsyncByteReader, path: str,
                              chunk: int = 1 << 20) -> int:
    """Streaming copy with thread-offloaded writes, double-buffered: the
    write of block N overlaps the read of block N+1 (the reference's
    io_copy overlap, src/bin/chunky-bits/util.rs:14-59, without the
    unsafe 'static transmutes).  Returns bytes copied."""
    total = 0
    # seam-routed open: streaming chunk publication must be recordable
    # by the crash harness just like the whole-buffer path
    f = await asyncio.to_thread(_fsio.open, path, "wb")
    pending: Optional[asyncio.Task] = None
    try:
        while True:
            data = await reader.read(chunk)
            if pending is not None:
                await pending
                pending = None
            if not data:
                break
            # lint: task-custody-ok awaited at the loop head or gathered
            # in the finally; the dataflow cannot correlate the
            # `pending is not None` guard with this assignment
            pending = asyncio.ensure_future(
                asyncio.to_thread(f.write, data))
            total += len(data)
        await asyncio.to_thread(f.flush)
    finally:
        if pending is not None:
            await asyncio.gather(pending, return_exceptions=True)
        await asyncio.to_thread(f.close)
    return total


async def copy_reader_to_writer(reader: AsyncByteReader, write,
                                chunk: int = 1 << 20) -> int:
    """Copy to an ``async write(bytes)`` callable; the io_copy analogue
    (reference: src/bin/chunky-bits/util.rs:14-59) — double buffering comes
    from the event loop interleaving read and write tasks."""
    total = 0
    pending: Optional[asyncio.Task] = None
    try:
        while True:
            data = await reader.read(chunk)
            if pending is not None:
                await pending
                pending = None
            if not data:
                break
            # lint: task-custody-ok awaited at the loop head or in the
            # finally; the dataflow cannot correlate the
            # `pending is not None` guard with this assignment
            pending = asyncio.ensure_future(write(data))
            total += len(data)
    finally:
        if pending is not None:
            await pending
    return total


def fs_path_join(base: str, name: str) -> str:
    return os.path.join(base, name)
