"""Virtual-mesh environment provisioning.

Single source of truth for the recipe that lets multi-device sharding code
run on hosts with fewer (or zero) real TPU chips: drop the axon tunnel
pinning, force the CPU platform, and ask XLA for an ``n``-device virtual
host mesh.  Used by ``tests/conftest.py`` (pytest) and
``__graft_entry__.dryrun_multichip`` (the driver's multichip check), which
must never drift apart.

Must stay importable without jax, and the target mapping must be populated
before the first jax import in the affected process.
"""

import re

__all__ = ["provision_virtual_mesh"]


def provision_virtual_mesh(environ, n_devices: int) -> None:
    """Mutate ``environ`` (any mutable mapping, e.g. ``os.environ`` or a
    ``dict`` copy destined for a subprocess) to provision an
    ``n_devices``-wide virtual CPU mesh.

    Any pre-existing ``--xla_force_host_platform_device_count`` flag is
    replaced, not kept, so a stale smaller count cannot starve the mesh.
    """
    # The axon sitecustomize registers the tunneled-TPU PJRT plugin and
    # pins JAX_PLATFORMS=axon whenever this is set.
    environ.pop("PALLAS_AXON_POOL_IPS", None)
    environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\S+",
        "",
        environ.get("XLA_FLAGS", ""),
    )
    environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
