"""YAML via libyaml's C loader/dumper when available.

Every metadata object (cluster definitions, file references — including
the reference's non-strict JSON formats, which parse through YAML as a
superset, src/cluster/metadata.rs:364-414) crosses this boundary.  The
pure-Python scanner costs ~1 s just to parse a 1 GiB object's file
reference (~90 parts x 5 chunks); the C loader is ~10x faster with
identical semantics.  Falls back to the pure-Python classes when PyYAML
was built without libyaml.
"""

from __future__ import annotations

import yaml

_LOADER = getattr(yaml, "CSafeLoader", yaml.SafeLoader)
_DUMPER = getattr(yaml, "CSafeDumper", yaml.SafeDumper)


def yaml_load(data):
    """``yaml.safe_load`` semantics, C-accelerated."""
    return yaml.load(data, Loader=_LOADER)


def yaml_dump(obj, stream=None, **kwargs):
    """``yaml.safe_dump`` semantics, C-accelerated.  Defaults match
    safe_dump (block style) so serialized metadata is byte-identical to
    the pure-Python emitter's."""
    kwargs.setdefault("default_flow_style", False)
    return yaml.dump(obj, stream, Dumper=_DUMPER, **kwargs)
