"""Shared multi-core host compute pipeline: ingest hashing + encode.

Every host-plane path used to run per-shard SHA-256 and per-stripe
GF(2^8) encode on one thread of the box (the ``native:N`` knob reached
the C++ codec but nothing above it).  This module is the one scheduler
they now share: a bounded, stage-aware executor running host compute on
``min(N, nproc)`` daemon worker threads, where N comes from an explicit
``HostPipeline(threads=N)``, the cluster's ``tunables.host_threads``,
or ``$CHUNKY_BITS_TPU_HOST_THREADS`` (read at first use).  The
memory-pass discipline follows *Accelerating XOR-based Erasure Coding
using Program Optimization Techniques* (arXiv:2108.02692): the round-4
fused encode+hash already touches each byte once per stripe; here that
per-stripe pass is scaled across cores instead of being restructured.

Slicing units (zero-copy by construction):

* **stripes** for the fused native encode+hash: each worker runs the
  cache-hot single pass over a contiguous stripe range, writing straight
  into its rows of the shared ``parity``/``digests`` outputs
  (``NativeBackend.encode_and_hash_into``, internal ``nthreads=1`` so
  total parallelism is the scheduler's worker count, honoring a
  ``native:N`` cap);
* **shard rows** for SHA-256 when stripes can't be sliced (a single
  stripe, or a non-fused backend): data rows hash on the workers while
  the stripe encode — a device dispatch for the jax/mesh backends —
  runs on the calling thread.  This subsumes the round-4 ingest-overlap
  pool (ops/backend.py's retired ``_ingest_hash_pool``).

Ordered completion is positional: every job writes only its own slice of
a preallocated output, so batch results assemble with no reorder step
and the writer's placement semantics (writer.rs:50-59 geometry, the
100 ms stagger chain) are untouched above this layer.

Invariants by construction (CLAUDE.md):

* workers are ``threading.Thread(daemon=True)`` and never required for
  interpreter exit (CB103);
* every queue put/get is bounded: workers poll ``get`` with a timeout
  and re-check shutdown, ``submit`` never blocks (a full queue runs the
  job on the caller — exactly the backpressure wanted), and the async
  path's blocking put is both off-loop and timeout-polled (CB101);
* a job's result-or-error is recorded in a ``finally`` before its
  waiters wake, so no waiter can hang on a completed job.

Byte identity: slicing never changes the math — stripes are independent
in GF(2^8) and SHA-256 is per-row — pinned by tests/test_host_pipeline.py
fuzz across worker counts and backends.
"""

from __future__ import annotations

import asyncio
import os
import queue
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.obs import tracing as obs_tracing

#: worker queue poll: the bound on every blocking get/put — short enough
#: that shutdown is prompt, long enough to stay off the scheduler's hot
#: path (a parked worker wakes on the put, not the timeout)
_POLL_SECONDS = 0.5


def _sanitizer():
    """The active runtime concurrency sanitizer, or None.  Reached only
    through ``sys.modules`` so the sanitize-off path costs one dict
    lookup and never imports the instrumentation (the zero-overhead
    contract pinned by tests/test_sanitizer.py)."""
    mod = sys.modules.get("chunky_bits_tpu.analysis.sanitizer")
    return mod.active() if mod is not None else None


class _Job:
    """One unit of host compute: a zero-arg callable tagged with a stage
    name and a byte count for the per-stage counters.  A minimal future:
    the running thread records result-or-exception and fires callbacks
    exactly once; waiters block on the event (sync) or bridge to a loop
    future (async)."""

    __slots__ = ("stage", "fn", "nbytes", "result", "error",
                 "_event", "_callbacks", "_lock", "_started",
                 "trace", "submitted_at")

    def __init__(self, stage: str, fn: Callable[[], Any],
                 nbytes: int = 0) -> None:
        self.stage = stage
        self.fn = fn
        self.nbytes = nbytes
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()
        self._callbacks: list[Callable[["_Job"], None]] = []
        self._lock = threading.Lock()
        self._started = False
        # capture-at-submit: the contextvar trace of the SUBMITTING
        # thread (None when tracing is off or the submitter is itself a
        # worker) rides the job across the plane boundary so queue-wait
        # and execution spans land on the request that asked (obs/
        # tracing.py — one ContextVar.get when tracing is off)
        self.trace = obs_tracing.current()
        self.submitted_at = time.monotonic() if self.trace is not None \
            else 0.0

    def _claim(self) -> bool:
        """Atomically claim the right to run this job.  Shutdown races
        hand the same queued job to both a worker/drain and a caller-side
        rescue; exactly one claimant executes ``fn``."""
        with self._lock:
            if self._started:
                return False
            self._started = True
            return True

    def add_done_callback(self, cb: Callable[["_Job"], None]) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def _finish(self) -> None:
        with self._lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def join(self) -> None:
        """Wait for completion without raising.  The poll keeps the wait
        interruptible at interpreter shutdown; jobs always finish — the
        runner records result-or-error in a ``finally``."""
        if not self._event.is_set():
            # a blocking wait issued FROM a loop thread stalls every
            # request on that loop; the sanitizer records it (an
            # already-finished job — the inline small-job path — never
            # waits, so it is exempt by the is_set() guard)
            san = _sanitizer()
            if san is not None:
                san.handoff.check_sync_wait("_Job.join()")
        while not self._event.wait(_POLL_SECONDS):
            pass

    def wait(self) -> Any:
        """Result, re-raising the job's error verbatim."""
        self.join()
        if self.error is not None:
            raise self.error
        return self.result


def join_jobs(jobs: list[_Job]) -> None:
    """Wait for every job, then raise the first recorded error (after
    all finished, so shared output buffers are quiescent when the caller
    unwinds)."""
    for job in jobs:
        job.join()
    for job in jobs:
        if job.error is not None:
            raise job.error


def _ranges(n: int, k: int) -> list[tuple[int, int]]:
    """min(k, n) contiguous near-even [lo, hi) slices covering range(n)."""
    k = max(1, min(k, n))
    base, rem = divmod(n, k)
    out = []
    lo = 0
    for i in range(k):
        hi = lo + base + (1 if i < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


@dataclass(frozen=True)
class PipelineStageStats:
    stage: str
    jobs: int
    busy_s: float
    nbytes: int

    def to_obj(self) -> dict:
        return {"stage": self.stage, "jobs": self.jobs,
                "busy_s": round(self.busy_s, 6), "nbytes": self.nbytes}

    def __str__(self) -> str:
        return f"{self.stage}: {self.jobs}j/{self.busy_s:.3f}s/{self.nbytes}B"


@dataclass(frozen=True)
class PipelineStats:
    """Cumulative process counters (like the cache's): saturation is
    observable — per-stage busy seconds and bytes against worker idle
    seconds — not asserted."""

    threads: int
    idle_s: float
    stages: tuple[PipelineStageStats, ...]

    def to_obj(self) -> dict:
        return {"threads": self.threads,
                "idle_s": round(self.idle_s, 6),
                "stages": [s.to_obj() for s in self.stages]}

    def __str__(self) -> str:
        inner = " | ".join(str(s) for s in self.stages)
        if inner:
            inner += " | "
        return f"Pipeline<{self.threads}w {inner}idle {self.idle_s:.3f}s>"


class HostPipeline:
    """Bounded stage-aware scheduler for host compute (see module
    docstring).  ``threads=None`` resolves ``tunables.host_threads`` and
    clamps to ``min(N, nproc)``; an explicit count is honored exactly so
    scaling sweeps and tests can pin or oversubscribe deliberately.

    The sync entry points (``submit``/``encode_hash_sync``) are for
    worker/ordinary threads; the async ones (``run``/``encode_hash``)
    are loop-safe and never block the event loop.
    """

    #: async jobs at or below this byte count run inline on the awaiting
    #: coroutine instead of hopping to a worker: the hop latency exceeds
    #: the compute (BASELINE round 5 measured the same effect fusing the
    #: page-cache map with hash verification), and lockstep completion
    #: preserves the arrival clustering the downstream reconstruct/encode
    #: batchers coalesce on.  0-byte (unknown-size) jobs always offload.
    INLINE_NBYTES = 128 << 10

    def __init__(self, threads: Optional[int] = None, *,
                 queue_depth: Optional[int] = None,
                 name: str = "cb-host") -> None:
        nproc = os.cpu_count() or 1
        if threads is None:
            from chunky_bits_tpu.cluster.tunables import host_threads

            n = min(host_threads(default=0) or nproc, nproc)
        else:
            n = int(threads)
        self.threads = max(1, n)
        self._q: "queue.Queue[_Job]" = queue.Queue(
            maxsize=queue_depth or max(128, 8 * self.threads))
        self._shutdown = threading.Event()
        self._lock = threading.Lock()
        self._stages: dict[str, list] = {}  # stage -> [jobs, busy_s, bytes]
        self._idle_s = 0.0
        self._local = threading.local()
        # self-activate the runtime sanitizer when the flag asks for it
        # (read-at-first-use like host_threads); when off, nothing is
        # imported and no per-job instrumentation exists
        from chunky_bits_tpu.cluster.tunables import sanitize_enabled

        if sanitize_enabled():
            from chunky_bits_tpu.analysis.sanitizer import get_monitor

            get_monitor()
        # weakly self-register with the process metrics registry so a
        # /metrics scrape folds in per-stage busy/idle/bytes counters
        # (stats() is already lock-guarded and thread-safe)
        from chunky_bits_tpu.obs.metrics import get_registry

        get_registry().register_source("pipeline", self)
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-{i}")
            for i in range(self.threads)
        ]
        for w in self._workers:
            w.start()

    # ---- worker plumbing ----

    def _worker(self) -> None:
        self._local.on_worker = True
        while not self._shutdown.is_set():
            t0 = time.perf_counter()
            try:
                job = self._q.get(timeout=_POLL_SECONDS)
            except queue.Empty:
                continue
            dt = time.perf_counter() - t0
            with self._lock:
                self._idle_s += dt
            self._run_job(job)

    def _run_job(self, job: _Job) -> None:
        if not job._claim():
            return  # a racing claimant (shutdown rescue) already ran it
        # lint: clock-escape-ok real worker-thread stage profiling;
        # real thread work has zero virtual width under sim
        t0 = time.perf_counter()
        # lint: clock-escape-ok trace spans timestamp real host work
        t0_mono = time.monotonic() if job.trace is not None else 0.0
        try:
            job.result = job.fn()
        # lint: broad-except-ok delivered verbatim to the waiter via
        # job.error (wait/join_jobs re-raise); nothing is swallowed
        except BaseException as err:
            job.error = err
        finally:
            # lint: clock-escape-ok real worker-thread stage profiling
            dt = time.perf_counter() - t0
            with self._lock:
                st = self._stages.setdefault(job.stage, [0, 0.0, 0])
                st[0] += 1
                st[1] += dt
                st[2] += job.nbytes
            if job.trace is not None:
                # two spans per traced job: how long it WAITED (the
                # queue — saturation's signature) and how long it RAN
                job.trace.add(f"queue.{job.stage}", "host",
                              job.submitted_at,
                              max(t0_mono - job.submitted_at, 0.0))
                job.trace.add(f"pipeline.{job.stage}", "host", t0_mono,
                              dt, "ok" if job.error is None
                              else "error")
            job._finish()

    def _offer(self, job: _Job) -> None:
        """Queue a job without ever blocking: a full queue, shutdown, or
        a call from one of our own workers runs it inline on the caller
        (backpressure lands on the producer; worker reentrancy can never
        deadlock on queue capacity)."""
        if getattr(self._local, "on_worker", False) \
                or self._shutdown.is_set():
            self._run_job(job)
            return
        try:
            self._q.put_nowait(job)
        except queue.Full:
            self._run_job(job)
            return
        if self._shutdown.is_set():
            # closed between the check and the put: the queue may never
            # be serviced again — rescue inline (the claim makes this a
            # no-op if a worker or close()'s drain got there first)
            self._run_job(job)

    def _put_blocking(self, job: _Job) -> None:
        """Off-loop blocking put, timeout-polled against shutdown; the
        post-put shutdown re-check rescues a job stranded by a racing
        close() (claimed exactly once — see ``_Job._claim``)."""
        while not self._shutdown.is_set():
            try:
                self._q.put(job, timeout=_POLL_SECONDS)
            except queue.Full:
                continue
            if self._shutdown.is_set():
                self._run_job(job)
            return
        self._run_job(job)

    # ---- core API ----

    def submit(self, stage: str, fn: Callable[[], Any], *,
               nbytes: int = 0) -> _Job:
        """Queue one job (sync callers); returns its handle for
        ``wait()``.  Never blocks — see ``_offer``."""
        job = _Job(stage, fn, nbytes)
        self._offer(job)
        return job

    async def run(self, stage: str, fn: Callable[[], Any], *,
                  nbytes: int = 0) -> Any:
        """Run one sync job on the pipeline and await its result — the
        ``asyncio.to_thread`` analogue with stage accounting and the
        bounded shared worker set.  Small known-size jobs run inline
        (see ``INLINE_NBYTES``)."""
        job = _Job(stage, fn, nbytes)
        if 0 < nbytes <= self.INLINE_NBYTES:
            self._run_job(job)
            return job.wait()
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        san = _sanitizer()
        token = san.handoff.submit_token() if san is not None else None

        def bridge(j: _Job) -> None:
            def resolve() -> None:
                if token is not None and san is not None:
                    # the handoff contract: this completion must be
                    # delivered on the submitting loop's thread
                    san.handoff.check_resolve(token)
                if fut.cancelled():
                    return
                if j.error is not None:
                    fut.set_exception(j.error)
                else:
                    fut.set_result(j.result)

            try:
                loop.call_soon_threadsafe(resolve)
            except RuntimeError:
                pass  # loop already closed; no waiter left to wake

        job.add_done_callback(bridge)
        if self._shutdown.is_set():
            # closed pipeline: degrade to a plain thread hop, never hang
            # (stragglers on a cluster whose pinned pipeline a sweep just
            # closed still complete)
            await asyncio.to_thread(self._run_job, job)
        else:
            try:
                self._q.put_nowait(job)
            except queue.Full:
                await asyncio.to_thread(self._put_blocking, job)
            else:
                if self._shutdown.is_set():
                    # close() raced the put: rescue off-loop (no-op if a
                    # worker or the close drain claimed the job first)
                    await asyncio.to_thread(self._run_job, job)
        # lint: unbounded-await-ok resolved in every outcome: the runner
        # records result-or-error in a finally and fires the bridge
        # callback; jobs are pure host compute on daemon workers (no
        # PJRT park on this path)
        return await fut

    def _scatter(self, jobs: list[_Job]) -> None:
        """Fan jobs out to the workers and wait.  Every job goes through
        the queue — never the calling thread — so concurrent scatters
        (e.g. the writer's double-buffered sub-blocks) share exactly the
        scheduler's N workers instead of stacking extra caller threads on
        top: the thread-count knob stays honest.  Deadlock-free at any
        worker count: a call *from* a worker runs inline (``_offer``),
        and a full queue falls back to the caller.  Raises the first job
        error once every job finished (shared outputs quiescent)."""
        for job in jobs:
            self._offer(job)
        join_jobs(jobs)

    # ---- the ingest compute: sliced encode + hash ----

    def hash_rows_jobs(self, rows: np.ndarray, out: np.ndarray, *,
                       stage: str = "hash") -> list[_Job]:
        """Queue sliced row-hash jobs — ``out[..., 32] = sha256`` of each
        ``rows[..., S]`` row — WITHOUT waiting (callers overlap them with
        an in-flight device dispatch, then ``join_jobs``).  Both arrays
        must be C-contiguous: each slice writes through a flat view."""
        if not (rows.flags.c_contiguous and out.flags.c_contiguous):
            raise ErasureError("hash_rows_jobs needs contiguous arrays")
        flat = rows.reshape(-1, rows.shape[-1]) if rows.ndim != 2 else rows
        oflat = out.reshape(-1, 32) if out.ndim != 2 else out
        hasher = _row_hasher()
        jobs = []
        for lo, hi in _ranges(flat.shape[0], self.threads):
            jobs.append(_Job(
                stage,
                lambda lo=lo, hi=hi: hasher(flat[lo:hi], oflat[lo:hi], 1),
                (hi - lo) * flat.shape[-1]))
        for job in jobs:
            self._offer(job)
        return jobs

    def encode_hash_sync(self, coder: Any, stacked: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
        """``ErasureCoder.encode_hash_batch`` scaled across the workers:
        ``(parity[B, p, S], digests[B, d+p, 32])`` for ``stacked[B, d,
        S]``, byte-identical to the single-threaded path at every worker
        count.  Blocking — call from a worker thread (``encode_hash`` is
        the loop-safe wrapper).

        Slicing: stripes for a fused backend (native) with B >= 2; shard
        rows otherwise, with the stripe encode (a device dispatch for
        async backends) on the calling thread while data rows hash on
        the workers.  A ``native:N`` backend caps total parallelism at N
        — the cluster.yaml thread knob keeps meaning *total host
        threads*, not threads-per-worker.
        """
        stacked = np.ascontiguousarray(stacked, dtype=np.uint8)
        if stacked.ndim != 3 or stacked.shape[1] != coder.data:
            raise ErasureError(
                f"expected stacked [B, {coder.data}, S], "
                f"got {stacked.shape}")
        b, d, s = stacked.shape
        p = coder.parity
        if b == 0 or s == 0:
            # degenerate shapes: the coder's own handling (sha256(b"")
            # digests etc.) is already exact and instant
            return coder.encode_hash_batch(stacked)
        cap = getattr(coder.backend, "nthreads", 0) or 0
        k = self.threads if cap <= 0 else min(self.threads, cap)
        fused_into = getattr(coder.backend, "encode_and_hash_into", None)
        fused_whole = getattr(coder.backend, "encode_and_hash", None)
        if not getattr(coder, "supports_fused_ingest", True):
            # sub-symbol codes (pm-msr): the backend's fused passes
            # apply parity_rows at chunk granularity — wrong matrix
            # shape for a stripe-structured code.  Null BOTH so such
            # coders take the decomposed path below: it calls the
            # coder's own encode_batch (exact) with per-shard hashing
            # sliced across the workers, overlapping device dispatch
            # the same way — never a single-threaded whole-batch job
            fused_into = fused_whole = None

        if fused_into is None and fused_whole is not None:
            # a device backend with its own fused/overlapped ingest path
            # (jax: device parity + per-block host hashing — which
            # already rides this pipeline's workers internally): the
            # device does the slicing, so delegate whole and run the
            # host-side orchestration on the calling thread
            job = _Job("encode", lambda: coder.encode_hash_batch(stacked),
                       b * d * s)
            self._run_job(job)
            return job.wait()

        if fused_into is not None and (b >= 2 or k == 1):
            # per-stripe fused pass, k-way sliced, zero-copy outputs
            parity = np.empty((b, p, s), dtype=np.uint8)
            digests = np.empty((b, d + p, 32), dtype=np.uint8)
            jobs = [
                _Job("encode",
                     lambda lo=lo, hi=hi: fused_into(
                         coder.parity_rows, stacked[lo:hi],
                         parity[lo:hi], digests[lo:hi], 1),
                     (hi - lo) * d * s)
                for lo, hi in _ranges(b, k)
            ]
            self._scatter(jobs)
            return parity, digests

        # decomposed path: per-shard SHA sliced across the workers,
        # per-stripe encode either on the calling thread (async-dispatch
        # device backends: a device wait, not host compute — the round-4
        # ingest overlap on shared workers) or queued like any other
        # host job so the worker count stays the ceiling
        hasher = _row_hasher()
        flat = stacked.reshape(b * d, s)
        ddig = np.empty((b * d, 32), dtype=np.uint8)
        hash_jobs = [
            _Job("hash",
                 lambda lo=lo, hi=hi: hasher(flat[lo:hi], ddig[lo:hi], 1),
                 (hi - lo) * s)
            for lo, hi in _ranges(b * d, k)
        ]
        enc = _Job("encode", lambda: coder.encode_batch(stacked), b * d * s)
        if getattr(coder.backend, "async_dispatch", False):
            for job in hash_jobs:
                self._offer(job)
            self._run_job(enc)
            join_jobs(hash_jobs + [enc])
        else:
            self._scatter(hash_jobs + [enc])
        parity = np.ascontiguousarray(enc.result)
        data_digests = ddig.reshape(b, d, 32)
        if p == 0:
            return parity, data_digests
        pdig = np.empty((b * p, 32), dtype=np.uint8)
        pflat = parity.reshape(b * p, s)
        pjobs = [
            _Job("hash",
                 lambda lo=lo, hi=hi: hasher(pflat[lo:hi], pdig[lo:hi], 1),
                 (hi - lo) * s)
            for lo, hi in _ranges(b * p, k)
        ]
        self._scatter(pjobs)
        return parity, np.concatenate(
            [data_digests, pdig.reshape(b, p, 32)], axis=1)

    async def encode_hash(self, coder: Any, stacked: np.ndarray
                          ) -> tuple[np.ndarray, np.ndarray]:
        """Loop-safe ``encode_hash_sync``: the orchestrating hop runs the
        first slice itself (caller-runs-first), so it is working, not
        waiting, and W=1 degrades to exactly one busy thread."""
        return await asyncio.to_thread(self.encode_hash_sync, coder,
                                       stacked)

    # ---- observability / lifecycle ----

    def stats(self) -> PipelineStats:
        with self._lock:
            stages = tuple(
                PipelineStageStats(stage, st[0], st[1], st[2])
                for stage, st in sorted(self._stages.items()))
            return PipelineStats(self.threads, self._idle_s, stages)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the workers (scaling sweeps and tests; the process-shared
        pipeline lives for the process — its workers are daemon and cost
        nothing idle).  Already-queued jobs are drained inline so no
        waiter is abandoned."""
        self._shutdown.set()
        # lint: clock-escape-ok join deadline bounds REAL threads at
        # shutdown — virtual time cannot advance a parked OS thread
        deadline = time.monotonic() + timeout
        for w in self._workers:
            # lint: clock-escape-ok same real join deadline
            w.join(max(0.0, deadline - time.monotonic()))
        while True:
            try:
                job = self._q.get_nowait()
            except queue.Empty:
                break
            self._run_job(job)


def _row_hasher() -> Callable[[np.ndarray, np.ndarray, int], None]:
    from chunky_bits_tpu.ops.backend import row_hasher

    return row_hasher()


_SHARED: Optional[HostPipeline] = None
_SHARED_LOCK = threading.Lock()


def get_host_pipeline() -> HostPipeline:
    """The process-shared pipeline, built on first use with
    ``min($CHUNKY_BITS_TPU_HOST_THREADS or nproc, nproc)`` workers.
    Read-at-first-dispatch (CLAUDE.md): set the env var before the first
    encode/verify — the worker count is baked in for the process."""
    global _SHARED
    if _SHARED is None:
        with _SHARED_LOCK:
            if _SHARED is None:
                _SHARED = HostPipeline()
    return _SHARED
