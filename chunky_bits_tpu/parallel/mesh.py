"""Mesh-sharded erasure math: the multi-chip compute plane.

The reference scales with HTTP fan-out across storage nodes
(src/cluster/writer.rs); the TPU rebuild scales the *math* across chips with
``jax.sharding`` + ``shard_map`` over a 2D mesh:

* ``dp`` — the part-batch axis: each chip encodes its own slice of parts
  (data-parallel; parts are independent stripes, reference
  src/file/writer.rs:208 encodes them one-by-one on one core).
* ``sp`` — the shard-byte axis: GF(2^8) transforms are element-wise across
  bytes, so a single huge stripe can be split across chips the way sequence
  parallelism splits a long context — each chip transforms its byte range,
  no halo exchange needed.
* ``tp`` — the stripe axis (wide stripes, BASELINE.md config 5: d=20 p=6
  over a v5e-8): the *contraction* dimension of the GF matmul is split, so
  each chip holds d/tp data shards and computes a partial bit-plane
  product; full parity emerges from an integer ``psum`` over ``tp``
  followed by a single mod-2 — exact because GF(2^8) addition is XOR and
  popcounts add over chips.  This is the tensor-parallel decomposition of
  erasure coding: the per-chip working set shrinks with the stripe width,
  and the only cross-chip traffic is the [B, p*8, S] accumulator riding
  ICI (int16 on the pallas impl — exact, since the global popcount is at
  most d*8 <= 2048 — halving the psum bytes).

The bit-matrix is tiny (<=2048x2048 bits) and replicated (column-sharded
over ``tp`` in the wide-stripe path).  Collectives are the ``tp`` psum and a
checksum psum used to validate mesh execution; shards ride ICI, never DCN.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from chunky_bits_tpu.ops import gf256


def _shard_map():
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map

    return shard_map


def _smap(fn, *, mesh, in_specs, out_specs, impl="einsum"):
    """shard_map with the varying-axes checker disabled for the pallas
    impls: pallas_call's out_shape carries no vma annotation, so jax's
    check_vma rejects it; the out_specs here are explicit and the psum
    lowers to the same collective either way."""
    import inspect

    sm = _shard_map()
    kwargs = {}
    if impl != "einsum":
        params = inspect.signature(sm).parameters
        if "check_vma" in params:
            kwargs["check_vma"] = False
        elif "check_rep" in params:
            kwargs["check_rep"] = False
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kwargs)


def _make_mesh_2d(n_devices, first, first_name, second, second_name,
                  devices=None):
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices, only {len(devices)} visible")
    devices = devices[:n]
    if first is None and second is None:
        second = 1
        first = n
    elif first is None:
        first = n // second
    elif second is None:
        second = n // first
    if first * second != n:
        raise ValueError(
            f"{first_name}({first}) * {second_name}({second}) "
            f"!= devices({n})")
    mesh_devices = np.array(devices).reshape(first, second)
    return Mesh(mesh_devices, (first_name, second_name))


def make_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None,
              sp: Optional[int] = None, devices=None):
    """Build a ('dp', 'sp') mesh over the first n of ``devices``
    (default: all global devices)."""
    return _make_mesh_2d(n_devices, dp, "dp", sp, "sp", devices=devices)


def make_stripe_mesh(n_devices: Optional[int] = None,
                     dp: Optional[int] = None, tp: Optional[int] = None,
                     devices=None):
    """Build a ('dp', 'tp') mesh for wide-stripe (contraction-sharded)
    encode/decode; ``tp`` must divide the stripe's data-shard count."""
    return _make_mesh_2d(n_devices, dp, "dp", tp, "tp", devices=devices)


from chunky_bits_tpu.ops.bitplane import apply_bitplane as _apply_local
from chunky_bits_tpu.ops.bitplane import bitplane_acc as _acc_local
from chunky_bits_tpu.ops.bitplane import pack_acc as _pack_acc


@functools.lru_cache(maxsize=16)
def _host_bit_matrix(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    # host-side cache only: caching device arrays would leak tracers if
    # the first call happened under a jit trace
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return gf256.expand_to_bit_matrix(mat).astype(np.float32)


def _device_bit_matrix(mat_bytes: bytes, r: int, k: int):
    import jax.numpy as jnp

    return jnp.asarray(_host_bit_matrix(mat_bytes, r, k),
                       dtype=jnp.bfloat16)


# ---------------------------------------------------------------------------
# Per-chip transform implementations.
#
# On a TPU mesh each chip runs the fused Pallas kernel
# (ops/pallas_kernels.py — unpack/MXU-matmul/pack entirely in VMEM, the
# same kernel that hits ~55 GiB/s single-chip), so the mesh path carries
# the single-chip roofline instead of falling back to the HBM-bound einsum
# expansion.  CPU meshes (the virtual 8-device test mesh) keep the einsum;
# "pallas_interpret" runs the kernel's interpret mode so the wiring is
# testable off-TPU.
# ---------------------------------------------------------------------------

_IMPLS = ("einsum", "pallas", "pallas_interpret")


def _check_impl(impl: str) -> None:
    if impl not in _IMPLS:
        raise ValueError(f"unknown mesh impl {impl!r} (want one of {_IMPLS})")


def _auto_impl(mesh, r: int, k_local: int, s_local: int) -> str:
    """Pick the per-chip transform: the fused Pallas kernel when the mesh
    lives on TPU chips and the local block fits its fast path, else the
    einsum bit-plane expansion."""
    from chunky_bits_tpu.ops.pallas_kernels import _pick_tile

    try:
        on_tpu = mesh.devices.flat[0].platform == "tpu"
    # lint: broad-except-ok platform probe only; a failure routes to the
    # einsum impl, which computes the same bytes
    except Exception:
        on_tpu = False
    if on_tpu and r > 0 and k_local > 0 and _pick_tile(s_local, k_local):
        return "pallas"
    return "einsum"


def _local_apply(impl: str):
    """The shard_map local function: bf16 standard-order matrix for the
    einsum impl, int8 bit-major matrix for the pallas impls."""
    if impl == "einsum":
        return _apply_local
    from chunky_bits_tpu.ops.pallas_kernels import apply_m2_bitmajor

    interp = impl == "pallas_interpret"

    def fn(m2, shards):
        return apply_m2_bitmajor(m2, shards, interpret=interp)

    return fn


def _device_matrix(impl: str, mat: np.ndarray):
    """Device matrix in the layout the impl's local function expects."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    if impl == "einsum":
        return _device_bit_matrix(mat.tobytes(), *mat.shape)
    from chunky_bits_tpu.ops.pallas_kernels import bitmajor_device_matrix

    return bitmajor_device_matrix(mat)


@functools.lru_cache(maxsize=32)
def _sharded_apply_fn(mesh, impl: str, donate: bool = False):
    """Jitted shard_mapped transform, cached per (mesh, impl, donate) so
    repeated calls reuse the XLA executable instead of retracing.
    ``donate`` hands the staged shards buffer back to the allocator
    (double-buffered dispatch keeps two in flight; donation halves the
    device-memory high-water mark) — TPU meshes only: on CPU jax may
    alias the caller's host numpy memory zero-copy, and donating an
    aliased buffer could corrupt it."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.jit(_smap(
        _local_apply(impl),
        mesh=mesh,
        in_specs=(P(None, None), P("dp", None, "sp")),
        out_specs=P("dp", None, "sp"),
        impl=impl,
    ), donate_argnums=(1,) if donate else ())


def sharded_apply(mesh, mat: np.ndarray, shards, *,
                  impl: Optional[str] = None, donate: bool = False):
    """out[B, R, S] = mat ⊗ shards with B split over 'dp' and S over 'sp'.

    Parts are independent and the transform is element-wise over S, so both
    shardings are embarrassingly parallel — XLA inserts only the final
    all-gather to deliver the replicated-out result.  ``impl`` overrides
    the per-chip transform choice (tests force "pallas_interpret");
    ``donate`` releases the staged input buffer to the allocator (TPU
    meshes only — see ``_sharded_apply_fn``).
    """
    import jax.numpy as jnp

    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    r, k = mat.shape
    s = shards.shape[2]
    if impl is None:
        impl = _auto_impl(mesh, r, k, s // mesh.shape["sp"])
    _check_impl(impl)
    m2 = _device_matrix(impl, mat)
    return _sharded_apply_fn(mesh, impl, donate)(m2, jnp.asarray(shards))


@functools.lru_cache(maxsize=32)
def _encode_step_fn(mesh, impl: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    local = _local_apply(impl)

    def step(m2, shards):
        parity = local(m2, shards)
        local_sum = parity.astype(jnp.uint32).sum()
        checksum = jax.lax.psum(jax.lax.psum(local_sum, "dp"), "sp")
        return parity, checksum

    return jax.jit(_smap(
        step,
        mesh=mesh,
        in_specs=(P(None, None), P("dp", None, "sp")),
        out_specs=(P("dp", None, "sp"), P()),
        impl=impl,
    ))


def encode_step_sharded(mesh, encode_matrix: np.ndarray, data,
                        *, impl: Optional[str] = None):
    """One full sharded ingest compute step: parity for every part plus a
    psum'd global checksum (the cross-chip collective exercised over ICI).

    ``data`` is uint8 [B, d, S]; returns (parity [B, p, S], checksum).
    """
    import jax.numpy as jnp

    d = encode_matrix.shape[1]
    parity_rows = np.ascontiguousarray(encode_matrix[d:], dtype=np.uint8)
    if impl is None:
        impl = _auto_impl(mesh, parity_rows.shape[0], d,
                          data.shape[2] // mesh.shape["sp"])
    _check_impl(impl)
    m2 = _device_matrix(impl, parity_rows)
    return _encode_step_fn(mesh, impl)(m2, jnp.asarray(data))


# ---------------------------------------------------------------------------
# Wide-stripe (contraction-sharded) path — BASELINE.md config 5.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _wide_apply_fn(mesh, impl: str, donate: bool = False):
    """Jitted transform with the GF contraction split over 'tp'.

    Each chip holds a [B/dp, K/tp, S] slice of the input shards and the
    matching column block of the bit-matrix; it computes the partial
    popcount accumulation, which is integer-``psum``'d over 'tp'
    (popcounts add across chips because GF(2^8) addition is XOR) and packed
    to bytes with one final mod-2.  Output is replicated within each 'tp'
    group — every chip in the group ends up with the full parity for its
    'dp' slice of parts, ready for the host gather.

    The einsum impl column-shards one standard-order bf16 bit-matrix with
    ``P(None, 'tp')``.  The pallas impls run the fused accumulation kernel
    (``acc_m2_bitmajor``) per chip; bit-major column order interleaves
    byte columns, so the host pre-splits the GF matrix into per-chip byte
    column blocks, expands each to bit-major, and ships them stacked
    [tp, R8, K8/tp] sharded ``P('tp', None, None)``.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    if impl == "einsum":
        def step(m2_cols, shards_local):
            acc = _acc_local(m2_cols, shards_local)
            acc = jax.lax.psum(acc, "tp")
            return _pack_acc(acc)

        m2_spec = P(None, "tp")
    else:
        from chunky_bits_tpu.ops.pallas_kernels import (acc_m2_bitmajor,
                                                        pack_acc_bitmajor)

        interp = impl == "pallas_interpret"

        def step(m2_blocks, shards_local):
            acc = acc_m2_bitmajor(m2_blocks[0], shards_local,
                                  interpret=interp)
            acc = jax.lax.psum(acc, "tp")
            return pack_acc_bitmajor(acc)

        m2_spec = P("tp", None, None)

    return jax.jit(_smap(
        step,
        mesh=mesh,
        in_specs=(m2_spec, P("dp", "tp", None)),
        out_specs=P("dp", None, None),
        impl=impl,
    ), donate_argnums=(1,) if donate else ())


@functools.lru_cache(maxsize=16)
def _host_bitmajor_blocks(mat_bytes: bytes, r: int, k: int,
                          tp: int) -> np.ndarray:
    """Per-chip bit-major column blocks [tp, R8, (K/tp)*8] for the pallas
    wide-stripe path."""
    from chunky_bits_tpu.ops.pallas_kernels import bit_matrix_bitmajor

    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    kb = k // tp
    blocks = [
        bit_matrix_bitmajor(np.ascontiguousarray(mat[:, t * kb:(t + 1) * kb]))
        for t in range(tp)
    ]
    return np.stack(blocks).astype(np.int8)


def wide_apply_sharded(mesh, mat: np.ndarray, shards,
                       *, impl: Optional[str] = None,
                       donate: bool = False):
    """out[B, R, S] = mat ⊗ shards with B over 'dp' and the K (stripe)
    axis over 'tp'.  ``mat`` is a GF(2^8) matrix [R, K] (parity rows for
    encode, host-inverted rows for decode — the same primitive serves
    both, like the reference's encode_sep/reconstruct pair at
    src/file/file_part.rs:161,302).  'tp' must divide K.  ``donate``
    releases the staged input buffer to the allocator (TPU meshes only —
    see ``_sharded_apply_fn``).
    """
    import jax.numpy as jnp

    tp = mesh.shape["tp"]
    r, k = mat.shape
    if k % tp != 0:
        raise ValueError(f"stripe width {k} not divisible by tp={tp}")
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    if impl is None:
        impl = _auto_impl(mesh, r, k // tp, shards.shape[2])
    _check_impl(impl)
    if impl == "einsum":
        m2 = _device_bit_matrix(mat.tobytes(), r, k)
    else:
        m2 = jnp.asarray(_host_bitmajor_blocks(mat.tobytes(), r, k, tp),
                         dtype=jnp.int8)
    return _wide_apply_fn(mesh, impl, donate)(m2, jnp.asarray(shards))


def encode_wide_sharded(mesh, encode_matrix: np.ndarray, data,
                        *, impl: Optional[str] = None):
    """Wide-stripe parity: data uint8 [B, d, S] with d split over 'tp'
    (and B over 'dp') -> parity uint8 [B, p, S]."""
    d = encode_matrix.shape[1]
    parity_rows = np.ascontiguousarray(encode_matrix[d:], dtype=np.uint8)
    return wide_apply_sharded(mesh, parity_rows, data, impl=impl)
