"""Mesh-sharded erasure math: the multi-chip compute plane.

The reference scales with HTTP fan-out across storage nodes
(src/cluster/writer.rs); the TPU rebuild scales the *math* across chips with
``jax.sharding`` + ``shard_map`` over a 2D mesh:

* ``dp`` — the part-batch axis: each chip encodes its own slice of parts
  (data-parallel; parts are independent stripes, reference
  src/file/writer.rs:208 encodes them one-by-one on one core).
* ``sp`` — the shard-byte axis: GF(2^8) transforms are element-wise across
  bytes, so a single huge stripe can be split across chips the way sequence
  parallelism splits a long context — each chip transforms its byte range,
  no halo exchange needed.

The bit-matrix is tiny (<=2048x2048 bits) and replicated.  The only
collective is a ``psum`` checksum reduction used to validate mesh execution
(and as the pattern for future cross-chip reductions, e.g. distributed
scrub/verify aggregation); shards ride ICI via the mesh, never DCN.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from chunky_bits_tpu.ops import gf256


def make_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None,
              sp: Optional[int] = None):
    """Build a ('dp', 'sp') mesh over the first n devices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if dp is None and sp is None:
        sp = 1
        dp = n
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    if dp * sp != n:
        raise ValueError(f"dp({dp}) * sp({sp}) != devices({n})")
    mesh_devices = np.array(devices).reshape(dp, sp)
    return Mesh(mesh_devices, ("dp", "sp"))


from chunky_bits_tpu.ops.bitplane import apply_bitplane as _apply_local


@functools.lru_cache(maxsize=16)
def _host_bit_matrix(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    # host-side cache only: caching device arrays would leak tracers if
    # the first call happened under a jit trace
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return gf256.expand_to_bit_matrix(mat).astype(np.float32)


def _device_bit_matrix(mat_bytes: bytes, r: int, k: int):
    import jax.numpy as jnp

    return jnp.asarray(_host_bit_matrix(mat_bytes, r, k),
                       dtype=jnp.bfloat16)


@functools.lru_cache(maxsize=16)
def _sharded_apply_fn(mesh):
    """Jitted shard_mapped transform, cached per mesh so repeated calls
    reuse the XLA executable instead of retracing."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    return jax.jit(shard_map(
        _apply_local,
        mesh=mesh,
        in_specs=(P(None, None), P("dp", None, "sp")),
        out_specs=P("dp", None, "sp"),
    ))


def sharded_apply(mesh, mat: np.ndarray, shards):
    """out[B, R, S] = mat ⊗ shards with B split over 'dp' and S over 'sp'.

    Parts are independent and the transform is element-wise over S, so both
    shardings are embarrassingly parallel — XLA inserts only the final
    all-gather to deliver the replicated-out result.
    """
    import jax.numpy as jnp

    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    m2 = _device_bit_matrix(mat.tobytes(), *mat.shape)
    return _sharded_apply_fn(mesh)(m2, jnp.asarray(shards))


@functools.lru_cache(maxsize=16)
def _encode_step_fn(mesh):
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def step(m2, shards):
        parity = _apply_local(m2, shards)
        local_sum = parity.astype(jnp.uint32).sum()
        checksum = jax.lax.psum(jax.lax.psum(local_sum, "dp"), "sp")
        return parity, checksum

    return jax.jit(shard_map(
        step,
        mesh=mesh,
        in_specs=(P(None, None), P("dp", None, "sp")),
        out_specs=(P("dp", None, "sp"), P()),
    ))


def encode_step_sharded(mesh, encode_matrix: np.ndarray, data):
    """One full sharded ingest compute step: parity for every part plus a
    psum'd global checksum (the cross-chip collective exercised over ICI).

    ``data`` is uint8 [B, d, S]; returns (parity [B, p, S], checksum).
    """
    import jax.numpy as jnp

    d = encode_matrix.shape[1]
    parity_rows = np.ascontiguousarray(encode_matrix[d:], dtype=np.uint8)
    m2 = _device_bit_matrix(parity_rows.tobytes(), *parity_rows.shape)
    return _encode_step_fn(mesh)(m2, jnp.asarray(data))
