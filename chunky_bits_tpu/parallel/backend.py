"""Mesh-sharded erasure backend, selectable from cluster.yaml.

Bridges the multi-chip compute plane (parallel/mesh.py) into the ordinary
``ErasureBackend`` string plumbing, so a cluster definition can put its
erasure math on a device mesh the same way it selects ``jax``
(tunables, reference analogue src/cluster/tunables.rs):

    tunables:
      backend: jax:dp4,sp2    # part batch over 4 chips, shard bytes over 2
      # or
      backend: jax:tp4        # wide stripes: GF contraction over 4 chips

Axes: ``dp`` splits the part batch, ``sp`` splits shard bytes, ``tp``
splits the stripe (contraction) axis with an integer psum over ICI
(mesh.py).  ``tp`` and ``sp`` are mutually exclusive (the wide path's
mesh is ('dp','tp')); unspecified axes default so the product covers all
visible devices.  Batch and byte axes that don't divide evenly are
zero-padded for the dispatch and sliced back — GF transforms are
columnwise, so padding never leaks into real output.
"""

from __future__ import annotations

import numpy as np

from chunky_bits_tpu.errors import ErasureError
from chunky_bits_tpu.ops.backend import ErasureBackend


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """Parse ``"dp4,sp2"`` → {"dp": 4, "sp": 2}.  Axes: dp, sp, tp."""
    axes: dict[str, int] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, num = item.partition("=") if "=" in item else (
            item[:2], "", item[2:])
        if name not in ("dp", "sp", "tp") or not num.isdigit() \
                or int(num) < 1:
            raise ErasureError(f"bad mesh axis {item!r} in {spec!r} "
                               f"(want e.g. jax:dp4,sp2 or jax:tp4)")
        if name in axes:
            raise ErasureError(f"duplicate mesh axis {name!r} in {spec!r}")
        axes[name] = int(num)
    if "tp" in axes and "sp" in axes:
        raise ErasureError("mesh axes tp and sp are mutually exclusive "
                           "(wide stripes shard bytes via dp instead)")
    if not axes:
        raise ErasureError(f"empty mesh spec {spec!r}")
    return axes


class MeshJaxBackend(ErasureBackend):
    """GF(2^8) matrix application sharded over a device mesh."""

    #: the generic ingest path overlaps host hashing with the sharded
    #: device dispatch (ops/backend.py encode_hash_batch)
    async_dispatch = True

    #: merged batcher dispatches amortize per-dispatch mesh RPC overhead
    prefers_merged_batches = True

    def __init__(self, spec: str) -> None:
        from chunky_bits_tpu.parallel import mesh as mesh_mod

        axes = parse_mesh_spec(spec)
        from chunky_bits_tpu.ops.jax_backend import await_device_init

        await_device_init()
        import jax

        n = len(jax.devices())
        self._wide = "tp" in axes
        if self._wide:
            tp = axes["tp"]
            dp = axes.get("dp", max(n // tp, 1))
            self.mesh = mesh_mod.make_stripe_mesh(dp * tp, dp=dp, tp=tp)
            self._apply = mesh_mod.wide_apply_sharded
            self.dp, self.minor = dp, tp
            minor_name = "tp"
        else:
            dp, sp = axes.get("dp"), axes.get("sp")
            n_dev = dp * sp if (dp and sp) else None
            self.mesh = mesh_mod.make_mesh(n_dev, dp=dp, sp=sp)
            self._apply = mesh_mod.sharded_apply
            self.dp = self.mesh.shape["dp"]
            self.minor = self.mesh.shape["sp"]
            minor_name = "sp"
        # Canonical name from the *resolved* axes so spelling variants
        # ("dp=4, sp=2", "sp2" on 8 devices, ...) dedupe to one registry
        # entry and one set of jitted executables.
        self.name = f"jax:dp{self.dp},{minor_name}{self.minor}"
        self._device_dead = False
        self._fallback = None

    def apply_matrix(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        b, k, s = shards.shape
        r = mat.shape[0]
        if r == 0 or b == 0:
            return np.zeros((b, r, s), dtype=np.uint8)
        if self._wide and k % self.minor != 0:
            raise ErasureError(
                f"stripe width {k} not divisible by tp={self.minor}")
        if self._device_dead:
            return self._cpu_fallback().apply_matrix(mat, shards)
        pad_b = (-b) % self.dp
        pad_s = 0 if self._wide else (-s) % self.minor
        if pad_b or pad_s:
            shards = np.pad(shards, ((0, pad_b), (0, 0), (0, pad_s)))
        from chunky_bits_tpu.errors import DeviceDispatchTimeout
        from chunky_bits_tpu.ops.jax_backend import run_bounded_dispatch

        try:
            out = run_bounded_dispatch(
                lambda: np.asarray(self._apply(self.mesh, mat, shards)),
                "mesh erasure dispatch")
        except DeviceDispatchTimeout as err:
            import warnings

            self._device_dead = True
            warnings.warn(
                f"{err}; DEGRADED to the native CPU codec for the rest "
                f"of this process (output stays byte-identical)",
                RuntimeWarning)
            return self._cpu_fallback().apply_matrix(
                mat, shards[:b, :, :s] if (pad_b or pad_s) else shards)
        if pad_b or pad_s:
            out = out[:b, :, :s]
        return np.ascontiguousarray(out)

    def _cpu_fallback(self) -> ErasureBackend:
        """The backend used once the mesh is marked dead mid-run."""
        if self._fallback is None:
            from chunky_bits_tpu.ops.backend import cpu_fallback_backend

            self._fallback = cpu_fallback_backend()
        return self._fallback
