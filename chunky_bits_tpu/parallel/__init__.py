"""Multi-chip scaling: mesh-sharded erasure transforms."""

from chunky_bits_tpu.parallel.mesh import (  # noqa: F401
    encode_step_sharded,
    encode_wide_sharded,
    make_mesh,
    make_stripe_mesh,
    sharded_apply,
    wide_apply_sharded,
)
from chunky_bits_tpu.parallel.multihost import (  # noqa: F401
    init_multihost,
    local_mesh,
    local_stripe_mesh,
    partition_parts,
)
