"""Multi-chip scaling: mesh-sharded erasure transforms."""

from chunky_bits_tpu.parallel.mesh import (  # noqa: F401
    encode_step_sharded,
    make_mesh,
    sharded_apply,
)
