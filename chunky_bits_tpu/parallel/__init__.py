"""Host- and device-plane parallelism.

Two planes live here: the jax mesh-sharded erasure transforms
(``mesh``/``multihost``/``backend`` — multi-chip scaling) and the
CPU-only host compute pipeline (``host_pipeline`` — multi-core ingest
hashing + encode).  The package exports are resolved lazily (PEP 562)
so importing the host plane never pays the seconds-long jax import the
mesh modules need: ``from chunky_bits_tpu.parallel import
get_host_pipeline`` stays cheap on CPU-only CLI paths.
"""

from typing import Any

_MESH_EXPORTS = (
    "encode_step_sharded",
    "encode_wide_sharded",
    "make_mesh",
    "make_stripe_mesh",
    "sharded_apply",
    "wide_apply_sharded",
)
_MULTIHOST_EXPORTS = (
    "init_multihost",
    "local_mesh",
    "local_stripe_mesh",
    "partition_parts",
)
_HOST_PIPELINE_EXPORTS = (
    "HostPipeline",
    "get_host_pipeline",
)

__all__ = list(_MESH_EXPORTS + _MULTIHOST_EXPORTS + _HOST_PIPELINE_EXPORTS)


def __getattr__(name: str) -> Any:
    if name in _MESH_EXPORTS:
        from chunky_bits_tpu.parallel import mesh

        return getattr(mesh, name)
    if name in _MULTIHOST_EXPORTS:
        from chunky_bits_tpu.parallel import multihost

        return getattr(multihost, name)
    if name in _HOST_PIPELINE_EXPORTS:
        from chunky_bits_tpu.parallel import host_pipeline

        return getattr(host_pipeline, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
