"""Multi-host bootstrap for the distributed compute plane.

Scaling stance (the erasure analogue of the reference's NCCL/MPI
question — it has none; its fabric is HTTP between storage nodes,
src/cluster/writer.rs): Reed-Solomon parts are *independent* stripes, so
the part-batch axis ('dp') is embarrassingly parallel and the compute
plane never needs a cross-host collective.  The layout that follows:

* **DCN (between hosts)** carries only the object plane — HTTP shard
  reads/writes and metadata, exactly like the reference — plus the
  one-time jax.distributed control handshake.
* **ICI (within a host's slice)** carries the only collectives the math
  has: the wide-stripe 'tp' psum and the 'sp' byte split
  (parallel/mesh.py).  Meshes are therefore built over
  ``jax.local_devices()`` — each process encodes its own slice of parts
  on its own chips.

``init_multihost`` wires processes into one jax.distributed job (so
device/process topology is queryable and future cross-host work — e.g.
replicating hot bit-matrices — can use global arrays), and
``partition_parts`` deals the part batch across processes.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["init_multihost", "local_mesh", "local_stripe_mesh",
           "partition_parts"]

_INITIALIZED = False


def _distributed_initialized(jax) -> bool:
    """Whether jax.distributed.initialize already ran in this process.
    ``jax.distributed.is_initialized`` only exists from jax 0.4.39; on
    older builds (0.4.37 here) the equivalent signal is the private
    global state's live client — reached defensively so an internals
    reshuffle degrades to "not initialized" rather than an error."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist

        state = getattr(_dist, "global_state", None)
        return getattr(state, "client", None) is not None
    # lint: broad-except-ok defensive jax-internals probe; any failure
    # must read as "not initialized", never crash backend resolution
    except Exception:
        return False


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   auto: bool = False) -> tuple[int, int]:
    """Join (or detect) the multi-host jax job; idempotent.

    Three ways in, checked in order:

    1. explicit args (any of ``coordinator_address``/``num_processes``/
       ``process_id``) — passed straight to ``jax.distributed.initialize``;
       initialization failures propagate, and explicit args after this
       process has already been finalized single-process raise instead of
       being silently ignored;
    2. the ``JAX_COORDINATOR_ADDRESS``/``COORDINATOR_ADDRESS`` env var;
    3. ``auto=True`` — jax's cluster auto-detection (Cloud TPU pods,
       GKE, Slurm); only on request because on a plain host it raises.

    With none of these it is a no-op single-process setup, so the same
    code path runs unchanged on one host.  Returns
    ``(process_index, process_count)``.
    """
    global _INITIALIZED
    import jax

    # Decide from args/env alone — jax.process_count() would initialize
    # the backends, after which jax.distributed.initialize refuses to run.
    explicit = (coordinator_address is not None
                or num_processes is not None
                or process_id is not None)
    env_coordinator = (os.environ.get("JAX_COORDINATOR_ADDRESS")
                       or os.environ.get("COORDINATOR_ADDRESS"))

    if _distributed_initialized(jax):
        _INITIALIZED = True
        return jax.process_index(), jax.process_count()

    if _INITIALIZED:
        if explicit:
            raise RuntimeError(
                "init_multihost() already finalized this process as "
                "single-host; pass coordinator args on the first call")
        return jax.process_index(), jax.process_count()

    if explicit:
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        jax.distributed.initialize(**kwargs)
    elif env_coordinator is not None:
        jax.distributed.initialize(coordinator_address=env_coordinator)
    elif auto:
        jax.distributed.initialize()
    _INITIALIZED = True
    return jax.process_index(), jax.process_count()


def local_mesh(dp: Optional[int] = None, sp: Optional[int] = None):
    """('dp', 'sp') mesh over THIS process's devices (ICI domain only).

    The cross-host axis is the object plane, not the mesh: each process
    gets its own mesh and its own slice of parts (``partition_parts``).
    """
    import jax

    from chunky_bits_tpu.parallel.mesh import make_mesh

    local = jax.local_devices()
    return make_mesh(len(local), dp=dp, sp=sp, devices=local)


def local_stripe_mesh(dp: Optional[int] = None, tp: Optional[int] = None):
    """('dp', 'tp') wide-stripe mesh over this process's devices; the
    'tp' psum rides ICI and never crosses DCN."""
    import jax

    from chunky_bits_tpu.parallel.mesh import make_stripe_mesh

    local = jax.local_devices()
    return make_stripe_mesh(len(local), dp=dp, tp=tp, devices=local)


def partition_parts(total_parts: int,
                    process_index: Optional[int] = None,
                    process_count: Optional[int] = None) -> tuple[int, int]:
    """Deal a global part batch across processes: the ``[start, stop)``
    slice this process encodes.  Contiguous balanced slices (first
    ``total % n`` processes take one extra part), so the ordered
    metadata assembly of writer.py concatenates host results without
    reshuffling.
    """
    import jax

    n = process_count if process_count is not None else jax.process_count()
    i = process_index if process_index is not None else jax.process_index()
    if not 0 <= i < n:
        raise ValueError(f"process_index {i} outside 0..{n - 1}")
    base, extra = divmod(total_parts, n)
    start = i * base + min(i, extra)
    stop = start + base + (1 if i < extra else 0)
    return start, stop
