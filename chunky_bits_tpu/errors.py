"""Error taxonomy, one class per failure domain.

Mirrors the reference's per-domain error enums and conversion lattice
(reference: src/error.rs:43-281): LocationError -> ShardError ->
FileWriteError/FileReadError -> ClusterError, plus MetadataReadError,
LocationParseError and SerdeError.  Python exception subclassing replaces the
Rust ``From`` conversions.
"""

from __future__ import annotations


class ChunkyBitsError(Exception):
    """Base class for every error raised by this framework."""


class LocationParseError(ChunkyBitsError, ValueError):
    """A location string could not be parsed (src/error.rs:256-281)."""


class LocationError(ChunkyBitsError):
    """I/O against a single Location failed (src/error.rs:101-136)."""


class WriteToRangeError(LocationError):
    """Attempted to write to a byte-range location (src/error.rs:112)."""

    def __init__(self) -> None:
        super().__init__("cannot write to a ranged location")


class HttpStatusError(LocationError):
    """Non-success HTTP status from a storage node."""

    def __init__(self, status: int, url: str):
        super().__init__(f"http status {status} for {url}")
        self.status = status
        self.url = url


#: HTTP statuses worth one jittered-backoff retry against the same
#: location before falling through (reads) / invalidating the node
#: (writes).  Other 4xx and 507 are deterministic — retrying a full
#: disk or a missing chunk only adds latency.
TRANSIENT_HTTP_STATUSES = frozenset((408, 429, 500, 502, 503, 504))


def is_transient_error(err: BaseException) -> bool:
    """True when ``err`` (a LocationError, or a ShardError wrapping one
    as its ``__cause__``) names a transient HTTP failure worth a single
    retry (``tunables.read_retries``)."""
    for cand in (err, err.__cause__):
        if isinstance(cand, HttpStatusError):
            return cand.status in TRANSIENT_HTTP_STATUSES
    return False


class ShardError(ChunkyBitsError):
    """A single shard write failed; carries the failing location
    (src/error.rs:77-97)."""

    def __init__(self, message: str = "shard write failed", location=None):
        super().__init__(message)
        self.location = location


class NotEnoughWriters(ChunkyBitsError):
    """Destination cannot supply d+p shard writers (src/error.rs:57)."""


class NotEnoughAvailability(ShardError):
    """Placement ran out of candidate nodes (src/cluster/writer.rs:254-276)."""

    def __init__(self) -> None:
        super().__init__("not enough availability to place shard")


class FileWriteError(ChunkyBitsError):
    """Whole-file ingest failed (src/error.rs:43-73)."""


class FileReadError(ChunkyBitsError):
    """Whole-file read failed (src/error.rs:139-164)."""


class NotEnoughChunks(FileReadError):
    """Fewer than ``d`` intact chunks; reconstruction impossible."""


class ErasureError(ChunkyBitsError):
    """Erasure-codec level failure (bad geometry, too many erasures)."""


class DeviceInitTimeout(ErasureError):
    """PJRT device init exceeded the bounded wait (tunnel/driver down).

    Raised instead of letting ``jax.devices()`` block forever; backend
    resolution catches it and degrades to the native CPU codec so
    ``backend: jax`` in cluster.yaml never hangs a ``cp``."""


class DeviceDispatchTimeout(ErasureError):
    """An in-flight device dispatch exceeded the bounded wait (tunnel
    died AFTER a successful init).  The jax backends catch it, mark the
    device dead for the process, and recompute on the native CPU codec
    — output stays byte-identical, the operation completes."""


class ClusterError(ChunkyBitsError):
    """Cluster-level failure (src/error.rs:167-192)."""


class SerdeError(ChunkyBitsError):
    """(De)serialization failure (src/error.rs:195-217)."""


class MetadataReadError(ChunkyBitsError):
    """Metadata store failure, incl. put_script exit codes
    (src/error.rs:220-253)."""
