"""SLO engine: windowed burn-rate alerting over the metrics registry.

The registry (``obs/metrics.py``) is cumulative — it answers "what
happened since process start", never "is the SLO burning NOW".  This
module is the windowed evaluation layer on top: a bounded ring of
clock-seam-timestamped registry snapshots providing delta / rate /
ratio / quantile-over-window views of the cumulative counters and
histograms, and a CLOSED declarative rule set evaluated as multi-window
burn rates (a fast and a slow window must BOTH breach — the classic
noise suppressor) through a ``pending -> firing -> resolved`` alert
state machine with hold-down hysteresis and a bounded firing-history
ring.

**One set of numbers everywhere** (the PR-8 discipline): the engine
reads the same snapshots ``/metrics`` renders, and publishes its own
state back into the registry as ``cb_slo_*`` / ``cb_alerts_*`` families
(closed ``rule`` label set — :data:`RULES`), so the gateway's
``GET /alerts`` JSON, the ``alerts`` stanza in ``/stats`` and
``chunky-bits stats``, the ``Slo<...>`` profiler stanza, and a
Prometheus scrape all derive from the one evaluation.  Under a
multi-worker supervisor the ``cb_alerts_state`` gauges ride the same
snapshot spool as every other family, so :func:`fleet_alert_states`
merges the fleet view (firing on ANY worker means firing fleet-wide,
and a spool-reaped dead worker drops out of the merge).

**Counter resets are epochs, not negative rates.**  A gateway worker
restart resets its cumulative counters; in a fleet-merged series that
appears as a value DROP.  Every windowed delta here is computed per
label set and clamps a negative delta to the end value (the series
restarted from zero — Prometheus ``increase`` semantics), so a restart
reads as a small positive delta, never a negative burn rate.

**Time goes through the clock seam** (``cluster/clock.py``, implemented
in ``utils/clock.py``; lint rule CB108 covers this module): snapshot
timestamps, window arithmetic, pending/clear hold-downs all read
``clock.monotonic()``, so the SAME engine runs in compressed virtual
time under ``sim.run`` — which is what makes detection quality
*provable*: the deterministic simulator (``sim/scenario.py``) asserts
each scenario's expected alerts fire within a bounded virtual-time
detection latency of the scripted fault and that zero alerts fire
outside fault windows, seed-reproducibly (bench --config 15 re-proves
it at fleet scale).

Default-off, like every measured-before-defaulted layer: nothing
constructs an engine until a gateway (``tunables.slo_eval_s`` > 0) or a
scenario asks for one, and the hot serve/encode paths never touch it —
the only cost of an idle engine is its periodic ``registry.snapshot()``
tick (bench --config 15's overhead A/B pins "within noise").
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Callable, Optional, Sequence

from chunky_bits_tpu.obs import metrics as obs_metrics

#: the clock seam (canonical surface cluster/clock.py; utils-side
#: import for the same cycle hygiene as file/profiler.py) — window
#: arithmetic MUST follow the active clock or the engine would read
#: real time inside a virtual-time simulation (CB108)
from chunky_bits_tpu.utils import clock as _clock

__all__ = [
    "ALERT_STATES",
    "RULES",
    "AlertStatus",
    "SloEngine",
    "SloObjectives",
    "SloStats",
    "SnapshotRing",
    "fleet_alert_states",
    "worker_labeled_snapshot",
]

#: the CLOSED rule set — also the closed value set of the ``rule``
#: metric label (CB107).  Adding a rule means adding it HERE, next to
#: its evaluator in SloEngine._evaluate; nothing mints rule names at
#: runtime.
RULES = (
    "availability",          # gateway 5xx ratio
    "read_latency_p99",      # gateway GET p99 vs objective
    "scrub_stall",           # scrub running but verifying nothing
    "repair_fallback_storm",  # planner escalating to classic resilver
    "breaker_open",          # fraction of nodes with tripped breakers
    "hedge_exhaustion",      # hedge fire rate at/above the budget slope
    "loop_lag_p99",          # event-loop scheduling delay p99
    "worker_down",           # live worker count below objective
)

#: alert states (ranked for the ``cb_alerts_state`` gauge: merging the
#: fleet view is a plain max)
INACTIVE = "inactive"
PENDING = "pending"
FIRING = "firing"
ALERT_STATES = (INACTIVE, PENDING, FIRING)
# lint: loop-shared-ok write-once module constants (state<->rank maps),
# read-only after import — no cross-loop mutation exists
_STATE_RANK = {INACTIVE: 0, PENDING: 1, FIRING: 2}
# lint: loop-shared-ok same write-once constant, inverted
_RANK_STATE = {rank: state for state, rank in _STATE_RANK.items()}

#: firing-history ring bound (engine-lifetime memory of resolved
#: alerts; the live states are unbounded-by-construction: one per rule)
MAX_HISTORY = 256

#: snapshot ring ENTRY-COUNT backstop.  The primary bound is by AGE
#: (the engine prunes entries older than its widest window + margin on
#: every append — a snapshot of a big fleet carries per-node families,
#: so retention must track what the rules can actually read back, not
#: a fixed count); this cap only catches a pathological tick cadence.
MAX_SNAPSHOTS = 512


@dataclass
class SloObjectives:
    """The operator-tunable objective knobs, one per rule (plus the
    shared window geometry).  YAML ``slo:`` mapping -> :meth:`from_obj`
    with loud unknown-key validation; scenario specs override the same
    way.  Defaults are deliberately conservative — alerting that cries
    wolf gets deleted."""

    #: shared multi-window geometry: breach must hold over BOTH the
    #: fast and the slow window (counters/ratios/quantiles), or persist
    #: for the fast window (instantaneous gauge rules)
    fast_s: float = 60.0
    slow_s: float = 300.0
    #: extra pending hold before firing (0 = fire on first two-window
    #: breach — the window pair is already the noise gate)
    for_s: float = 0.0
    #: hold-down hysteresis: a firing alert must observe clean windows
    #: this long before it resolves (flapping input, stable output)
    clear_s: float = 120.0
    #: availability: 5xx fraction of gateway requests
    availability_5xx_ratio: float = 0.01
    #: read latency: GET wall-time p99 objective, milliseconds
    read_p99_ms: float = 500.0
    #: scrub stall: scrub running but zero bytes verified for this long
    #: (must out-span the pass interval + a pass, or idle gaps alert)
    scrub_stall_s: float = 600.0
    #: repair fallback storm: this many classic-resilver escalations
    #: inside the fast window (the planner giving up is news)
    fallback_plans: float = 1.0
    #: breaker-open: fraction of traffic-bearing nodes whose breaker is
    #: not closed (open or half-open — both mean the node is degraded)
    breaker_node_fraction: float = 0.3
    #: hedge exhaustion: hedges fired per PRIMARY fetch at/above this.
    #: The scoreboard's budget slope (hedge_ratio) is 0.05, so a
    #: sustained fire rate there means the token bucket is pinned at
    #: its cap; the default sits at 90% of the slope because a pinned
    #: bucket burns at exactly the slope (give or take the burst) and
    #: an equality threshold would flap on float jitter
    hedge_fire_ratio: float = 0.045
    #: event-loop lag p99 objective, milliseconds
    loop_lag_p99_ms: float = 100.0
    #: minimum live gateway workers (0 disables the rule — a
    #: single-process deployment has nothing to compare against)
    min_workers: int = 0

    @classmethod
    def from_obj(cls, obj: object) -> "SloObjectives":
        if obj is None:
            return cls()
        if not isinstance(obj, dict):
            raise ValueError("slo objectives must be a mapping")
        known = {f.name for f in fields(cls)}
        unknown = sorted(set(obj) - known)
        if unknown:
            raise ValueError(
                f"unknown slo objective(s) {unknown} "
                f"(know {sorted(known)})")
        kwargs = {}
        for key, value in obj.items():
            try:
                kwargs[key] = (int(value) if key == "min_workers"
                               else float(value))
            except (TypeError, ValueError) as err:
                raise ValueError(
                    f"invalid slo objective {key}={value!r}") from err
            if kwargs[key] < 0:
                raise ValueError(
                    f"slo objective {key} must be >= 0, got {value!r}")
        return cls(**kwargs)

    def to_obj(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}


class SnapshotRing:
    """Bounded ring of ``(t, snapshot)`` registry snapshots with the
    windowed delta/ratio/quantile views the rules read.

    Timestamps come off the clock seam unless the caller supplies
    ``now`` explicitly (tests and the simulator's deterministic ticks).
    All reads are per-label-set reset-aware: a cumulative series that
    went DOWN restarted (worker restart, spool-reaped sibling), and its
    delta is the post-reset end value, never negative."""

    def __init__(self, maxlen: int = MAX_SNAPSHOTS,
                 max_age_s: Optional[float] = None) -> None:
        self._entries: deque[tuple[float, dict]] = deque(maxlen=maxlen)
        #: age bound: entries older than this behind the newest are
        #: pruned on append (None = count-bound only).  The engine
        #: passes its widest window + margin — windowed reads never
        #: look further back, so keeping more would be pure memory.
        self.max_age_s = max_age_s

    def __len__(self) -> int:
        return len(self._entries)

    def append(self, snapshot: dict,
               now: Optional[float] = None) -> None:
        t = _clock.monotonic() if now is None else float(now)
        self._entries.append((t, snapshot))
        if self.max_age_s is not None:
            cutoff = t - self.max_age_s
            # keep >= 2 entries so delta views always have a pair
            while (len(self._entries) > 2
                   and self._entries[0][0] < cutoff
                   and self._entries[1][0] <= cutoff):
                self._entries.popleft()

    def latest(self) -> Optional[tuple[float, dict]]:
        return self._entries[-1] if self._entries else None

    # ---- window selection ----

    def _window_pair(self, window_s: float
                     ) -> Optional[tuple[tuple[float, dict],
                                         tuple[float, dict]]]:
        """(oldest-in-window entry, newest entry), or None when the
        ring does not yet span at least half the window — a young ring
        must read as "insufficient data", never as a zero rate."""
        if len(self._entries) < 2:
            return None
        newest = self._entries[-1]
        cutoff = newest[0] - window_s
        oldest = None
        for entry in self._entries:
            if entry[0] >= cutoff:
                oldest = entry
                break
        if oldest is None or oldest is newest:
            oldest = self._entries[-2]
        if newest[0] - oldest[0] < window_s * 0.5:
            return None
        return oldest, newest

    def window_entries(self, window_s: float
                       ) -> list[tuple[float, dict]]:
        """Every ring entry inside the trailing window (for gauge
        persistence checks)."""
        if not self._entries:
            return []
        cutoff = self._entries[-1][0] - window_s
        return [e for e in self._entries if e[0] >= cutoff]

    # ---- windowed views ----

    #: family-by-name lookup (shared with the stats CLI renderer)
    _family = staticmethod(obs_metrics.find_family)

    @staticmethod
    def _matches(labels: dict, match: Optional[dict]) -> bool:
        if not match:
            return True
        return all(labels.get(k) == v for k, v in match.items())

    def counter_delta(self, name: str, window_s: float,
                      match: Optional[dict] = None) -> Optional[float]:
        """Sum of per-series increases of a counter family over the
        trailing window; None when the family is absent from the newest
        snapshot or the ring is too young.  Per-series reset clamp: a
        negative per-key delta reads as the end value (fresh epoch)."""
        pair = self._window_pair(window_s)
        if pair is None:
            return None
        (_, old_snap), (_, new_snap) = pair
        new_fam = self._family(new_snap, name)
        if new_fam is None:
            return None
        old_fam = self._family(old_snap, name) or {"samples": []}
        old_vals = {
            tuple(sorted(s["labels"].items())): float(s.get("value", 0))
            for s in old_fam.get("samples", ())
        }
        total = 0.0
        for s in new_fam.get("samples", ()):
            if not self._matches(s["labels"], match):
                continue
            end = float(s.get("value", 0))
            start = old_vals.get(tuple(sorted(s["labels"].items())), 0.0)
            delta = end - start
            total += end if delta < 0 else delta
        return total

    def hist_window(self, name: str, window_s: float,
                    match: Optional[dict] = None
                    ) -> Optional[tuple[list, list]]:
        """(bucket bounds, per-bucket count increases) of a histogram
        family over the trailing window, summed across matching label
        sets; None when absent or too young.  The reset clamp is
        per-series, whole-vector: any bucket going backwards means the
        series restarted, so its window contribution is the end
        vector."""
        pair = self._window_pair(window_s)
        if pair is None:
            return None
        (_, old_snap), (_, new_snap) = pair
        new_fam = self._family(new_snap, name)
        if new_fam is None or "buckets" not in new_fam:
            return None
        bounds = list(new_fam["buckets"])
        old_fam = self._family(old_snap, name) or {"samples": []}
        old_counts = {
            tuple(sorted(s["labels"].items())): list(s.get("counts", ()))
            for s in old_fam.get("samples", ())
        }
        total = [0.0] * (len(bounds) + 1)
        for s in new_fam.get("samples", ()):
            if not self._matches(s["labels"], match):
                continue
            end = list(s.get("counts", ()))
            if len(end) != len(total):
                continue  # bucket layout changed: skip the series
            start = old_counts.get(tuple(sorted(s["labels"].items())))
            if start is None or len(start) != len(end) \
                    or any(e < o for e, o in zip(end, start)):
                delta = end  # fresh epoch
            else:
                delta = [e - o for e, o in zip(end, start)]
            for i, d in enumerate(delta):
                total[i] += d
        return bounds, total

    def quantile(self, name: str, q: float, window_s: float,
                 match: Optional[dict] = None) -> Optional[float]:
        """``histogram_quantile`` over the window's bucket increases;
        None when absent/young/empty-in-window."""
        win = self.hist_window(name, window_s, match)
        if win is None:
            return None
        bounds, counts = win
        if sum(counts) <= 0:
            return None
        return obs_metrics.histogram_quantile(bounds, counts, q)

    def gauge_values(self, snapshot: dict, name: str,
                     match: Optional[dict] = None
                     ) -> Optional[list[float]]:
        """All matching sample values of a gauge family in one
        snapshot; None when the family is absent."""
        fam = self._family(snapshot, name)
        if fam is None:
            return None
        return [float(s.get("value", 0))
                for s in fam.get("samples", ())
                if self._matches(s["labels"], match)]

    def gauge_persisted(self, window_s: float,
                        reduce_fn: Callable[[dict], Optional[float]]
                        ) -> Optional[float]:
        """Minimum of ``reduce_fn(snapshot)`` over the trailing window
        — the persistence view of an instantaneous gauge rule: only a
        value that held for (at least half) the window counts.  None
        when the ring is young or any reduction is None."""
        entries = self.window_entries(window_s)
        if len(entries) < 2 \
                or entries[-1][0] - entries[0][0] < window_s * 0.5:
            return None
        values = []
        for _t, snap in entries:
            v = reduce_fn(snap)
            if v is None:
                return None
            values.append(v)
        return min(values)


@dataclass
class AlertStatus:
    """One rule's live state — the ``/alerts`` row."""

    rule: str
    state: str = INACTIVE
    since: float = 0.0            # when the current state was entered
    value_fast: Optional[float] = None
    value_slow: Optional[float] = None
    threshold: float = 0.0
    fired_count: int = 0          # lifetime firings of this rule
    _pending_since: Optional[float] = None
    _clear_since: Optional[float] = None

    def to_obj(self) -> dict:
        return {
            "rule": self.rule,
            "state": self.state,
            "since": round(self.since, 3),
            "value_fast": (None if self.value_fast is None
                           else round(self.value_fast, 6)),
            "value_slow": (None if self.value_slow is None
                           else round(self.value_slow, 6)),
            "threshold": self.threshold,
            "fired_count": self.fired_count,
        }


@dataclass
class SloStats:
    """Engine snapshot for the ``Slo<...>`` profiler stanza and the
    ``/stats`` payload."""

    evaluations: int
    firing: list[str]
    pending: list[str]
    resolved_total: int

    def to_obj(self) -> dict:
        return {
            "evaluations": self.evaluations,
            "firing": list(self.firing),
            "pending": list(self.pending),
            "resolved_total": self.resolved_total,
        }

    def __str__(self) -> str:
        firing = ",".join(self.firing) or "-"
        pending = ",".join(self.pending) or "-"
        return (f"Slo<evals={self.evaluations} firing=[{firing}] "
                f"pending=[{pending}] "
                f"resolved={self.resolved_total}>")


class SloEngine:
    """The windowed evaluator: feed it snapshots, read alert states.

    ``observe()`` is the one write path — append a snapshot to the
    ring, evaluate every rule's fast/slow window pair, step each
    rule's state machine, and publish ``cb_slo_*`` / ``cb_alerts_*``
    into ``registry``.  Thread-safe the registry way (one lock, sync
    updates only) because the gateway ticker and a ``/alerts`` handler
    may interleave; in the simulator everything runs on one loop and
    the lock is uncontended.

    ``on_transition(rule, old_state, new_state, t, value)`` fires on
    every state change — the scenario engine's trace hook, which is
    what makes detection latency a deterministic, assertable number.
    """

    def __init__(self, objectives: Optional[SloObjectives] = None,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 on_transition: Optional[Callable] = None) -> None:
        self.objectives = objectives or SloObjectives()
        self._registry = registry or obs_metrics.get_registry()
        self._on_transition = on_transition
        self._lock = threading.Lock()
        # retain exactly what the rules can read back: the widest
        # configured window, doubled for the window-pair selection's
        # slack, plus a couple of minutes of margin
        obj = self.objectives
        widest = max(obj.fast_s, obj.slow_s, obj.scrub_stall_s)
        self.ring = SnapshotRing(max_age_s=widest * 2.0 + 120.0)
        self._alerts = {rule: AlertStatus(rule=rule) for rule in RULES}
        self._history: deque[dict] = deque(maxlen=MAX_HISTORY)
        self._evaluations = 0
        self._resolved_total = 0
        # the engine's own families (closed `rule` label set = RULES;
        # CB107): published into the registry so they ride the fleet
        # spool like every other series
        self._g_value = self._registry.gauge(
            "cb_slo_value",
            "latest fast-window value per SLO rule", labels=("rule",))
        self._g_state = self._registry.gauge(
            "cb_alerts_state",
            "alert state per rule (0 inactive, 1 pending, 2 firing)",
            labels=("rule",))
        self._c_transitions = self._registry.counter(
            "cb_alerts_transitions_total",
            "alert state-machine transitions", labels=("rule", "to"))
        self._c_evals = self._registry.counter(
            "cb_slo_evaluations_total", "SLO engine evaluations")
        for rule in RULES:
            self._g_state.labels(rule=rule).set(0)

    # ---- rule evaluation (value extraction) ----

    def _ratio(self, name: str, window_s: float, num_match: dict,
               den_match: Optional[dict] = None) -> Optional[float]:
        num = self.ring.counter_delta(name, window_s, num_match)
        den = self.ring.counter_delta(name, window_s, den_match)
        if num is None or den is None or den <= 0:
            return None
        return num / den

    def _breaker_fraction(self, snapshot: dict) -> Optional[float]:
        values = self.ring.gauge_values(snapshot,
                                        "cb_node_breaker_state")
        if not values:
            return None  # no traffic-bearing nodes yet
        return sum(1 for v in values if v >= 1) / len(values)

    def _workers_missing(self, snapshot: dict) -> Optional[float]:
        if self.objectives.min_workers <= 0:
            return None
        values = self.ring.gauge_values(snapshot, "cb_worker_up")
        if values is None:
            return None  # not a gateway process
        return float(self.objectives.min_workers) - sum(values)

    def _hedge_rate(self, window_s: float) -> Optional[float]:
        """Hedges fired per primary fetch over the window — the exact
        slope of the scoreboard's budget bound (fired <= ratio *
        primaries + burst), so a sustained value at/near the ratio
        means the token bucket is pinned at its cap."""
        fired = self.ring.counter_delta("cb_hedges_fired_total",
                                        window_s)
        if fired is None:
            return None
        prim = self.ring.counter_delta("cb_hedge_primaries_total",
                                       window_s)
        return fired / max(prim or 0.0, 1.0)

    def _scrub_stalled(self, window_s: float) -> Optional[float]:
        latest = self.ring.latest()
        if latest is None:
            return None
        running = self.ring.gauge_values(latest[1], "cb_scrub_running")
        if not running or sum(running) <= 0:
            return 0.0 if running is not None else None
        verified = self.ring.counter_delta(
            "cb_scrub_bytes_verified_total", window_s)
        if verified is None:
            return None
        return 1.0 if verified <= 0 else 0.0

    def _evaluate(self) -> dict[str, tuple[Optional[float],
                                           Optional[float], float]]:
        """(fast value, slow value, threshold) per rule.  None means
        "insufficient data": never a breach, and clears a firing alert
        (no data is no evidence of burn)."""
        obj = self.objectives
        fast, slow = obj.fast_s, obj.slow_s
        latest = self.ring.latest()
        latest_snap = latest[1] if latest else {"families": []}
        out: dict = {}
        out["availability"] = (
            self._ratio("cb_request_total", fast,
                        {"status_class": "5xx"}),
            self._ratio("cb_request_total", slow,
                        {"status_class": "5xx"}),
            obj.availability_5xx_ratio)
        q_fast = self.ring.quantile("cb_request_seconds", 99.0, fast,
                                    {"method": "GET"})
        q_slow = self.ring.quantile("cb_request_seconds", 99.0, slow,
                                    {"method": "GET"})
        out["read_latency_p99"] = (
            None if q_fast is None else q_fast * 1000.0,
            None if q_slow is None else q_slow * 1000.0,
            obj.read_p99_ms)
        stall = self._scrub_stalled(obj.scrub_stall_s)
        out["scrub_stall"] = (stall, stall, 1.0)
        out["repair_fallback_storm"] = (
            self.ring.counter_delta("cb_repair_plans_total", fast,
                                    {"kind": "fallback"}),
            self.ring.counter_delta("cb_repair_plans_total", slow,
                                    {"kind": "fallback"}),
            obj.fallback_plans)
        out["breaker_open"] = (
            self._breaker_fraction(latest_snap),
            self.ring.gauge_persisted(fast, self._breaker_fraction),
            obj.breaker_node_fraction)
        out["hedge_exhaustion"] = (
            self._hedge_rate(fast), self._hedge_rate(slow),
            obj.hedge_fire_ratio)
        lag_fast = self.ring.quantile("cb_eventloop_lag_seconds", 99.0,
                                      fast)
        lag_slow = self.ring.quantile("cb_eventloop_lag_seconds", 99.0,
                                      slow)
        out["loop_lag_p99"] = (
            None if lag_fast is None else lag_fast * 1000.0,
            None if lag_slow is None else lag_slow * 1000.0,
            obj.loop_lag_p99_ms)
        out["worker_down"] = (
            self._workers_missing(latest_snap),
            self.ring.gauge_persisted(fast, self._workers_missing),
            1.0)
        return out

    # ---- the state machine ----

    def _transition(self, alert: AlertStatus, new_state: str,
                    now: float) -> None:
        old = alert.state
        alert.state = new_state
        alert.since = now
        self._c_transitions.labels(rule=alert.rule, to=new_state).inc()
        if new_state == FIRING:
            alert.fired_count += 1
            self._history.append({"rule": alert.rule, "fired_at": now,
                                  "resolved_at": None,
                                  "value": alert.value_fast})
        elif old == FIRING:
            self._resolved_total += 1
            for entry in reversed(self._history):
                if entry["rule"] == alert.rule \
                        and entry["resolved_at"] is None:
                    entry["resolved_at"] = now
                    break
        if self._on_transition is not None:
            self._on_transition(alert.rule, old, new_state, now,
                                alert.value_fast)

    def _step(self, alert: AlertStatus, now: float,
              v_fast: Optional[float], v_slow: Optional[float],
              threshold: float) -> None:
        alert.value_fast = v_fast
        alert.value_slow = v_slow
        alert.threshold = threshold
        breach = (v_fast is not None and v_slow is not None
                  and v_fast >= threshold and v_slow >= threshold)
        obj = self.objectives
        if alert.state == INACTIVE:
            if breach:
                alert._pending_since = now
                if obj.for_s <= 0:
                    self._transition(alert, FIRING, now)
                else:
                    self._transition(alert, PENDING, now)
        elif alert.state == PENDING:
            if not breach:
                alert._pending_since = None
                self._transition(alert, INACTIVE, now)
            elif now - (alert._pending_since or now) >= obj.for_s:
                self._transition(alert, FIRING, now)
        else:  # FIRING
            if breach:
                alert._clear_since = None
                return
            # hysteresis hold-down: clean (or data-less) windows must
            # persist clear_s before the alert resolves
            if alert._clear_since is None:
                alert._clear_since = now
            if now - alert._clear_since >= obj.clear_s:
                alert._clear_since = None
                alert._pending_since = None
                self._transition(alert, INACTIVE, now)

    # ---- public surface ----

    def observe(self, snapshot: Optional[dict] = None,
                now: Optional[float] = None) -> None:
        """One evaluation tick: append ``snapshot`` (default: this
        registry's own) to the ring, evaluate every rule, step the
        state machines, publish the ``cb_slo_*`` families."""
        if snapshot is None:
            snapshot = self._registry.snapshot()
        t = _clock.monotonic() if now is None else float(now)
        with self._lock:
            self.ring.append(snapshot, now=t)
            values = self._evaluate()
            for rule in RULES:
                v_fast, v_slow, threshold = values[rule]
                self._step(self._alerts[rule], t, v_fast, v_slow,
                           threshold)
                if v_fast is not None:
                    self._g_value.labels(rule=rule).set(v_fast)
                self._g_state.labels(rule=rule).set(
                    _STATE_RANK[self._alerts[rule].state])
            self._evaluations += 1
            self._c_evals.inc()

    def alerts(self) -> list[AlertStatus]:
        with self._lock:
            return [self._alerts[rule] for rule in RULES]

    def firing(self) -> list[str]:
        with self._lock:
            return [r for r in RULES
                    if self._alerts[r].state == FIRING]

    def history(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._history]

    def stats(self) -> SloStats:
        with self._lock:
            return SloStats(
                evaluations=self._evaluations,
                firing=[r for r in RULES
                        if self._alerts[r].state == FIRING],
                pending=[r for r in RULES
                         if self._alerts[r].state == PENDING],
                resolved_total=self._resolved_total,
            )

    def to_obj(self) -> dict:
        """The ``/alerts`` payload body (single-process form; the
        gateway handler adds the fleet merge under a supervisor)."""
        with self._lock:
            return {
                "enabled": True,
                "evaluations": self._evaluations,
                "alerts": [self._alerts[rule].to_obj()
                           for rule in RULES],
                "firing": [r for r in RULES
                           if self._alerts[r].state == FIRING],
                "history": [
                    {**e,
                     "fired_at": round(e["fired_at"], 3),
                     "resolved_at": (None if e["resolved_at"] is None
                                     else round(e["resolved_at"], 3)),
                     "value": (None if e["value"] is None
                               else round(e["value"], 6))}
                    for e in self._history],
                "objectives": self.objectives.to_obj(),
            }


def worker_labeled_snapshot(entries: Sequence[tuple[Optional[str],
                                                   dict]]) -> dict:
    """Combine per-worker registry snapshots for the ENGINE's ring:
    every sample of every kind gains a ``worker`` label instead of
    being summed (``merge_snapshots`` sums counters across workers,
    which would make the ring's per-series reset clamp misfire — one
    worker restarting drops the fleet SUM slightly, and a negative
    delta on a summed series would clamp to the surviving workers'
    entire lifetime total, firing every ratio rule spuriously).  With
    worker-labeled series the deltas are per worker: a restarted
    worker clamps only its own small post-reset value, and a
    spool-reaped worker's series simply vanish from the newest
    snapshot and contribute nothing.  Window sums/ratios over the
    labeled series equal the fleet numbers, because the rules sum
    matching samples anyway."""
    fams: dict[str, dict] = {}
    for worker_id, snap in entries:
        wid = str(worker_id)
        for fam in snap.get("families", ()):
            out = fams.get(fam["name"])
            if out is None:
                out = fams[fam["name"]] = {
                    "name": fam["name"], "type": fam["type"],
                    "help": fam.get("help", ""), "samples": []}
                if "buckets" in fam:
                    out["buckets"] = list(fam["buckets"])
            for s in fam.get("samples", ()):
                labeled = dict(s)
                labeled["labels"] = {**s.get("labels", {}),
                                     "worker": wid}
                out["samples"].append(labeled)
    return {"families": [fams[name] for name in sorted(fams)]}


# ---- fleet aggregation (the /alerts twin of merge_snapshots) ----


def fleet_alert_states(entries: Sequence[tuple[Optional[str], dict]]
                       ) -> dict:
    """Merge per-worker registry snapshots' ``cb_alerts_state`` gauges
    into the fleet alert view: per rule, the MAX state across workers
    (firing on any worker means the fleet is firing), plus the
    per-worker breakdown so an operator sees WHICH worker burns.
    ``entries`` is ``[(worker_id, snapshot)]`` — the same spool shape
    :func:`obs.metrics.load_spool` returns, so a spool-reaped dead
    worker simply is not in the input and cannot contribute a stale
    firing alert."""
    per_worker: dict[str, dict[str, str]] = {}
    fleet: dict[str, str] = {rule: INACTIVE for rule in RULES}
    for worker_id, snap in entries:
        states: dict[str, str] = {}
        for fam in snap.get("families", ()):
            if fam.get("name") != "cb_alerts_state":
                continue
            for s in fam.get("samples", ()):
                rule = s.get("labels", {}).get("rule")
                if rule not in fleet:
                    continue  # closed set: foreign labels are ignored
                state = _RANK_STATE.get(int(s.get("value", 0)),
                                        INACTIVE)
                states[rule] = state
                if _STATE_RANK[state] > _STATE_RANK[fleet[rule]]:
                    fleet[rule] = state
        if states:
            per_worker[str(worker_id)] = states
    return {
        "fleet": fleet,
        "firing": [r for r in RULES if fleet[r] == FIRING],
        "workers": dict(sorted(per_worker.items())),
    }
