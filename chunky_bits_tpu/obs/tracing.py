"""Two-plane request tracing: trace IDs, spans, slowest-N buffer.

Answers "why was this p999 request slow" with WHICH PLANE ate the time:
the gateway's access-log middleware mints a trace ID (or accepts the
client's via ``X-Chunky-Trace``) and parks the active
:class:`Trace` in a ``contextvars.ContextVar`` — asyncio copies the
context into every task, so the trace follows the request through
``FileReadBuilder.stream``'s prefetch tasks, ``FilePart.read_buffers``'s
hedged fetch races, and the reconstruct path with zero explicit
plumbing.  The one boundary contextvars cannot cross — the host
pipeline's worker threads — is bridged by capture-at-submit:
``_Job.__init__`` snapshots :func:`current` on the submitting thread and
the job runner records queue-wait and execution spans onto that trace
from the worker (``Trace.add`` is thread-safe).

Spans are flat ``(name, plane, start, duration, outcome)`` records —
planes: ``gateway`` (the request envelope), ``network`` (chunk
fetches / location I/O), ``host`` (pipeline queue wait + compute),
``compute`` (erasure reconstruct dispatch) — enough to attribute a slow
request without the weight of a span tree.

**Opt-in, measured-before-defaulting**: tracing arms only when
``tunables.trace_slow_ms`` / ``$CHUNKY_BITS_TPU_TRACE_SLOW_MS`` > 0
(the gateway reads it at app build).  Off, the only cost anywhere is a
ContextVar.get returning the None default.  On, completed traces at
least ``trace_slow_ms`` slow enter the process-wide slowest-N buffer
served at gateway ``GET /debug/traces``.
"""

from __future__ import annotations

import contextvars
import heapq
import itertools
import os
import threading
from typing import Optional

#: the clock seam (canonical surface cluster/clock.py; utils-side
#: import for cycle hygiene): span starts arrive off this clock
#: (file_part.py, gateway middleware), so the trace birth stamp the
#: offsets subtract from must come off the SAME clock — inside a
#: virtual-time simulation a real-clock t0 would turn every start_ms
#: into timebase-mixed garbage
from chunky_bits_tpu.utils import clock as _clock

#: the active trace for this context; None = tracing off / untraced
#: request.  A ContextVar, not module state: every asyncio task gets
#: its own copy, worker threads read None unless a job carried a
#: captured trace.
_CURRENT: "contextvars.ContextVar[Optional[Trace]]" = \
    contextvars.ContextVar("cb_trace", default=None)

#: bound on spans per trace — a pathological fan-out (thousands of
#: chunk fetches) must not make one trace unbounded; drops are counted
#: on the trace itself
MAX_SPANS = 256

#: traces kept in the slowest-N buffer
BUFFER_CAPACITY = 64

#: accepted ``X-Chunky-Trace`` shape: short, printable, header-safe
_MAX_ID_LEN = 64


class Span:
    __slots__ = ("name", "plane", "start_ms", "duration_ms", "outcome")

    def __init__(self, name: str, plane: str, start_ms: float,
                 duration_ms: float, outcome: str) -> None:
        self.name = name
        self.plane = plane
        self.start_ms = start_ms
        self.duration_ms = duration_ms
        self.outcome = outcome

    def to_obj(self) -> dict:
        return {"name": self.name, "plane": self.plane,
                "start_ms": round(self.start_ms, 3),
                "duration_ms": round(self.duration_ms, 3),
                "outcome": self.outcome}


class Trace:
    """One request's span collection.  ``add`` is thread-safe: loop
    callbacks, hedge tasks AND pipeline worker threads all record onto
    the same trace."""

    __slots__ = ("trace_id", "t0", "spans", "dropped_spans", "_lock")

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.t0 = _clock.monotonic()
        self.spans: list[Span] = []
        self.dropped_spans = 0
        self._lock = threading.Lock()

    def add(self, name: str, plane: str, start: float, duration: float,
            outcome: str = "ok") -> None:
        """Record one span; ``start`` is a clock-seam ``monotonic()``
        stamp (converted to ms offset from the trace's birth)."""
        span = Span(name, plane, (start - self.t0) * 1000.0,
                    duration * 1000.0, outcome)
        with self._lock:
            if len(self.spans) >= MAX_SPANS:
                self.dropped_spans += 1
                return
            self.spans.append(span)

    def to_obj(self, duration_ms: float, meta: dict) -> dict:
        with self._lock:
            spans = [s.to_obj() for s in self.spans]
            dropped = self.dropped_spans
        planes: dict[str, float] = {}
        for s in spans:
            planes[s["plane"]] = planes.get(s["plane"], 0.0) \
                + s["duration_ms"]
        return {"trace_id": self.trace_id,
                "duration_ms": round(duration_ms, 3),
                "plane_ms": {k: round(v, 3)
                             for k, v in sorted(planes.items())},
                "spans": spans,
                **({"dropped_spans": dropped} if dropped else {}),
                **meta}


class TraceBuffer:
    """Bounded slowest-N keeper: a min-heap on duration, so a new slow
    trace evicts the fastest retained one — the buffer converges on the
    worst tail, exactly the requests worth debugging."""

    def __init__(self, capacity: int = BUFFER_CAPACITY) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._heap: list[tuple[float, int, dict]] = []

    def offer(self, duration_ms: float, record: dict) -> bool:
        with self._lock:
            if len(self._heap) < self.capacity:
                heapq.heappush(self._heap,
                               (duration_ms, next(self._seq), record))
                return True
            if self._heap and duration_ms > self._heap[0][0]:
                heapq.heapreplace(
                    self._heap, (duration_ms, next(self._seq), record))
                return True
            return False

    def snapshot(self) -> list[dict]:
        """Retained traces, slowest first."""
        with self._lock:
            items = sorted(self._heap,
                           key=lambda t: (-t[0], -t[1]))
        return [rec for _d, _s, rec in items]

    def clear(self) -> None:
        with self._lock:
            self._heap = []


#: the process-wide slowest-N buffer /debug/traces serves; per-worker
#: like every other serving-plane store (the fleet view is per-worker
#: by design — a trace is a single worker's story)
_BUFFER = TraceBuffer()


def buffer() -> TraceBuffer:
    return _BUFFER


def mint_id() -> str:
    return os.urandom(8).hex()


def clean_id(raw: Optional[str]) -> str:
    """A usable trace id from a client's ``X-Chunky-Trace`` header —
    minted fresh when absent or unprintable/oversized (header values
    land in JSON debug payloads; garbage must not)."""
    if raw:
        raw = raw.strip()
        if 0 < len(raw) <= _MAX_ID_LEN and raw.isprintable() \
                and '"' not in raw and "\\" not in raw:
            return raw
    return mint_id()


def start(trace_id: str) -> tuple["Trace", "contextvars.Token"]:
    """Open a trace and make it current; pair with :func:`finish`."""
    trace = Trace(trace_id)
    token = _CURRENT.set(trace)
    return trace, token


def finish(trace: "Trace", token: "contextvars.Token", *,
           duration: float, slow_s: float, meta: dict) -> bool:
    """Close out a trace: restore the context and, when the request ran
    at least ``slow_s``, file it in the slowest-N buffer.  Returns
    whether the trace was retained."""
    _CURRENT.reset(token)
    duration_ms = duration * 1000.0
    if duration < slow_s:
        return False
    return _BUFFER.offer(duration_ms,
                         trace.to_obj(duration_ms, dict(meta)))


def current() -> Optional["Trace"]:
    """The context's active trace, or None (tracing off — the one-call
    fast path every instrumented site pays)."""
    return _CURRENT.get()


def record_span(name: str, plane: str, start_t: float, duration: float,
                outcome: str = "ok") -> None:
    """Record a span onto the context's trace; no-op when untraced."""
    trace = _CURRENT.get()
    if trace is not None:
        trace.add(name, plane, start_t, duration, outcome)
