"""Unified metrics registry: counters, gauges, log-bucket histograms.

One process-wide, thread-safe registry (worker threads record too, so
this module is deliberately NOT loop-bound — same contract as
cluster/health.py) is the single sink behind the existing stat sources:

* **event-recorded series** — ``Profiler.log_request``/``log_read``/
  ``log_write`` feed latency histograms and byte counters through the
  :func:`record_request` / :func:`record_io` helpers; the gateway's
  admission/shed counters increment registry counters directly;
* **polled sources** — ``ChunkCache``, ``HostPipeline``,
  ``HealthScoreboard``, ``ScrubDaemon`` and ``RepairPlanner``
  self-register (weakly) at construction and are snapshot at scrape
  time off their existing
  ``stats()`` dataclasses, so one ``GET /metrics`` shows the whole
  system while the ``Profiler`` stanzas keep rendering on top of the
  same numbers.

Exposition is Prometheus text (``render_exposition``), validated by the
strict line-grammar parser :func:`parse_exposition` that the tests and
the CI scrape step share.  :func:`merge_snapshots` is the fleet
aggregation the multi-worker gateway uses: counters and histograms sum
across workers, gauges gain a ``worker`` label (gateway/workers.py
spools per-worker JSON snapshots; any worker's ``/metrics`` merges the
fleet's).

**Label cardinality rule** (lint rule CB107 machine-checks the call
sites): label values must come from closed sets — HTTP method, status
class, serving source, pipeline stage, configured node key — NEVER from
request paths or other client-controlled strings.  The registry
enforces a hard ceiling (:data:`MAX_LABEL_SETS`) per family as the
runtime backstop: an open-ended label is a memory leak and a scrape
bomb.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import re
import threading
import weakref
from typing import Iterable, Optional, Sequence

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: hard per-family ceiling on distinct label sets — the runtime
#: backstop behind lint rule CB107: a family that tries to grow past
#: this is recording an open-ended label (a request path, a client
#: string) and must fail loudly, not leak silently
MAX_LABEL_SETS = 128

#: default histogram layout: fixed log2 buckets from 0.1 ms to ~105 s.
#: Fixed (never adaptive) so merging across workers and scrapes is a
#: plain per-bucket sum.
DEFAULT_TIME_BUCKETS: tuple[float, ...] = tuple(
    1e-4 * (2.0 ** k) for k in range(21))

class ExpositionError(ValueError):
    """A /metrics payload violated the exposition line grammar."""


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"bad metric name {name!r}")
    return name


class _Cell:
    """One (family, label set) scalar series.  ``inc`` for counters,
    ``set`` for gauges; a lock per cell keeps updates exact under
    concurrent thread + loop recording."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistCell:
    """One (family, label set) histogram series: per-bucket counts
    (NOT cumulative — exposition cumulates at render), sum, count."""

    __slots__ = ("_lock", "_bounds", "counts", "sum", "count")

    def __init__(self, bounds: tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last cell = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1

    def snap(self) -> tuple[list, float, int]:
        with self._lock:
            return list(self.counts), self.sum, self.count


class Family:
    """One named metric family.  ``labels(**kv)`` returns the cell for
    a label set (created on first use, capped at MAX_LABEL_SETS); an
    unlabeled family is its own single cell via ``inc``/``set``/
    ``observe``."""

    def __init__(self, name: str, kind: str, help_: str,
                 labelnames: tuple[str, ...],
                 buckets: Optional[tuple[float, ...]] = None) -> None:
        self.name = _check_name(name)
        self.kind = kind
        self.help = help_
        self.labelnames = labelnames
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln):
                raise ValueError(f"bad label name {ln!r}")
        if kind == HISTOGRAM:
            b = tuple(float(x) for x in (buckets or DEFAULT_TIME_BUCKETS))
            if list(b) != sorted(b) or len(set(b)) != len(b):
                raise ValueError("histogram buckets must be ascending")
            self.buckets: Optional[tuple[float, ...]] = b
        else:
            self.buckets = None
        self._lock = threading.Lock()
        self._cells: dict[tuple[str, ...], object] = {}

    def _new_cell(self) -> object:
        if self.kind == HISTOGRAM:
            assert self.buckets is not None
            return _HistCell(self.buckets)
        return _Cell()

    def labels(self, **kv: str):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                if len(self._cells) >= MAX_LABEL_SETS:
                    raise ValueError(
                        f"{self.name}: more than {MAX_LABEL_SETS} label "
                        "sets — label values must come from a closed "
                        "set (CB107)")
                cell = self._cells[key] = self._new_cell()
        return cell

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; call .labels()")
        return self.labels()

    # unlabeled conveniences
    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def _samples(self) -> list[dict]:
        with self._lock:
            items = sorted(self._cells.items())
        out = []
        for key, cell in items:
            labels = dict(zip(self.labelnames, key))
            if self.kind == HISTOGRAM:
                counts, sum_, count = cell.snap()  # type: ignore[union-attr]
                out.append({"labels": labels, "counts": counts,
                            "sum": sum_, "count": count})
            else:
                out.append({"labels": labels,
                            "value": cell.value})  # type: ignore[union-attr]
        return out


class MetricsRegistry:
    """Thread-safe family container + weakly-registered polled sources.

    ``snapshot()`` is the one read path: direct families plus the
    source-derived families, as plain JSON-able dicts — the gateway's
    ``/stats`` payload, the fleet spool format, and the input to
    :func:`render_exposition` are all this one shape.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}
        self._sources: list[tuple[str, weakref.ref]] = []

    # ---- family factories (get-or-create; shape mismatch raises) ----

    def _family(self, name: str, kind: str, help_: str,
                labelnames: tuple[str, ...],
                buckets: Optional[tuple[float, ...]] = None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name} re-declared with a different "
                        "shape")
                return fam
            fam = Family(name, kind, help_, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> Family:
        return self._family(name, COUNTER, help_, tuple(labels))

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> Family:
        return self._family(name, GAUGE, help_, tuple(labels))

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Family:
        return self._family(
            name, HISTOGRAM, help_, tuple(labels),
            tuple(buckets) if buckets is not None else None)

    # ---- polled sources ----

    def register_source(self, kind: str, obj: object) -> None:
        """Weakly register a stat source (``kind`` one of "cache",
        "pipeline", "health", "scrub", "repair", "xor_schedule",
        "qos"); its
        ``stats()`` (``info()`` for the xor-schedule cache) snapshot is
        folded into every registry snapshot while the object lives.
        Registration never extends the object's lifetime, so per-loop
        caches and sweep-pinned pipelines drop out with their owners."""
        with self._lock:
            self._sources = [(k, r) for k, r in self._sources
                             if r() is not None]
            for k, r in self._sources:
                if k == kind and r() is obj:
                    return
            self._sources.append((kind, weakref.ref(obj)))

    def _live_sources(self, kind: str) -> list:
        with self._lock:
            return [r() for k, r in self._sources
                    if k == kind and r() is not None]

    # ---- snapshot / render ----

    def snapshot(self) -> dict:
        fams: list[dict] = []
        with self._lock:
            direct = sorted(self._families.items())
        for _name, fam in direct:
            entry: dict = {"name": fam.name, "type": fam.kind,
                           "help": fam.help,
                           "samples": fam._samples()}
            if fam.buckets is not None:
                entry["buckets"] = list(fam.buckets)
            fams.append(entry)
        fams.extend(_source_families(self))
        fams.sort(key=lambda f: f["name"])
        return {"families": fams}

    def render(self) -> str:
        return render_exposition(self.snapshot())


# ---- the process-global registry ----

_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry (one per gateway worker process —
    fleet-wide aggregation happens at scrape via the snapshot spool,
    see gateway/workers.py)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def swap_registry(registry: Optional[MetricsRegistry]
                  ) -> Optional[MetricsRegistry]:
    """Swap the process registry for ``registry`` (None = a fresh one
    on next :func:`get_registry`); returns the previous registry so
    callers can restore it.  The cluster simulator brackets every
    scenario run with this so two runs of the same seed observe — and
    can compare — exactly the counters that run produced; production
    code never calls it."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        previous = _REGISTRY
        _REGISTRY = registry
    return previous


# ---- event-recorded helpers (Profiler / gateway call these) ----

#: closed label sets for the request/IO series (CB107: anything outside
#: the set clamps to "other" rather than minting a new label value)
_METHODS = frozenset(("GET", "HEAD", "PUT", "POST", "DELETE"))
_SOURCES = frozenset(("cache", "sendfile", "cond", "meta", "store", "-"))


def _status_class(status: int) -> str:
    return f"{status // 100}xx" if 100 <= status <= 599 else "other"


#: cached Family handles for the event-recorded series, built on first
#: use — per-event resolution through the registry would serialize the
#: hot serve path on the one registry lock; the families are fixed, so
#: cache them once (the build race is benign: the registry's
#: get-or-create hands every builder the same Family objects)
# lint: loop-shared-ok deliberate process-wide cache of process-wide
# Family singletons; Family cells are themselves lock-guarded
_EVENT_FAMILIES: dict[str, Family] = {}


def _event_family(key: str, build) -> Family:
    fam = _EVENT_FAMILIES.get(key)
    if fam is None:
        fam = _EVENT_FAMILIES[key] = build(get_registry())
    return fam


def record_request(method: str, status: int, nbytes: int,
                   duration: float, source: str) -> None:
    """One gateway request into the registry (the event-recorded twin
    of ``Profiler.log_request`` — same numbers, durable series)."""
    method = method if method in _METHODS else "OTHER"
    source = source if source in _SOURCES else "other"
    status_class = _status_class(status)
    _event_family("req_seconds", lambda reg: reg.histogram(
        "cb_request_seconds", "gateway request wall time",
        labels=("method",))).labels(method=method).observe(duration)
    _event_family("req_total", lambda reg: reg.counter(
        "cb_request_total", "gateway requests served",
        labels=("method", "status_class", "source"),
    )).labels(method=method, status_class=status_class,
              source=source).inc()
    _event_family("req_bytes", lambda reg: reg.counter(
        "cb_request_bytes_total", "gateway response body bytes",
        labels=("method",))).labels(method=method).inc(max(nbytes, 0))


def record_io(op: str, ok: bool, nbytes: int, duration: float) -> None:
    """One location I/O completion (``Profiler.log_read``/``log_write``)."""
    op = op if op in ("read", "write") else "other"
    ok_label = "true" if ok else "false"
    _event_family("io_seconds", lambda reg: reg.histogram(
        "cb_io_seconds", "location I/O wall time",
        labels=("op", "ok"))).labels(op=op, ok=ok_label).observe(duration)
    if ok:
        _event_family("io_bytes", lambda reg: reg.counter(
            "cb_io_bytes_total", "location I/O bytes moved",
            labels=("op",))).labels(op=op).inc(max(nbytes, 0))


def record_build_info(version: str, backend: str, flags: dict,
                      registry: Optional[MetricsRegistry] = None
                      ) -> None:
    """Publish the ``cb_build_info`` static-info gauge: value 1, with
    the process's version, erasure backend, and active tunable flags as
    labels.  The point is the FLEET view: gauges gain a ``worker``
    label in the spool merge, so one ``/metrics`` scrape of a
    supervisor fleet shows exactly which worker runs which version and
    configuration — a mixed-version or mixed-flag rollout is visible at
    a glance instead of invisible until it bites.

    Labels are CB107-closed by construction: ``version`` is the baked
    package version, ``backend`` comes from cluster config, and every
    flag value is a closed token ("on"/"off", a KNOWN_CODES member) —
    the caller clamps, like ``record_request`` does."""
    labels = {"version": str(version), "backend": str(backend or "auto")}
    for key in sorted(flags):
        labels[str(key)] = str(flags[key])
    (registry or get_registry()).gauge(
        "cb_build_info",
        "build/configuration identity (value is always 1)",
        labels=tuple(labels),
    ).labels(**labels).set(1)


def record_dropped(kind: str, n: int = 1) -> None:
    """Ring-buffer drop accounting (``Profiler``'s bounded logs)."""
    kind = kind if kind in ("requests", "entries", "location_failures") \
        else "other"
    _event_family("dropped", lambda reg: reg.counter(
        "cb_profiler_dropped_total",
        "profiler log entries dropped by the bounded ring buffers",
        labels=("kind",))).labels(kind=kind).inc(n)


# ---- polled-source adapters ----


def _sum_rows(rows: Iterable[dict], keys: Sequence[str]) -> dict:
    out = {k: 0.0 for k in keys}
    for row in rows:
        for k in keys:
            out[k] += float(row.get(k, 0) or 0)
    return out


def _fam(name: str, kind: str, help_: str, samples: list[dict]) -> dict:
    return {"name": name, "type": kind, "help": help_,
            "samples": samples}


def _scalar(value: float, **labels: str) -> dict:
    return {"labels": labels, "value": float(value)}


def _source_families(reg: MetricsRegistry) -> list[dict]:
    """Fold the live registered sources into snapshot families.
    Multiple same-kind sources in one process (per-loop caches, a
    sweep's pinned pipelines) sum — these are process totals, the
    per-instance view stays in the Profiler stanzas."""
    fams: list[dict] = []

    caches = [c.stats().to_obj() for c in reg._live_sources("cache")]
    if caches:
        s = _sum_rows(caches, ("hits", "misses", "coalesced", "inserts",
                               "evictions", "rejects", "size_bytes",
                               "capacity_bytes", "entries"))
        for key in ("hits", "misses", "coalesced", "inserts",
                    "evictions", "rejects"):
            fams.append(_fam(f"cb_cache_{key}_total", COUNTER,
                             f"chunk cache {key}", [_scalar(s[key])]))
        for key in ("size_bytes", "capacity_bytes", "entries"):
            fams.append(_fam(f"cb_cache_{key}", GAUGE,
                             f"chunk cache {key}", [_scalar(s[key])]))

    pipes = [p.stats().to_obj() for p in reg._live_sources("pipeline")]
    if pipes:
        fams.append(_fam("cb_pipeline_threads", GAUGE,
                         "host pipeline worker threads",
                         [_scalar(sum(p["threads"] for p in pipes))]))
        fams.append(_fam("cb_pipeline_idle_seconds_total", COUNTER,
                         "host pipeline worker idle seconds",
                         [_scalar(sum(p["idle_s"] for p in pipes))]))
        stages: dict[str, dict] = {}
        for p in pipes:
            for st in p["stages"]:
                agg = stages.setdefault(
                    st["stage"], {"jobs": 0.0, "busy_s": 0.0,
                                  "nbytes": 0.0})
                agg["jobs"] += st["jobs"]
                agg["busy_s"] += st["busy_s"]
                agg["nbytes"] += st["nbytes"]
        for metric, key, help_ in (
                ("cb_pipeline_jobs_total", "jobs",
                 "host pipeline jobs run"),
                ("cb_pipeline_busy_seconds_total", "busy_s",
                 "host pipeline busy seconds"),
                ("cb_pipeline_bytes_total", "nbytes",
                 "host pipeline bytes processed")):
            fams.append(_fam(metric, COUNTER, help_, [
                _scalar(agg[key], stage=stage)
                for stage, agg in sorted(stages.items())]))

    healths = [h.stats().to_obj() for h in reg._live_sources("health")]
    if healths:
        hsum = _sum_rows(healths, ("hedges_fired", "hedges_won",
                                   "hedges_cancelled", "primaries"))
        for key in ("hedges_fired", "hedges_won", "hedges_cancelled"):
            fams.append(_fam(f"cb_{key}_total", COUNTER,
                             f"hedged reads: {key.replace('_', ' ')}",
                             [_scalar(hsum[key])]))
        # the budget denominator, exported so the SLO engine's
        # hedge-exhaustion rule (obs/slo.py) evaluates EXACTLY the
        # scoreboard's amplification bound: fired <= ratio*primaries
        # + burst — fired/primaries sustained at the slope means the
        # budget is pinned at its cap
        fams.append(_fam("cb_hedge_primaries_total", COUNTER,
                         "primary (non-hedge) chunk fetches — the "
                         "hedge-budget accrual denominator",
                         [_scalar(hsum["primaries"])]))
        nodes: dict[str, dict] = {}
        for h in healths:
            for row in h["locations"]:
                # node keys come from cluster config (netloc / disk
                # root) — a closed set, CB107-legal as a label
                agg = nodes.get(row["node"])
                if agg is None:
                    nodes[row["node"]] = dict(row)
                else:
                    agg["completions"] += row["completions"]
                    agg["errors"] += row["errors"]
                    agg["inflight"] += row["inflight"]
        breaker_rank = {"closed": 0, "half-open": 1, "open": 2}
        for metric, kind, key, help_ in (
                ("cb_node_completions_total", COUNTER, "completions",
                 "location completions recorded"),
                ("cb_node_errors_total", COUNTER, "errors",
                 "location errors recorded"),
                ("cb_node_inflight", GAUGE, "inflight",
                 "location I/Os in flight"),
                ("cb_node_err_rate", GAUGE, "err_rate",
                 "location error-rate EWMA")):
            fams.append(_fam(metric, kind, help_, [
                _scalar(row[key], node=node)
                for node, row in sorted(nodes.items())]))
        fams.append(_fam(
            "cb_node_ewma_seconds", GAUGE,
            "location latency EWMA (successes)", [
                _scalar((row["ewma_ms"] or 0.0) / 1000.0, node=node)
                for node, row in sorted(nodes.items())]))
        fams.append(_fam(
            "cb_node_breaker_state", GAUGE,
            "breaker state (0 closed, 1 half-open, 2 open)", [
                _scalar(breaker_rank.get(row["breaker"], 2), node=node)
                for node, row in sorted(nodes.items())]))

    repairs = [r.stats().to_obj() for r in reg._live_sources("repair")]
    if repairs:
        # plan kinds, helper-read sources AND erasure codes are CLOSED
        # label sets (CB107): copy = 1x from a healthy replica, decode
        # = ranged reads off d helpers, msr = pm-msr β-projection
        # regeneration, fallback = handed to full resilver; codes come
        # from cluster.repair.CODES
        by_code: dict[str, dict] = {}
        for r in repairs:
            for code, counters in (r.get("by_code") or {}).items():
                agg = by_code.setdefault(code, {})
                for key, value in counters.items():
                    agg[key] = agg.get(key, 0.0) + float(value or 0)
        codes = sorted(by_code)
        fams.append(_fam("cb_repair_plans_total", COUNTER,
                         "repair plans executed by kind and code", [
                             _scalar(by_code[c].get(f"plans_{kind}", 0),
                                     kind=kind, code=c)
                             for c in codes
                             for kind in ("copy", "decode", "msr",
                                          "fallback")]))
        fams.append(_fam("cb_repair_helper_bytes_total", COUNTER,
                         "bytes read off helpers for repair by source "
                         "and code", [
                             _scalar(by_code[c].get(
                                 f"helper_bytes_{src}", 0),
                                 source=src, code=c)
                             for c in codes
                             for src in ("replica", "decode", "msr")]))
        for key, help_ in (
                ("bytes_localized",
                 "victim bytes re-read to localize damage"),
                ("bytes_rebuilt", "damaged bytes rebuilt in place"),
                ("bytes_written", "repair bytes written to victims"),
                ("ranges_rebuilt", "damaged ranges rebuilt"),
                ("verify_failures",
                 "rebuilt chunks failing the end-to-end hash gate")):
            fams.append(_fam(f"cb_repair_{key}_total", COUNTER,
                             f"repair planner: {help_}",
                             [_scalar(by_code[c].get(key, 0), code=c)
                              for c in codes]))

    scrubs = [s.stats().to_obj() for s in reg._live_sources("scrub")]
    if scrubs:
        s = _sum_rows(scrubs, ("passes", "files_scanned",
                               "chunks_scanned", "bytes_verified",
                               "corrupt", "unavailable", "repaired",
                               "repair_failures"))
        for key in ("passes", "files_scanned", "chunks_scanned",
                    "bytes_verified", "corrupt", "unavailable",
                    "repaired", "repair_failures"):
            fams.append(_fam(f"cb_scrub_{key}_total", COUNTER,
                             f"scrub {key.replace('_', ' ')}",
                             [_scalar(s[key])]))
        fams.append(_fam("cb_scrub_running", GAUGE,
                         "scrub daemon running", [_scalar(
                             sum(1 for x in scrubs if x["running"]))]))
        fams.append(_fam("cb_scrub_rate_bytes_per_sec", GAUGE,
                         "scrub byte-rate bound", [_scalar(
                             sum(x["rate_bytes_per_sec"]
                                 for x in scrubs))]))

    qoses = [q.stats().to_obj() for q in reg._live_sources("qos")]
    if qoses:
        # the ``tenant`` label values come from the scheduler's CLOSED
        # table (named YAML tenants + "other", cluster/qos.py) — the
        # only place tenant names exist, so nothing here can mint one
        # (CB107); per-worker schedulers sum in the fleet merge like
        # every counter family
        tenants: dict[str, dict] = {}
        for q in qoses:
            for name, row in q["tenants"].items():
                agg = tenants.setdefault(
                    name, {"admitted": 0.0, "shed": 0.0, "bytes": 0.0,
                           "throttle_waits": 0.0, "queued": 0.0})
                for key in agg:
                    agg[key] += float(row.get(key, 0) or 0)
        for metric, key, kind, help_ in (
                ("cb_qos_admitted_total", "admitted", COUNTER,
                 "QoS admissions granted"),
                ("cb_qos_shed_total", "shed", COUNTER,
                 "QoS admissions shed (queue full / wait deadline)"),
                ("cb_qos_bytes_total", "bytes", COUNTER,
                 "QoS bytes admitted"),
                ("cb_qos_throttle_waits_total", "throttle_waits",
                 COUNTER, "QoS per-tenant rate-bucket waits"),
                ("cb_qos_queued", "queued", GAUGE,
                 "QoS waiters currently queued")):
            fams.append(_fam(metric, kind, help_, [
                _scalar(agg[key], tenant=tenant)
                for tenant, agg in sorted(tenants.items())]))
        qsum = _sum_rows(qoses, ("hedge_suppressed",
                                 "hedge_conserved"))
        fams.append(_fam("cb_qos_hedge_suppressed_total", COUNTER,
                         "hedge launches suppressed under admission "
                         "pressure", [_scalar(qsum["hedge_suppressed"])]))
        fams.append(_fam("cb_qos_hedge_conserved_total", COUNTER,
                         "hedge budget conserved on ample p99 headroom",
                         [_scalar(qsum["hedge_conserved"])]))
        fams.append(_fam("cb_qos_pressure", GAUGE,
                         "gateway admission pressure [0,1]",
                         [_scalar(max(q["pressure"] for q in qoses))]))

    scheds = [s.info() for s in reg._live_sources("xor_schedule")]
    if scheds:
        s = _sum_rows(scheds, ("hits", "misses", "evictions", "size"))
        for key in ("hits", "misses", "evictions"):
            fams.append(_fam(f"cb_xor_schedule_{key}_total", COUNTER,
                             f"scheduled-XOR program cache {key} "
                             "(ops/xor_schedule.py LRU)",
                             [_scalar(s[key])]))
        fams.append(_fam("cb_xor_schedule_entries", GAUGE,
                         "scheduled-XOR program cache entries",
                         [_scalar(s["size"])]))

    return fams


# ---- exposition ----


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _label_str(labels: dict, extra: Optional[tuple[str, str]] = None
               ) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"' for k, v in items)
    return "{" + inner + "}"


def render_exposition(snapshot: dict) -> str:
    """Prometheus text exposition of a snapshot (one worker's, or the
    merged fleet's).  Histogram buckets cumulate here; every family
    gets exactly one HELP/TYPE pair."""
    lines: list[str] = []
    for fam in snapshot["families"]:
        name, kind = fam["name"], fam["type"]
        help_ = fam.get("help") or name
        lines.append(f"# HELP {name} "
                     f"{help_.replace(chr(10), ' ').strip()}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == HISTOGRAM:
            bounds = fam.get("buckets") or []
            for s in fam["samples"]:
                cum = 0
                for bound, c in zip(list(bounds) + [math.inf],
                                    s["counts"]):
                    cum += c
                    le = "+Inf" if math.isinf(bound) else _fmt(bound)
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(s['labels'], ('le', le))} {cum}")
                lines.append(f"{name}_sum{_label_str(s['labels'])} "
                             f"{_fmt(s['sum'])}")
                lines.append(f"{name}_count{_label_str(s['labels'])} "
                             f"{cum}")
        else:
            for s in fam["samples"]:
                lines.append(f"{name}{_label_str(s['labels'])} "
                             f"{_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(.*)\})?"
    r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|\+Inf|-Inf|NaN))$")
_LABEL_PAIR_RE = re.compile(
    r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\["\\n])*)"$')


def _split_labels(raw: str, lineno: int) -> dict:
    labels: dict[str, str] = {}
    # split on commas outside quotes
    parts, buf, in_q, esc = [], [], False, False
    for ch in raw:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            buf.append(ch)
            continue
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    for part in parts:
        m = _LABEL_PAIR_RE.match(part.strip())
        if not m:
            raise ExpositionError(
                f"line {lineno}: bad label pair {part!r}")
        if m.group(1) in labels:
            raise ExpositionError(
                f"line {lineno}: duplicate label {m.group(1)!r}")
        labels[m.group(1)] = m.group(2)
    return labels


def parse_exposition(text: str) -> dict:
    """Strict line-grammar check of a Prometheus text payload; raises
    :class:`ExpositionError` on any violation and returns
    ``{family: {"type", "samples": [(labels, value)]}}`` on success.
    The tests and the CI ``/metrics`` scrape step run this, so the
    grammar the gateway emits is pinned, not assumed.

    Beyond the per-line grammar it checks family-level invariants:
    every sample's base name carries a preceding TYPE, counter values
    are finite and non-negative, histogram bucket counts are cumulative
    non-decreasing over ascending ``le`` bounds ending at ``+Inf``, and
    ``_count`` equals the ``+Inf`` bucket."""
    types: dict[str, str] = {}
    helps: set[str] = set()
    samples: dict[str, list] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name = rest.split(" ", 1)[0]
            if not _NAME_RE.match(name):
                raise ExpositionError(f"line {lineno}: bad HELP name")
            if name in helps:
                raise ExpositionError(
                    f"line {lineno}: duplicate HELP for {name}")
            helps.add(name)
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):].split(" ")
            if len(rest) != 2 or rest[1] not in (COUNTER, GAUGE,
                                                 HISTOGRAM):
                raise ExpositionError(f"line {lineno}: bad TYPE line")
            name = rest[0]
            if not _NAME_RE.match(name) or name in types:
                raise ExpositionError(
                    f"line {lineno}: bad/duplicate TYPE for {name}")
            types[name] = rest[1]
            continue
        if line.startswith("#"):
            raise ExpositionError(
                f"line {lineno}: unknown comment form")
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ExpositionError(f"line {lineno}: bad sample line "
                                  f"{line!r}")
        name, raw_labels, raw_value = m.groups()
        labels = _split_labels(raw_labels, lineno) if raw_labels else {}
        value = float(raw_value.replace("+Inf", "inf").replace(
            "-Inf", "-inf").replace("NaN", "nan"))
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            trimmed = name[: -len(suffix)] if name.endswith(suffix) \
                else None
            if trimmed and types.get(trimmed) == HISTOGRAM:
                base = trimmed
                break
        if base not in types:
            raise ExpositionError(
                f"line {lineno}: sample {name} has no TYPE")
        if types[base] == COUNTER and not (value >= 0
                                           and math.isfinite(value)):
            raise ExpositionError(
                f"line {lineno}: counter {name} value {raw_value}")
        samples.setdefault(base, []).append((name, labels, value))
    # histogram family invariants
    for base, kind in types.items():
        if kind != HISTOGRAM:
            continue
        rows = samples.get(base, [])
        series: dict[tuple, dict] = {}
        for name, labels, value in rows:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            st = series.setdefault(key, {"buckets": [], "sum": None,
                                         "count": None})
            if name == base + "_bucket":
                if "le" not in labels:
                    raise ExpositionError(
                        f"{base}_bucket missing le label")
                le = float(labels["le"].replace("+Inf", "inf"))
                st["buckets"].append((le, value))
            elif name == base + "_sum":
                st["sum"] = value
            elif name == base + "_count":
                st["count"] = value
        for key, st in series.items():
            bkts = st["buckets"]
            if not bkts or not math.isinf(bkts[-1][0]):
                raise ExpositionError(
                    f"{base}: histogram series must end at le=+Inf")
            les = [b[0] for b in bkts]
            counts = [b[1] for b in bkts]
            if les != sorted(les) or len(set(les)) != len(les):
                raise ExpositionError(f"{base}: le bounds not ascending")
            if counts != sorted(counts):
                raise ExpositionError(
                    f"{base}: bucket counts not cumulative")
            if st["sum"] is None or st["count"] is None:
                raise ExpositionError(f"{base}: missing _sum/_count")
            if st["count"] != counts[-1]:
                raise ExpositionError(
                    f"{base}: _count != le=+Inf bucket")
    out = {}
    for base, kind in types.items():
        out[base] = {"type": kind, "samples": samples.get(base, [])}
    return out


def find_family(snapshot: dict, name: str) -> Optional[dict]:
    """The one family-by-name lookup over a snapshot's ``families``
    list — shared by the SLO engine's windowed views (obs/slo.py) and
    the stats CLI's renderer, so a future snapshot-schema change has
    exactly one scan to update."""
    for fam in snapshot.get("families", ()):
        if fam.get("name") == name:
            return fam
    return None


def histogram_quantile(bounds: Sequence[float], counts: Sequence[int],
                       q: float) -> float:
    """Approximate quantile from per-bucket (non-cumulative) counts —
    linear interpolation inside the winning bucket, like the percentile
    helper in file/profiler.py but over aggregated buckets instead of
    raw samples (the fleet view has no raw samples)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = (q / 100.0) * total
    cum = 0.0
    lo = 0.0
    for bound, c in zip(list(bounds) + [math.inf], counts):
        if c > 0 and cum + c >= rank:
            hi = bound if math.isfinite(bound) else lo * 2 or 1.0
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
        if math.isfinite(bound):
            lo = bound
    return lo


# ---- fleet aggregation (the multi-worker gateway's merge) ----


def merge_snapshots(entries: Sequence[tuple[Optional[str], dict]]
                    ) -> dict:
    """Aggregate per-worker snapshots into one fleet view: counters and
    histograms SUM by (name, labels); gauges gain a ``worker`` label so
    per-worker levels stay distinguishable (summing cache sizes across
    partitioned caches would hide one worker's runaway).  ``entries``
    is ``[(worker_id, snapshot)]``; a worker_id of None leaves gauges
    unlabeled (the single-process case)."""
    fams: dict[str, dict] = {}
    for worker_id, snap in entries:
        for fam in snap.get("families", ()):
            name, kind = fam["name"], fam["type"]
            out = fams.get(name)
            if out is None:
                out = fams[name] = {
                    "name": name, "type": kind,
                    "help": fam.get("help", ""), "samples": [],
                    "_index": {}}
                if "buckets" in fam:
                    out["buckets"] = list(fam["buckets"])
            if out["type"] != kind:
                raise ValueError(f"{name}: type mismatch across workers")
            if kind == HISTOGRAM and out.get("buckets") != list(
                    fam.get("buckets", [])):
                raise ValueError(
                    f"{name}: bucket layout mismatch across workers")
            for s in fam["samples"]:
                labels = dict(s["labels"])
                if kind == GAUGE and worker_id is not None:
                    labels["worker"] = str(worker_id)
                key = tuple(sorted(labels.items()))
                existing = out["_index"].get(key)
                if existing is None:
                    merged = {"labels": labels}
                    if kind == HISTOGRAM:
                        merged["counts"] = list(s["counts"])
                        merged["sum"] = s["sum"]
                        merged["count"] = s.get(
                            "count", sum(s["counts"]))
                    else:
                        merged["value"] = s["value"]
                    out["_index"][key] = merged
                    out["samples"].append(merged)
                elif kind == HISTOGRAM:
                    existing["counts"] = [
                        a + b for a, b in zip(existing["counts"],
                                              s["counts"])]
                    existing["sum"] += s["sum"]
                    existing["count"] += s.get("count",
                                               sum(s["counts"]))
                else:  # counters sum; same-label gauges sum too
                    existing["value"] += s["value"]
    out_fams = []
    for name in sorted(fams):
        fam = fams[name]
        fam.pop("_index")
        fam["samples"].sort(key=lambda s: sorted(s["labels"].items()))
        out_fams.append(fam)
    return {"families": out_fams}


# ---- snapshot spool (per-worker files the fleet merge reads) ----


def write_snapshot_file(path: str, snapshot: dict) -> None:
    """Atomically publish one worker's snapshot (tmp + rename, the same
    publication discipline as chunk files).  Blocking — call off-loop."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snapshot, f, separators=(",", ":"))
    os.replace(tmp, path)


def load_spool(spool_dir: str) -> list[tuple[str, dict]]:
    """Read every worker snapshot in the spool; corrupt/torn files are
    skipped (the writer republishes within a heartbeat).  Blocking —
    call off-loop."""
    out: list[tuple[str, dict]] = []
    try:
        names = sorted(os.listdir(spool_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("worker-") and name.endswith(".json")):
            continue
        wid = name[len("worker-"):-len(".json")]
        try:
            with open(os.path.join(spool_dir, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(snap, dict) and "families" in snap:
            out.append((wid, snap))
    return out


def fleet_snapshot(spool_dir: str,
                   own: Optional[tuple[str, dict]] = None) -> dict:
    """The merged fleet snapshot: every spooled worker snapshot, with
    ``own`` (the scraped worker's LIVE snapshot) replacing its possibly
    stale spool entry.  Blocking — call off-loop."""
    entries = load_spool(spool_dir)
    if own is not None:
        entries = [(wid, snap) for wid, snap in entries
                   if wid != own[0]]
        entries.append(own)
    return merge_snapshots(entries)


# ---- event-loop lag (the always-on cousin of the sanitizer watchdog) ----


class LoopLagMonitor:
    """Cheap always-on event-loop scheduling-delay sampler: a chained
    ``call_later`` measures how late each tick fires and feeds the
    ``cb_eventloop_lag_seconds`` histogram — the production-grade
    cousin of the opt-in sanitizer's stall watchdog (which needs a
    whole sampling thread because it must catch a loop that never runs
    callbacks at all; this one just prices the delay of a loop that
    does).  A timer handle, not a task — nothing to leak, nothing for
    the task registry to track."""

    INTERVAL = 0.25

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval: float = INTERVAL) -> None:
        self._hist = (registry or get_registry()).histogram(
            "cb_eventloop_lag_seconds",
            "event-loop callback scheduling delay")
        self._interval = interval
        self._handle = None
        self._loop = None
        self._expected = 0.0
        self._stopped = False

    def start(self, loop) -> None:
        self._loop = loop
        # lint: clock-escape-ok loop lag is defined against the loop's
        # OWN clock; under sim the virtual loop makes this virtual too
        self._expected = loop.time() + self._interval
        self._handle = loop.call_later(self._interval, self._tick)

    def _tick(self) -> None:
        if self._stopped or self._loop is None:
            return
        now = self._loop.time()
        self._hist.observe(max(now - self._expected, 0.0))
        self._expected = now + self._interval
        self._handle = self._loop.call_later(self._interval, self._tick)

    def stop(self) -> None:
        self._stopped = True
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
