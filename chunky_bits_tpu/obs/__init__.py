"""Observability plane: unified metrics registry + request tracing.

A TPU-repo extension (the reference has no metrics surface at all —
src/file/profiler.rs renders one-shot report strings and that is the
whole story): ``obs.metrics`` is the process-wide, thread-safe sink
behind every existing stat source (chunk cache, host pipeline, health
scoreboard, scrub daemon, the gateway access log), exposed as
Prometheus text at gateway ``GET /metrics`` and JSON at ``GET /stats``;
``obs.tracing`` follows one request across the async plane, the host
pipeline's worker threads, and the network fetches, into a bounded
slowest-N buffer served at ``GET /debug/traces``.

Both modules are stdlib-only and import nothing from the rest of the
package, so every layer (file/, parallel/, cluster/, gateway/) may feed
them without import cycles, and the linter (which must run with the
tunnel down and no third-party deps) can scan them like any other
module.
"""

from chunky_bits_tpu.obs import metrics, tracing  # noqa: F401
