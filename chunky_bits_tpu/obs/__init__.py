"""Observability plane: unified metrics registry + request tracing.

A TPU-repo extension (the reference has no metrics surface at all —
src/file/profiler.rs renders one-shot report strings and that is the
whole story): ``obs.metrics`` is the process-wide, thread-safe sink
behind every existing stat source (chunk cache, host pipeline, health
scoreboard, scrub daemon, the gateway access log), exposed as
Prometheus text at gateway ``GET /metrics`` and JSON at ``GET /stats``;
``obs.tracing`` follows one request across the async plane, the host
pipeline's worker threads, and the network fetches, into a bounded
slowest-N buffer served at ``GET /debug/traces``.

``obs.slo`` is the windowed layer on top of the registry: burn-rate
SLO rules over a bounded snapshot ring, the pending→firing→resolved
alert state machine behind gateway ``GET /alerts``, and the
simulator-verified detection verdicts (sim/scenario.py runs the same
engine in virtual time).

All three modules are stdlib-only and import nothing from the rest of
the package (``obs.slo`` reads time through the clock seam's
stdlib-only implementation half, ``utils/clock.py`` — the same
cycle-hygiene import file/profiler.py uses), so every layer (file/,
parallel/, cluster/, gateway/, sim/) may feed them without import
cycles, and the linter (which must run with the tunnel down and no
third-party deps) can scan them like any other module.
"""

from chunky_bits_tpu.obs import metrics, tracing  # noqa: F401
