"""Indexed metadata plane: append-only namespace log + compacting index.

A TPU-repo extension beyond the reference (``Chunky-Bits`` keeps one
YAML file per file reference, src/cluster/metadata.rs:94-205): at the
ROADMAP's north-star scale (10^5-10^6 objects) file-per-ref turns the
*namespace* into the bottleneck — every ``list`` is a dirent walk,
every scrub/GC pass re-opens and re-parses one file per object, and a
recursive listing costs O(objects) syscalls before a single chunk is
touched.  This module does for metadata exactly what ``file/slab.py``
did for chunks: refs are appended to a few large log files, the
name -> (offset/len, publish generation, publish time, tombstone)
mapping lives in an append-only journal + an in-memory compacting
index, and every namespace question (``list``, prefix scan, scrub
pre-scan, GC candidate walk) becomes an index scan with zero dirents
and zero per-entry parses.

On-disk layout, rooted at a directory::

    <root>/refs-000001.log   append-only serialized ref bytes (no framing)
    <root>/meta.jsonl        append-only index journal, one JSON/line
    <root>/.lock             flock target for cross-process appends

Journal records (one complete JSON line each)::

    {"o": "p", "n": <name>, "g": <gen>, "s": <log>, "f": <off>,
     "l": <len>, "t": <unix>,
     "h": [<hash>...], "nk": [[<kind>, <node>]...]}    publish
    {"o": "d", "n": <name>, "g": <gen>, "t": <unix>}   tombstone
    {"o": "g", "g": <gen>}             generation floor (compaction)

The optional ``h``/``nk`` fields are the *index projection* of a file
reference: its chunk hashes in display form (``sha256-<hex>``) and the
health-scoreboard node keys (``cluster.health.location_key``) of every
replica, extracted at publish time.  They are what turns the scrub
priority pre-scan and the GC liveness walk into pure index scans —
zero ref reads, zero parses (:meth:`MetadataLog.namespace_nodes` /
:meth:`MetadataLog.namespace_hashes`).  Non-file-reference payloads
publish without them, and any live entry missing a projection makes
the corresponding fast path report "unavailable" so consumers fall
back to the full snapshot read — correctness never depends on the
projection being present.

Publication protocol — the slab discipline with the metadata plane's
STRONGER durability contract: metadata publication is the cluster's
WRITE ACKNOWLEDGMENT (``MetadataPath.write`` fsyncs its temp and the
directory for the same reason), so unlike the slab's flush-only chunk
appends every publish here is power-loss durable before it returns:
ref bytes are appended to the active log and **fsync'd**, THEN the
journal line is appended in a single write and **fsync'd**, with a
directory fsync whenever the append created a file.  A crashed writer
leaves at worst unreferenced log tail bytes (reclaimed by compaction)
and possibly a torn final journal line — ignored by every reader (the
parser consumes whole lines only) and terminated by the next append.
A short append (ENOSPC mid-write) truncates its partial tail back off
the log before surfacing, so offset accounting never packs around
garbage.  The crash harness replays every crash point of the
append/commit/compact protocols under kill/torn/power-cut models and
verifies the oracles machine-checked (``sim/crash.py``
``meta_log_append``/``meta_log_compact``, tests/test_crash.py): acked
publishes survive both power-cut extremes, torn tails are terminated,
compaction leaves old-or-new-never-neither.

Generations: every publish/tombstone carries a monotonically
increasing per-store generation.  ``changes(since_generation)`` is the
bounded tail feed the scrub daemon uses to prioritize recently-written
objects; compaction writes a ``{"o": "g"}`` floor record so the
counter never runs backwards across a journal swap (a consumer's
``since`` cursor stays valid).

Concurrency: in-process access is serialized by a ``threading.Lock``
(the store's methods are synchronous — async callers hop through
``asyncio.to_thread``); cross-process appenders (pre-forked gateway
workers share one metadata root) serialize on ``flock(<root>/.lock)``
around the append+journal commit, reusing the slab's ``_Flock``.
Readers take no lock: extents are write-once and index refresh
tolerates a torn tail.  Compaction republishes live refs into fresh
log files and swaps the journal in by atomic rename, exactly like
``SlabStore.compact``.

``MetadataLog`` (bottom) is the async ``MetadataStore`` kind —
``metadata: {type: meta-log, ...}`` in cluster YAML
(``metadata_from_obj`` selects it; ``kind:`` is accepted as an alias
tag) — serving the same ``write``/``read``/``list``/``to_obj``
contract as ``MetadataPath``, so Cluster, gateway, CLI, scrub, repair
and sim need zero call-site changes.  On top of it: O(index)
``namespace_snapshot()`` (each ref's bytes read at most once from the
log, grouped by log file) and ``changes()``.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from typing import NamedTuple, Optional

import yaml

from chunky_bits_tpu.errors import (
    LocationError,
    MetadataReadError,
    SerdeError,
)
from chunky_bits_tpu.file.slab import _Flock
from chunky_bits_tpu.utils import fsio as _fsio

#: rollover threshold for the active ref log; refs are small (KBs), so
#: 64 MiB packs ~10^4-10^5 refs per descriptor while keeping
#: compaction copies and snapshot read windows bounded
DEFAULT_LOG_MAX_BYTES = 64 << 20

JOURNAL_NAME = "meta.jsonl"
LOG_PREFIX = "refs-"
LOG_SUFFIX = ".log"

#: default bound on one ``changes()`` page — a tail feed, not a dump
DEFAULT_CHANGES_LIMIT = 1024


class MetaLogEntry(NamedTuple):
    """One name's latest state in the index."""

    generation: int
    log: str  # ref log basename ("" for a tombstone)
    offset: int
    length: int
    published: float  # unix time of the journal commit
    tombstone: bool
    #: index projection of the ref (journal ``h``/``nk``): chunk hashes
    #: in display form, and health node keys as (kind, node) pairs.
    #: None = published without one (foreign payload / older writer).
    hashes: Optional[tuple] = None
    nodes: Optional[tuple] = None


class ChangeRecord(NamedTuple):
    """One row of the ``changes(since_generation)`` tail feed."""

    name: str
    generation: int
    tombstone: bool
    published: float


class MetaLogError(OSError):
    """Store-level failure surfaced to the metadata plane (a subclass
    of OSError so the existing ``except OSError -> MetadataReadError``
    seams catch it unchanged)."""


def _parse_log_index(name: str) -> Optional[int]:
    if not (name.startswith(LOG_PREFIX) and name.endswith(LOG_SUFFIX)):
        return None
    digits = name[len(LOG_PREFIX):-len(LOG_SUFFIX)]
    if len(digits) == 6 and digits.isdigit():
        return int(digits)
    return None


def _log_name(index: int) -> str:
    return f"{LOG_PREFIX}{index:06d}{LOG_SUFFIX}"


def norm_name(path: str) -> str:
    """Canonical store key for a public path: normal components only
    (no traversal — the same rule as ``metadata._sub_path``), joined
    with "/".  "" is the namespace root."""
    return "/".join(p for p in str(path).split("/")
                    if p not in ("", ".", ".."))


def _parse_hashes(raw) -> Optional[tuple]:
    """Journal ``h`` field -> hashes tuple, None on absence/garbage."""
    if not isinstance(raw, list):
        return None
    return tuple(str(h) for h in raw)


def _parse_nodes(raw) -> Optional[tuple]:
    """Journal ``nk`` field -> ((kind, node), ...), None on
    absence/garbage — a malformed pair drops the whole projection (the
    consumer falls back to a full read) rather than a silently partial
    node set (which would mis-score the ref as healthier than it is)."""
    if not isinstance(raw, list):
        return None
    out = []
    for pair in raw:
        if not (isinstance(pair, (list, tuple)) and len(pair) == 2):
            return None
        out.append((str(pair[0]), str(pair[1])))
    return tuple(out)


def extract_index_meta(payload) -> tuple[Optional[list], Optional[list]]:
    """(chunk hashes, health node keys) of a file-reference payload, or
    (None, None) for anything that does not parse as one.  Runs at
    publish time — one ``FileReference.from_obj`` per write, amortized
    into the (fsync-bound) append — so every namespace-scale consumer
    afterwards reads the projection from the index instead of the log."""
    try:
        from chunky_bits_tpu.cluster.health import location_key
        from chunky_bits_tpu.file.file_reference import FileReference

        ref = FileReference.from_obj(payload)
        hashes: list[str] = []
        nodes: list[list[str]] = []
        seen: set = set()
        for part in ref.parts:
            for chunk in part.data + part.parity:
                hashes.append(str(chunk.hash))
                for location in chunk.locations:
                    key = location_key(location)
                    if key not in seen:
                        seen.add(key)
                        nodes.append([key[0], key[1]])
        return hashes, nodes
    # lint: broad-except-ok the projection is an optional accelerator:
    # ANY payload that is not a well-formed file reference (foreign
    # metadata, future schema) publishes without one and the fast
    # paths fall back — a failure here must never block the write ack
    except Exception:
        return None, None


class MetaLogStore:
    """One indexed metadata store rooted at a directory.

    Every method is synchronous (bounded local file I/O) — async
    callers hop through ``asyncio.to_thread``, the same discipline as
    ``SlabStore``.  Instances are process-shared per root
    (:func:`get_store`) so all loops and worker threads of a process
    see one coherent in-memory index.
    """

    def __init__(self, root: str,
                 log_max_bytes: int = DEFAULT_LOG_MAX_BYTES) -> None:
        self.root = os.path.abspath(root)
        self.log_max_bytes = int(log_max_bytes)
        self._lock = threading.Lock()
        #: latest state per name — live entries AND tombstones (the
        #: changes() feed needs deletions until compaction drops them)
        self._entries: dict[str, MetaLogEntry] = {}
        self._gen = 0
        self._dead_bytes = 0
        self._journal_pos = 0
        self._journal_id: Optional[int] = None
        self._loaded = False

    # ---- paths ----

    def journal_path(self) -> str:
        return os.path.join(self.root, JOURNAL_NAME)

    def log_path(self, log: str) -> str:
        return os.path.join(self.root, log)

    def log_files(self) -> list[str]:
        """Basenames of the ref log files currently on disk, ordered."""
        try:
            entries = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n for n in entries
                      if _parse_log_index(n) is not None)

    # ---- journal loading / refresh (identical discipline to
    #      SlabStore: whole lines only, torn tails unconsumed) ----

    def _reset_locked(self) -> None:
        self._entries.clear()
        self._gen = 0
        self._dead_bytes = 0
        self._journal_pos = 0
        self._journal_id = None

    def _apply_line_locked(self, line: bytes) -> None:
        try:
            obj = json.loads(line)
        except ValueError:
            return  # foreign garbage: skip, like the slab journal does
        op = obj.get("o")
        if op == "g":
            try:
                self._gen = max(self._gen, int(obj["g"]))
            except (KeyError, TypeError, ValueError):
                pass
            return
        name = obj.get("n")
        if not isinstance(name, str):
            return
        try:
            gen = int(obj.get("g", 0))
            stamp = float(obj.get("t", 0.0))
        except (TypeError, ValueError):
            return
        old = self._entries.get(name)
        if op == "p":
            try:
                entry = MetaLogEntry(gen, str(obj["s"]), int(obj["f"]),
                                     int(obj["l"]), stamp, False,
                                     _parse_hashes(obj.get("h")),
                                     _parse_nodes(obj.get("nk")))
            except (KeyError, TypeError, ValueError):
                return
        elif op == "d":
            entry = MetaLogEntry(gen, "", 0, 0, stamp, True)
        else:
            return
        if old is not None and not old.tombstone:
            self._dead_bytes += old.length
        self._entries[name] = entry
        self._gen = max(self._gen, gen)

    def _refresh_locked(self) -> None:
        """Apply journal bytes written since the last look (another
        process appended), or reload from scratch when the journal was
        swapped (compaction) or truncated."""
        path = self.journal_path()
        try:
            st = os.stat(path)
        except OSError:
            if self._loaded and self._journal_id is not None:
                self._reset_locked()  # journal vanished: empty store
            self._loaded = True
            return
        if (self._journal_id != st.st_ino
                or st.st_size < self._journal_pos):
            self._reset_locked()
            self._journal_id = st.st_ino
        self._loaded = True
        if st.st_size == self._journal_pos:
            return
        with open(path, "rb") as f:
            f.seek(self._journal_pos)
            tail = f.read()
        # whole lines only: a torn final line (crashed writer) stays
        # unapplied and unconsumed until its writer — or compaction —
        # completes it
        end = tail.rfind(b"\n")
        if end < 0:
            return
        for line in tail[:end].splitlines():
            self._apply_line_locked(line)
        self._journal_pos += end + 1

    # ---- lookups (all O(index): no dirents, no per-entry parses) ----

    def lookup(self, name: str) -> Optional[MetaLogEntry]:
        with self._lock:
            self._refresh_locked()
            entry = self._entries.get(norm_name(name))
            if entry is None or entry.tombstone:
                return None
            return entry

    def generation(self) -> int:
        with self._lock:
            self._refresh_locked()
            return self._gen

    def live_count(self) -> int:
        with self._lock:
            self._refresh_locked()
            return sum(1 for e in self._entries.values()
                       if not e.tombstone)

    def live_names(self) -> list[str]:
        with self._lock:
            self._refresh_locked()
            return sorted(n for n, e in self._entries.items()
                          if not e.tombstone)

    def dead_bytes(self) -> int:
        with self._lock:
            self._refresh_locked()
            return self._dead_bytes

    def prefix_names(self, prefix: str) -> list[str]:
        """Every live name under ``prefix`` (recursive), sorted — the
        no-dirent-walk namespace scan.  "" scans the whole store."""
        key = norm_name(prefix)
        want = key + "/" if key else ""
        with self._lock:
            self._refresh_locked()
            return sorted(
                n for n, e in self._entries.items()
                if not e.tombstone
                and (not want or n.startswith(want) or n == key))

    def list_children(self, path: str
                      ) -> Optional[tuple[str, list[tuple[str, str]]]]:
        """One-level listing at ``path``: ("file"|"directory", sorted
        [(kind, name), ...]) with directories synthesized from name
        prefixes, or None when the path names neither a live ref nor a
        populated directory.  The namespace root is always a (possibly
        empty) directory, like an existing-but-empty MetadataPath
        root."""
        key = norm_name(path)
        with self._lock:
            self._refresh_locked()
            entry = self._entries.get(key)
            if entry is not None and not entry.tombstone:
                return ("file", [])
            prefix = key + "/" if key else ""
            children: dict[str, str] = {}
            for name, e in self._entries.items():
                if e.tombstone or not name.startswith(prefix):
                    continue
                rest = name[len(prefix):]
                head, sep, _ = rest.partition("/")
                kind = "directory" if sep else "file"
                # a directory prefix wins over a same-named file (the
                # filesystem cannot even express that collision)
                if children.get(head) != "directory":
                    children[head] = kind
            if not children and key:
                return None
            out = [(children[name], name) for name in sorted(children)]
            return ("directory", out)

    def snapshot_entries(self) -> list[tuple[str, MetaLogEntry]]:
        """(name, entry) for every live ref, name-sorted — the index
        half of a namespace snapshot."""
        with self._lock:
            self._refresh_locked()
            return sorted((n, e) for n, e in self._entries.items()
                          if not e.tombstone)

    def index_meta(self) -> list[tuple]:
        """(name, hashes, nodes) for every live ref, name-sorted — the
        zero-read pre-scan surface (projection fields None where a
        publish carried none; consumers requiring them fall back)."""
        with self._lock:
            self._refresh_locked()
            return sorted((n, e.hashes, e.nodes)
                          for n, e in self._entries.items()
                          if not e.tombstone)

    def entries_for(self, names) -> list[tuple[str, MetaLogEntry]]:
        """Live index entries for ``names`` (input order, unknown and
        tombstoned names skipped) under ONE lock/refresh — the paged
        read path's batch lookup."""
        with self._lock:
            self._refresh_locked()
            out = []
            for name in names:
                key = norm_name(name)
                entry = self._entries.get(key)
                if entry is not None and not entry.tombstone:
                    out.append((key, entry))
            return out

    def changes(self, since_generation: int,
                limit: int = DEFAULT_CHANGES_LIMIT) -> list[ChangeRecord]:
        """Publishes/tombstones with generation > ``since_generation``,
        generation-ordered, at most ``limit`` rows — the bounded tail
        feed.  Entries superseded before compaction show only their
        LATEST generation (the index is compacting by construction);
        rows older than the last compaction's floor are gone, which a
        consumer observes as a gap it fills with a full snapshot."""
        with self._lock:
            self._refresh_locked()
            rows = [ChangeRecord(n, e.generation, e.tombstone,
                                 e.published)
                    for n, e in self._entries.items()
                    if e.generation > since_generation]
        rows.sort(key=lambda r: r.generation)
        return rows[:max(int(limit), 0)]

    # ---- reads ----

    def read_bytes(self, name: str) -> bytes:
        """Serialized ref bytes by one positioned read.  Raises
        ``FileNotFoundError`` for unknown/tombstoned names so the
        metadata plane surfaces the same errno as a missing ref
        file."""
        entry = self.lookup(name)
        if entry is None:
            raise FileNotFoundError(
                f"no ref {norm_name(name)!r} in meta log {self.root}")
        with open(self.log_path(entry.log), "rb") as f:
            f.seek(entry.offset)
            data = f.read(entry.length)
        if len(data) != entry.length:
            raise MetaLogError(
                f"log {entry.log} truncated under live ref "
                f"{norm_name(name)!r}")
        return data

    def read_many(self, entries: list[tuple[str, MetaLogEntry]]
                  ) -> list[tuple[str, bytes]]:
        """Ref bytes for many index entries, each log file opened ONCE
        and its referenced span read in ONE sequential read (then
        sliced per entry) — the snapshot contract that a pass reads
        each ref's bytes at most once from the log, with no per-entry
        syscalls.  Peak extra memory is one log file's span (bounded
        by ``log_max_bytes``), released before the next log."""
        by_log: dict[str, list[tuple[str, MetaLogEntry]]] = {}
        for name, entry in entries:
            by_log.setdefault(entry.log, []).append((name, entry))
        out: dict[str, bytes] = {}
        for log, group in sorted(by_log.items()):
            lo = min(e.offset for _n, e in group)
            hi = max(e.offset + e.length for _n, e in group)
            with open(self.log_path(log), "rb") as f:
                f.seek(lo)
                blob = f.read(hi - lo)
            if len(blob) != hi - lo:
                raise MetaLogError(
                    f"log {log} truncated under live refs "
                    f"({hi - lo} span, {len(blob)} read)")
            for name, entry in group:
                start = entry.offset - lo
                out[name] = blob[start:start + entry.length]
        return [(name, out[name]) for name, _ in entries]

    # ---- writes ----

    def _active_log_locked(self, incoming: int) -> tuple[str, int]:
        """(basename, current size) of the log file the next append
        lands in, rolling over past ``log_max_bytes``."""
        logs = self.log_files()
        if logs:
            current = logs[-1]
            try:
                size = os.path.getsize(self.log_path(current))
            except OSError:
                size = 0
            if size + incoming <= self.log_max_bytes or size == 0:
                return current, size
            nxt = (_parse_log_index(current) or 0) + 1
            return _log_name(nxt), 0
        return _log_name(1), 0

    def _journal_commit_locked(self, record: dict) -> bool:
        """Append one journal line and fsync it (the metadata plane's
        acked-durability contract — unlike the slab journal, this
        commit IS the write acknowledgment).  Same unbuffered 'a+b'
        torn-tail probe as ``SlabStore._journal_append_locked``: a
        crashed writer's torn final line is terminated so this record
        starts fresh instead of merging into (and dying with) the
        fragment.  Returns True when the append created the journal
        (the caller owes a directory fsync)."""
        line = (json.dumps(record, separators=(",", ":")) + "\n").encode()
        with _fsio.open(self.journal_path(), "a+b", buffering=0) as f:
            size = os.fstat(f.fileno()).st_size
            if size > 0:
                f.seek(size - 1)
                if f.read(1) != b"\n":
                    line = b"\n" + line
            f.write(line)
            # a failing fsync raises and ABORTS the publication — it
            # is never swallowed and assumed durable (the same rule as
            # MetadataPath.write's temp fsync)
            _fsio.fsync(f)
            if self._journal_id is None:
                self._journal_id = os.fstat(f.fileno()).st_ino
        self._journal_pos = size + len(line)
        return size == 0

    def append(self, name: str, data: bytes,
               hashes: Optional[list] = None,
               nodes: Optional[list] = None) -> MetaLogEntry:
        """Publish one ref: log append + fsync, journal commit + fsync,
        directory fsync when a file was created.  An existing live
        entry of the same name is superseded (its bytes go dead for
        compaction).  ``hashes``/``nodes`` are the optional index
        projection (see the module docstring) carried on the journal
        record.  Power-loss durable on return — this IS the cluster's
        write acknowledgment."""
        key = norm_name(name)
        if not key:
            raise MetaLogError(f"invalid meta-log name {name!r}")
        view = memoryview(data)
        _fsio.makedirs(self.root)
        with self._lock, _Flock(self.root):
            self._refresh_locked()
            log, offset = self._active_log_locked(len(view))
            path = self.log_path(log)
            with _fsio.open(path, "ab") as f:
                # 'ab' positions at EOF; trust the fd, not the earlier
                # stat (appends are flock-serialized, but another
                # process's store handle may have raced the rollover
                # decision)
                offset = f.tell()
                try:
                    f.write(view)
                    f.flush()
                    _fsio.fsync(f)
                except OSError:
                    # ENOSPC/EIO mid-append: truncate the partial tail
                    # away so offset accounting never packs around
                    # garbage; nothing was journaled, so the failed
                    # publish is invisible to every reader
                    try:
                        f.close()
                    except OSError:
                        pass
                    try:
                        _fsio.truncate(path, offset)
                    except OSError:
                        pass  # reclaim is best-effort: the tail is
                        # unreferenced either way, just unreclaimed
                    raise
            created = offset == 0
            # lint: clock-ok wall-clock publish stamp for humans and
            # the GC grace window (like the slab journal's `t` field —
            # operator forensics, never a duration; it must stay real
            # even inside a simulation)
            published = time.time()
            gen = self._gen + 1
            record = {"o": "p", "n": key, "g": gen, "s": log,
                      "f": offset, "l": len(view), "t": published}
            if hashes is not None:
                record["h"] = list(hashes)
            if nodes is not None:
                record["nk"] = [list(pair) for pair in nodes]
            created |= self._journal_commit_locked(record)
            if created:
                # new dirent(s): without this barrier the completed
                # publish is not power-loss durable (powercut-meta
                # would lose the file entirely — the crash harness
                # pins it); appends to existing files are covered by
                # the data/journal fsyncs alone
                _fsio.fsync_dir(self.root)
            old = self._entries.get(key)
            if old is not None and not old.tombstone:
                self._dead_bytes += old.length
            entry = MetaLogEntry(gen, log, offset, len(view),
                                 published, False,
                                 _parse_hashes(hashes),
                                 _parse_nodes(nodes))
            self._entries[key] = entry
            self._gen = gen
            return entry

    def tombstone(self, name: str) -> None:
        """Delete a ref: the entry goes dead and its log bytes are
        reclaimed by :meth:`compact`.  Raises ``FileNotFoundError``
        when there is no live entry, matching ``os.remove`` on a
        missing ref file.  Durable like a publish (a deletion is an
        acknowledgment too)."""
        key = norm_name(name)
        with self._lock, _Flock(self.root):
            self._refresh_locked()
            old = self._entries.get(key)
            if old is None or old.tombstone:
                raise FileNotFoundError(
                    f"no ref {key!r} in meta log {self.root}")
            # lint: clock-ok wall-clock deletion stamp, same contract
            # as the publish stamp above
            stamp = time.time()
            gen = self._gen + 1
            created = self._journal_commit_locked(
                {"o": "d", "n": key, "g": gen, "t": stamp})
            if created:
                _fsio.fsync_dir(self.root)
            self._dead_bytes += old.length
            self._entries[key] = MetaLogEntry(gen, "", 0, 0, stamp, True)
            self._gen = gen

    # ---- compaction ----

    def compact(self) -> dict:
        """Reclaim dead bytes and drop tombstones: copy every live ref
        into fresh log files, atomically swap in a rewritten journal
        (data fsync'd before the rename, the store directory fsync'd
        after it), unlink the old logs.  The copy-then-publish shape
        of ``SlabStore.compact``: a crash at any point leaves a store
        that reads either entirely pre- or entirely post-compaction —
        the crash harness replays every point of this sequence and
        verifies exactly that (sim/crash.py ``meta_log_compact``).
        Generations survive the swap via the journal's ``{"o": "g"}``
        floor record, so a ``changes()`` cursor never sees the counter
        run backwards.  Returns ``{"copied_bytes", "reclaimed_bytes",
        "live_refs"}``."""
        with self._lock, _Flock(self.root):
            self._refresh_locked()
            old_logs = self.log_files()
            base = (_parse_log_index(old_logs[-1]) or 0) + 1 \
                if old_logs else 1
            copied = 0
            out_log = _log_name(base)
            out_path = self.log_path(out_log)
            new_entries: dict[str, MetaLogEntry] = {}
            lines = [json.dumps({"o": "g", "g": self._gen},
                                separators=(",", ":"))]
            out = _fsio.open(out_path, "wb")
            try:
                live = sorted((n, e) for n, e in self._entries.items()
                              if not e.tombstone)
                for name, entry in live:
                    if out.tell() + entry.length > self.log_max_bytes \
                            and out.tell() > 0:
                        _fsio.fsync(out)
                        out.close()
                        base += 1
                        out_log = _log_name(base)
                        out_path = self.log_path(out_log)
                        out = _fsio.open(out_path, "wb")
                    offset = out.tell()
                    with open(self.log_path(entry.log), "rb") as src:
                        src.seek(entry.offset)
                        data = src.read(entry.length)
                    if len(data) != entry.length:
                        raise MetaLogError(
                            f"log {entry.log} truncated under live "
                            f"ref {name!r}")
                    out.write(data)
                    copied += entry.length
                    new_entries[name] = MetaLogEntry(
                        entry.generation, out_log, offset, entry.length,
                        entry.published, False,
                        entry.hashes, entry.nodes)
                    record = {"o": "p", "n": name, "g": entry.generation,
                              "s": out_log, "f": offset,
                              "l": entry.length, "t": entry.published}
                    if entry.hashes is not None:
                        record["h"] = list(entry.hashes)
                    if entry.nodes is not None:
                        record["nk"] = [list(p) for p in entry.nodes]
                    lines.append(json.dumps(record,
                                            separators=(",", ":")))
                # a failing fsync here (or on the journal temp below)
                # propagates and ABORTS the swap: the old journal stays
                # authoritative, nothing is published against bytes
                # that may never have reached the platter
                _fsio.fsync(out)
            finally:
                out.close()
            if not new_entries:
                try:
                    _fsio.unlink(out_path)
                except OSError:
                    pass
            payload = ("".join(line + "\n" for line in lines)).encode()
            tmp = self.journal_path() + f".compact.{os.getpid()}"
            with _fsio.open(tmp, "wb") as f:
                f.write(payload)
                _fsio.fsync(f)
            _fsio.replace(tmp, self.journal_path())
            # directory-entry barrier: without it the completed rename
            # is not power-loss durable — a post-compaction power cut
            # could resurrect the old journal while later appends
            # landed against the new one.  A failure raises BEFORE the
            # in-memory state flips, so the store re-reads whichever
            # journal the disk actually holds.
            _fsio.fsync_dir(self.root)
            reclaimed = self._dead_bytes
            self._entries = new_entries
            self._dead_bytes = 0
            self._journal_pos = len(payload)
            self._journal_id = os.stat(self.journal_path()).st_ino
            keep = set(e.log for e in new_entries.values())
            for log in old_logs:
                if log not in keep:
                    try:
                        _fsio.unlink(self.log_path(log))
                    except OSError:
                        pass  # held open elsewhere is fine; orphaned
            return {"copied_bytes": copied,
                    "reclaimed_bytes": reclaimed,
                    "live_refs": len(new_entries)}

    def stats(self) -> dict:
        with self._lock:
            self._refresh_locked()
            live = [e for e in self._entries.values() if not e.tombstone]
            return {
                "root": self.root,
                "live_refs": len(live),
                "live_bytes": sum(e.length for e in live),
                "dead_bytes": self._dead_bytes,
                "generation": self._gen,
                "log_files": len(self.log_files()),
            }


def is_meta_log_root(path: str) -> bool:
    """True when ``path`` is (or is being used as) a meta-log root —
    its journal exists."""
    return os.path.isfile(os.path.join(path, JOURNAL_NAME))


#: process-shared stores keyed by realpath.
# lint: loop-shared-ok deliberately process-wide, NOT per-loop: the
# store serializes cross-thread access with its own threading.Lock and
# cross-process access with flock, and every loop/worker of a process
# must see one coherent index per root (two instances over one root
# would race their rollover decisions)
_STORES: dict[str, MetaLogStore] = {}
_STORES_LOCK = threading.Lock()


def get_store(root: str) -> MetaLogStore:
    """The process-shared :class:`MetaLogStore` for a root directory."""
    key = os.path.realpath(root)
    with _STORES_LOCK:
        store = _STORES.get(key)
        if store is None:
            store = _STORES[key] = MetaLogStore(root)
        return store


class MetadataLog:
    """The ``type: meta-log`` :class:`MetadataStore` kind: the
    file-per-ref contract (``write``/``read``/``list``/``to_obj``)
    over a :class:`MetaLogStore`, plus the index-powered extras
    (``namespace_snapshot``, ``changes``, ``delete``) the scrub daemon
    and GC ride.  Formats serialize exactly like ``MetadataPath`` —
    the golden ``meta_log_placement`` fixture pins refs byte-identical
    across stores."""

    def __init__(self, path: str, format=None):
        from chunky_bits_tpu.cluster.metadata import MetadataFormat

        self.path = str(path)
        self.format = format or MetadataFormat()
        self.store = get_store(self.path)

    def _append(self, path: str, data: bytes, payload) -> None:
        """Off-loop half of :meth:`write`: extract the index projection
        (one ``FileReference.from_obj`` — CPU work that belongs on the
        worker thread, not the event loop) and append."""
        hashes, nodes = extract_index_meta(payload)
        self.store.append(path, data, hashes=hashes, nodes=nodes)

    async def write(self, path: str, payload) -> None:
        text = self.format.to_string(payload)
        try:
            await asyncio.to_thread(self._append, path, text.encode(),
                                    payload)
        except OSError as err:
            raise MetadataReadError(str(err)) from err

    async def read(self, path: str):
        try:
            data = await asyncio.to_thread(self.store.read_bytes, path)
        except OSError as err:
            raise MetadataReadError(str(err)) from err
        return self.format.from_bytes(data)

    async def delete(self, path: str) -> None:
        try:
            await asyncio.to_thread(self.store.tombstone, path)
        except OSError as err:
            raise MetadataReadError(str(err)) from err

    async def list(self, path: str):
        from chunky_bits_tpu.cluster.metadata import FileOrDirectory

        listed = await asyncio.to_thread(self.store.list_children, path)
        if listed is None:
            raise MetadataReadError(
                str(LocationError(f"not a file or directory: {path}")))
        kind, children = listed
        key = norm_name(path)
        top = FileOrDirectory(kind, key if key else ".")
        out = [top]
        for child_kind, name in children:
            pub = f"{key}/{name}" if key else name
            out.append(FileOrDirectory(child_kind, pub))
        return out

    async def list_files_recursive(self, path: str = "") -> list[str]:
        """Every live file path under ``path`` (sorted) from ONE index
        scan — the no-dirent-walk recursive listing ("" = the whole
        namespace).  The path-store equivalent is a ``list()`` walk
        with one round-trip per directory."""
        return await asyncio.to_thread(self.store.prefix_names, path)

    async def namespace_snapshot(self) -> list[tuple[str, object]]:
        """(public path, parsed ref obj) for every live ref,
        name-sorted — one index scan plus at most one sequential read
        per log file.  THE input for a scrub/GC pass: degraded-first
        ordering, the verify walk and the liveness set all come from
        this single read instead of one metadata round-trip per object
        per consumer."""

        def _snapshot() -> list[tuple[str, object]]:
            raw = self.store.read_many(self.store.snapshot_entries())
            loads = self.format.loader()
            try:
                return [(name, loads(data)) for name, data in raw]
            except (json.JSONDecodeError, yaml.YAMLError) as err:
                raise SerdeError(str(err)) from err

        try:
            return await asyncio.to_thread(_snapshot)
        except OSError as err:
            raise MetadataReadError(str(err)) from err

    async def namespace_nodes(self) -> Optional[list]:
        """[(public path, ((kind, node), ...)), ...] for every live
        ref, name-sorted, from ONE index scan — zero ref reads, zero
        parses.  THE scrub priority pre-scan input: intersect each
        ref's node keys with ``HealthScoreboard.degraded_keys()`` to
        score the whole namespace in microseconds per thousand refs.
        None when any live entry lacks the projection (foreign payload
        or a pre-projection writer) — the caller falls back to the
        full snapshot read, so scoring is never silently partial."""

        def _scan() -> Optional[list]:
            out = []
            for name, _hashes, nodes in self.store.index_meta():
                if nodes is None:
                    return None
                out.append((name, nodes))
            return out

        return await asyncio.to_thread(_scan)

    async def namespace_hashes(self) -> Optional[list]:
        """[(public path, (hash display str, ...)), ...] for every live
        ref, name-sorted, from ONE index scan — the GC liveness walk
        with zero ref reads and zero parses.  None when any live entry
        lacks the projection (the caller falls back to the snapshot
        parse, so liveness is never silently partial — a missed live
        hash would be a deleted chunk)."""

        def _scan() -> Optional[list]:
            out = []
            for name, hashes, _nodes in self.store.index_meta():
                if hashes is None:
                    return None
                out.append((name, hashes))
            return out

        return await asyncio.to_thread(_scan)

    async def read_objs(self, names) -> list[tuple[str, object]]:
        """(public path, parsed ref obj) for ``names`` (input order;
        unknown/deleted names skipped): one batch lookup, grouped
        sequential log reads, one parse per ref — the scrub verify
        walk's paged fetch, so a pass holds one PAGE of parsed objects
        instead of the whole namespace."""

        def _read() -> list[tuple[str, object]]:
            raw = self.store.read_many(self.store.entries_for(names))
            loads = self.format.loader()
            try:
                return [(name, loads(data)) for name, data in raw]
            except (json.JSONDecodeError, yaml.YAMLError) as err:
                raise SerdeError(str(err)) from err

        try:
            return await asyncio.to_thread(_read)
        except OSError as err:
            raise MetadataReadError(str(err)) from err

    async def changes(self, since_generation: int,
                      limit: int = DEFAULT_CHANGES_LIMIT
                      ) -> list[ChangeRecord]:
        """The bounded recently-written tail (see
        :meth:`MetaLogStore.changes`)."""
        return await asyncio.to_thread(self.store.changes,
                                       since_generation, limit)

    async def generation(self) -> int:
        return await asyncio.to_thread(self.store.generation)

    async def compact(self) -> dict:
        return await asyncio.to_thread(self.store.compact)

    def to_obj(self) -> dict:
        return {"type": "meta-log", "format": self.format.name,
                "path": self.path}
