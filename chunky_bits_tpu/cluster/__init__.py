"""Cluster orchestration (the reference's src/cluster/ layer)."""

from chunky_bits_tpu.cluster.cluster import Cluster  # noqa: F401
from chunky_bits_tpu.cluster.destination import (  # noqa: F401
    ClusterWriter,
    Destination,
)
from chunky_bits_tpu.cluster.metadata import (  # noqa: F401
    FileOrDirectory,
    MetadataFormat,
    MetadataGit,
    MetadataPath,
    metadata_from_obj,
)
from chunky_bits_tpu.cluster.nodes import ClusterNode, ClusterNodes  # noqa: F401
from chunky_bits_tpu.cluster.profile import (  # noqa: F401
    ClusterProfile,
    ClusterProfiles,
    ZoneRule,
)
from chunky_bits_tpu.cluster.tunables import Tunables  # noqa: F401
