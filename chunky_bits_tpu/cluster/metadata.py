"""Durable file-reference store.

Mirrors src/cluster/metadata.rs: tag-dispatched ``type: path`` /
``type: git`` stores (:42-92).  ``MetadataPath`` writes the serialized
FileReference under a root directory, optionally running a ``put_script``
via ``/bin/sh -c`` with ``fail_on_script_error`` (:94-141); listing is a
one-level directory scan with private->public path mapping (:152-205).
``MetadataGit`` wraps MetadataPath and runs ``git add`` + ``git commit`` per
write, denying ``.git`` paths (:223-328).  Formats: json, json-pretty
(default), json-strict, yaml — non-strict variants parse via YAML, a JSON
superset (:364-414).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional, Union

import yaml

from chunky_bits_tpu.cluster import tunables
from chunky_bits_tpu.errors import (
    LocationError,
    MetadataReadError,
    SerdeError,
)
from chunky_bits_tpu.file import fsio as _fsio
from chunky_bits_tpu.file.location import Location
from chunky_bits_tpu.utils.yamlio import yaml_load, yaml_dump

JSON = "json"
JSON_PRETTY = "json-pretty"
JSON_STRICT = "json-strict"
YAML = "yaml"
FORMATS = (JSON, JSON_PRETTY, JSON_STRICT, YAML)


class MetadataFormat:
    """(metadata.rs:364-414)"""

    def __init__(self, name: str = JSON_PRETTY):
        if name not in FORMATS:
            raise SerdeError(f"unknown metadata format {name!r}")
        self.name = name

    def to_string(self, payload) -> str:
        if self.name in (JSON, JSON_STRICT):
            return json.dumps(payload, separators=(",", ":"))
        if self.name == JSON_PRETTY:
            return json.dumps(payload, indent=2)
        return yaml_dump(payload, sort_keys=False)

    def from_bytes(self, data: bytes):
        try:
            if self.name == JSON_STRICT:
                return json.loads(data)
            return yaml_load(data)
        except (json.JSONDecodeError, yaml.YAMLError) as err:
            raise SerdeError(str(err)) from err

    def loader(self):
        """The raw bytes->obj parse callable with the format branch
        hoisted — for batch consumers (the meta-log's
        ``namespace_snapshot`` parses the whole namespace in one call,
        where the per-call wrapper overhead of ``from_bytes`` is
        measurable).  Raises the codec's native errors; batch callers
        wrap them in SerdeError once per batch."""
        return json.loads if self.name == JSON_STRICT else yaml_load

    async def from_location(self, location: Union[str, Location],
                            cx=None):
        if not isinstance(location, Location):
            location = Location.parse(str(location))
        data = await location.read(cx)
        return self.from_bytes(data)


@dataclass
class FileOrDirectory:
    """(metadata.rs:417-506)"""

    kind: str  # "file" | "directory"
    path: str

    def is_file(self) -> bool:
        return self.kind == "file"

    def is_directory(self) -> bool:
        return self.kind == "directory"

    def __str__(self) -> str:
        return self.path

    @staticmethod
    async def from_local_path(path: str) -> "FileOrDirectory":
        if await asyncio.to_thread(os.path.isdir, path):
            return FileOrDirectory("directory", path)
        if await asyncio.to_thread(os.path.isfile, path):
            return FileOrDirectory("file", path)
        raise LocationError(f"not a file or directory: {path}")

    @staticmethod
    async def list(path: str) -> list["FileOrDirectory"]:
        """Top-level entry followed by its immediate children."""
        top = await FileOrDirectory.from_local_path(path)
        out = [top]
        if top.is_directory():

            def _scan() -> list[tuple[str, str]]:
                # one scandir pass: the dirent already carries the
                # entry type, so N children cost one getdents stream
                # instead of listdir + an isdir/isfile stat pair per
                # name (entries that are neither — sockets, dangling
                # links, raced unlinks — are skipped, same outcome as
                # from_local_path's LocationError)
                found = []
                with os.scandir(path) as it:
                    for entry in it:
                        try:
                            if entry.is_dir():
                                found.append(("directory", entry.name))
                            elif entry.is_file():
                                found.append(("file", entry.name))
                        except OSError:
                            continue
                found.sort(key=lambda t: t[1])
                return found

            # the scan must ride the thread hop: eager, it would run
            # on the loop (CB201)
            for kind, name in await asyncio.to_thread(_scan):
                out.append(FileOrDirectory(kind, os.path.join(path, name)))
        return out


def _sub_path(root: str, path: str) -> str:
    """Join, keeping only normal components (no traversal;
    metadata.rs:197-205)."""
    parts = [p for p in str(path).split("/")
             if p not in ("", ".", "..")]
    return os.path.join(root, *parts) if parts else root


def _pub_path(root: str, sub: str) -> str:
    """Strip the store root off a private path (metadata.rs:174-195)."""
    rel = os.path.relpath(sub, root)
    return "." if rel == "." else rel


#: a publication temp older than this is a crashed writer's leak (a
#: metadata write takes milliseconds; the margin covers a paused
#: writer) — reaped by the next write to the same directory, so a
#: crash between temp write and rename never leaks ``.tmp`` files
#: forever (the GC's dirent stale-temp reaper only walks chunk hash
#: dirs, never metadata roots)
STALE_TEMP_SECONDS = 60.0


def _reap_stale_temps(dirname: str) -> None:
    """Remove crashed writers' publication temps from one metadata
    directory (sync — runs inside the write's thread hop).  Age-gated:
    a concurrent writer's in-flight temp is younger than the threshold
    and survives; its rename needs nothing but the inode anyway.
    Called once per (MetadataPath instance, directory) — the scan is
    O(dir entries), and paying it per write would turn a million-object
    namespace walk quadratic (measured +1.5 ms/write at a mere 150
    entries on this box's 9p /tmp); a crashed writer's leak is reaped
    by the next PROCESS's first write there, which is what "reap on
    next write" can soundly mean without a per-write scan."""
    from chunky_bits_tpu.file.location import is_publish_temp

    try:
        entries = os.listdir(dirname)
    except OSError:
        return
    # lint: clock-ok file mtimes are wall-clock; comparing them against
    # anything else would misclassify every temp inside a simulation
    now = time.time()
    for entry in entries:
        if not is_publish_temp(entry):
            continue
        path = os.path.join(dirname, entry)
        try:
            if now - os.path.getmtime(path) > STALE_TEMP_SECONDS:
                _fsio.unlink(path)
        except OSError:
            continue  # raced another reaper / already renamed away


class MetadataPath:
    """(metadata.rs:94-205)"""

    def __init__(self, path: str, format: Optional[MetadataFormat] = None,
                 put_script: Optional[str] = None,
                 fail_on_script_error: bool = False):
        self.path = str(path)
        self.format = format or MetadataFormat()
        self.put_script = put_script
        self.fail_on_script_error = fail_on_script_error
        #: directories whose stale publication temps this instance has
        #: already reaped (once per instance: see _reap_stale_temps);
        #: set add/contains are GIL-atomic, and a racing double-scan
        #: is merely redundant
        self._reaped_dirs: set[str] = set()

    async def write(self, path: str, payload) -> None:
        target = _sub_path(self.path, path)
        text = self.format.to_string(payload)

        def _write() -> None:
            # Atomic publication, like every other local write in this
            # repo (file/location._publish_atomically): the reference
            # truncates in place (metadata.rs:120-130), which lets a
            # concurrent reader observe an empty/torn reference — a
            # live hazard now that the scrub daemon republishes
            # metadata while clients read it.  Unlike the per-chunk
            # path, metadata publication is the cluster's WRITE
            # ACKNOWLEDGMENT, so it is made power-loss durable: temp
            # fsync before the rename, directory fsync after it (the
            # crash harness's powercut images pin both directions —
            # sim/crash.py, tests/test_crash.py).  A failing fsync
            # raises and ABORTS the publication; it is never swallowed
            # and assumed durable.
            from chunky_bits_tpu.file.location import publish_temp_name

            dirname = os.path.dirname(target)
            _fsio.makedirs(dirname)
            if dirname not in self._reaped_dirs:
                self._reaped_dirs.add(dirname)
                _reap_stale_temps(dirname)
            tmp = publish_temp_name(target)
            try:
                with _fsio.open(tmp, "w") as f:
                    f.write(text)
                    _fsio.fsync(f)
                _fsio.replace(tmp, target)
                _fsio.fsync_dir(dirname)
            except BaseException:
                try:
                    _fsio.unlink(tmp)
                except OSError:
                    pass
                raise

        try:
            await asyncio.to_thread(_write)
        except OSError as err:
            raise MetadataReadError(str(err)) from err
        if self.put_script:
            proc = await asyncio.create_subprocess_shell(
                self.put_script, cwd=self.path)
            # lint: unbounded-deadline-ok user-supplied local hook; a
            # timeout here would orphan a zombie and ack the write with
            # the hook's outcome unknown — runaway hooks are the
            # operator's contract (reference parity: put_script blocks)
            code = await proc.wait()
            if self.fail_on_script_error and code != 0:
                # Distinguish signal-death from a nonzero exit like the
                # reference's ExitCode/Signal variants (error.rs:236-253);
                # a negative returncode is -signum.
                if code < 0:
                    raise MetadataReadError(
                        f"put_script killed by signal {-code}")
                raise MetadataReadError(
                    f"put_script exited with code {code}")

    async def read(self, path: str):
        target = _sub_path(self.path, path)

        def _read() -> bytes:
            with open(target, "rb") as f:
                return f.read()

        try:
            data = await asyncio.to_thread(_read)
        except OSError as err:
            raise MetadataReadError(str(err)) from err
        return self.format.from_bytes(data)

    async def list(self, path: str) -> list[FileOrDirectory]:
        target = _sub_path(self.path, path)
        try:
            entries = await FileOrDirectory.list(target)
        except LocationError as err:
            raise MetadataReadError(str(err)) from err
        return [
            FileOrDirectory(e.kind, _pub_path(self.path, e.path))
            for e in entries
        ]

    def to_obj(self) -> dict:
        obj = {"type": "path", "format": self.format.name,
               "path": self.path}
        if self.put_script is not None:
            obj["put_script"] = self.put_script
        if self.fail_on_script_error:
            obj["fail_on_script_error"] = True
        return obj


def _deny_git(path: str) -> str:
    first = [p for p in str(path).split("/") if p not in ("", ".")]
    if first and first[0] == ".git":
        raise MetadataReadError("Access to .git is denied")
    return path


class MetadataGit:
    """(metadata.rs:208-329)"""

    def __init__(self, path: str, format: Optional[MetadataFormat] = None):
        self.meta_path = MetadataPath(path, format)

    @property
    def path(self) -> str:
        return self.meta_path.path

    @property
    def format(self) -> MetadataFormat:
        return self.meta_path.format

    async def _git(self, *args: str) -> None:
        proc = await asyncio.create_subprocess_exec(
            "git", *args, cwd=self.meta_path.path)
        # lint: unbounded-deadline-ok local git child on a local repo;
        # abandoning wait() would leak a zombie and race the next
        # add/commit against this one's index lock
        code = await proc.wait()
        if code != 0:
            raise MetadataReadError(f"git {args[0]} exited with {code}")

    async def write(self, path: str, payload) -> None:
        _deny_git(path)
        await self.meta_path.write(path, payload)
        rel = "/".join(p for p in str(path).split("/")
                       if p not in ("", ".", ".."))
        await self._git("add", rel)
        await self._git("commit", "-m", f"Write {rel}")

    async def read(self, path: str):
        _deny_git(path)
        return await self.meta_path.read(path)

    async def list(self, path: str) -> list[FileOrDirectory]:
        _deny_git(path)
        entries = await self.meta_path.list(path)
        out = []
        for e in entries:
            try:
                _deny_git(e.path)
            except MetadataReadError:
                continue
            out.append(e)
        return out

    def to_obj(self) -> dict:
        return {"type": "git", "format": self.format.name,
                "path": self.path}


if TYPE_CHECKING:
    from chunky_bits_tpu.cluster.meta_log import MetadataLog

MetadataStore = Union[MetadataPath, MetadataGit, "MetadataLog"]


def metadata_from_obj(obj: dict) -> MetadataStore:
    """Tag-dispatched deserialization (metadata.rs:42-48), extended
    with the repo's ``meta-log`` kind (cluster/meta_log.py — ``kind:``
    is accepted as an alias for the tag).  A fleet-wide
    ``$CHUNKY_BITS_TPU_METADATA_KIND=meta-log``
    (``tunables.metadata_kind``, read here = cluster-config load time)
    rebuilds plain ``type: path`` stores as meta-logs over the same
    root; stores with a ``put_script`` silently stay ``path`` (the log
    has no per-write hook), mirroring ``$CHUNKY_BITS_TPU_CODE``'s
    stay-rs-on-incompatible-profiles semantics."""
    if not isinstance(obj, dict) or not ("type" in obj or "kind" in obj):
        raise SerdeError("metadata must be a mapping with a 'type' tag")
    kind = obj["type"] if "type" in obj else obj["kind"]
    fmt = MetadataFormat(obj["format"]) if "format" in obj else None
    if kind == "path":
        if (obj.get("put_script") is None
                and tunables.metadata_kind() == "meta-log"):
            from chunky_bits_tpu.cluster.meta_log import MetadataLog

            return MetadataLog(path=obj["path"], format=fmt)
        return MetadataPath(
            path=obj["path"],
            format=fmt,
            put_script=obj.get("put_script"),
            fail_on_script_error=bool(obj.get("fail_on_script_error",
                                              False)),
        )
    if kind == "git":
        return MetadataGit(path=obj["path"], format=fmt)
    if kind == "meta-log":
        # lazy import, like location.py's slab: the plain path store
        # never pays for the log machinery
        from chunky_bits_tpu.cluster.meta_log import MetadataLog

        return MetadataLog(path=obj["path"], format=fmt)
    raise SerdeError(f"unknown metadata type {kind!r}")
