"""Per-location health scoreboard: the network plane's I/O scheduler state.

The reference walks ``chunk.locations`` in metadata order with no memory
of past behaviour (src/file/file_part.rs:83-101) and its writer reacts
only to hard errors (src/cluster/writer.rs:99-122).  This module is a
TPU-repo extension: a per-location scoreboard that remembers EWMA
latency, error rate, and in-flight counts for every storage node, plus
the two mechanisms built on top of it —

* a **closed -> open -> half-open breaker** per location, so a node
  that keeps failing stops being anyone's first choice until a probe
  succeeds (the read path still falls through to open-breaker nodes as
  a last resort: with data at stake, "degrade, never refuse");
* the **hedge machinery** for tail-tolerant reads (Dean & Barroso,
  "The Tail at Scale"): an adaptive hedge delay (p95 of recent
  latencies, clamped to ``[hedge_ms, 20*hedge_ms]``) and a global
  token-bucket budget capping hedges at ~``hedge_ratio`` (default 5%)
  of primary requests, so hedging can never amplify load meaningfully.

Health is tracked per **node**, not per URL: chunk addresses are unique
per object, so the key collapses an HTTP location to its netloc and a
local location to its parent directory — the unit that actually fails
or slows down.

Thread-safety: completions are recorded from event-loop callbacks AND
from host-pipeline worker threads (the fused mmap+verify path runs the
mapper off-loop), so all bookkeeping is guarded by a ``threading.Lock``
held only for sync dict/float updates — never across an await (CB202)
and never blocking (CB201-safe by construction: no I/O, no sleeps).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Sequence
from urllib.parse import urlsplit

from chunky_bits_tpu.cluster import clock as _clock

#: re-exported for callers that think in scheduler terms; the
#: definitions live in errors.py so file/ modules can use them without
#: importing the cluster package (import-cycle hygiene)
from chunky_bits_tpu.errors import (  # noqa: F401
    TRANSIENT_HTTP_STATUSES,
    is_transient_error,
)


def location_key(location) -> tuple[str, str]:
    """The health-tracking identity of a location: the storage *node*
    behind it.  HTTP chunks collapse to their netloc, local chunks to
    their parent directory (the node's disk root in every cluster
    layout this repo generates)."""
    target = location.target
    if location.kind == "http":
        return ("http", urlsplit(target).netloc)
    return ("local", os.path.dirname(target))


#: breaker states (string-valued for cheap rendering/tests)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class _Node:
    """Mutable per-key record; all access under the scoreboard lock."""

    __slots__ = ("ewma", "err", "inflight", "consec_failures",
                 "breaker", "opened_at", "reads", "errors")

    def __init__(self) -> None:
        self.ewma: Optional[float] = None  # seconds, successes only
        self.err = 0.0  # EWMA of the failure indicator (0..1)
        self.inflight = 0
        self.consec_failures = 0
        self.breaker = CLOSED
        self.opened_at = 0.0
        self.reads = 0  # completions recorded (either verb)
        self.errors = 0


@dataclass
class LocationHealth:
    """Immutable snapshot row for reports/tests."""

    key: tuple[str, str]
    ewma_ms: Optional[float]
    err_rate: float
    inflight: int
    breaker: str
    completions: int
    errors: int

    def to_obj(self) -> dict:
        """Plain-dict row (the metrics registry's health collector and
        the ``chunky-bits stats`` renderer; ``node`` is the config-
        derived key — a closed label set per CB107)."""
        return {
            "node": self.key[1],
            "kind": self.key[0],
            "ewma_ms": (None if self.ewma_ms is None
                        else round(self.ewma_ms, 3)),
            "err_rate": round(self.err_rate, 4),
            "inflight": self.inflight,
            "breaker": self.breaker,
            "completions": self.completions,
            "errors": self.errors,
        }

    def __str__(self) -> str:
        ewma = "-" if self.ewma_ms is None else f"{self.ewma_ms:.1f}ms"
        return (f"{self.key[1]}: ewma={ewma} "
                f"err={self.err_rate * 100:.0f}% "
                f"inflight={self.inflight} breaker={self.breaker} "
                f"n={self.completions}")


@dataclass
class HealthStats:
    """Scoreboard snapshot surfaced through ``file/profiler.py``."""

    locations: list[LocationHealth]
    hedges_fired: int
    hedges_won: int
    hedges_cancelled: int
    #: primary (non-hedge) fetches that accrued hedge budget — the
    #: denominator of the hedge-amplification bound the simulator's
    #: thundering-herd scenario asserts (fired <= ratio*primaries+burst)
    primaries: int = 0

    def to_obj(self) -> dict:
        return {
            "locations": [row.to_obj() for row in self.locations],
            "hedges_fired": self.hedges_fired,
            "hedges_won": self.hedges_won,
            "hedges_cancelled": self.hedges_cancelled,
            "primaries": self.primaries,
        }

    def __str__(self) -> str:
        rows = "; ".join(str(r) for r in self.locations) or "no traffic"
        return (f"Health<{rows} | hedges fired={self.hedges_fired} "
                f"won={self.hedges_won} "
                f"cancelled={self.hedges_cancelled}>")


class HealthScoreboard:
    """Loop-safe per-location scoreboard + hedge budget.

    One instance per cluster (``Cluster.__init__`` hangs it on the
    shared ``LocationContext``), shared by every event loop and worker
    thread that touches the cluster — health memory must span loops,
    unlike the loop-bound batchers/caches.  NOT ``LOOP_BOUND``: every
    method is a sub-microsecond sync update under ``self._lock``.
    """

    #: EWMA smoothing for latency and error rate
    ALPHA = 0.2
    #: consecutive failures that trip the breaker closed -> open
    BREAKER_FAILURES = 5
    #: seconds an open breaker waits before allowing a half-open probe
    BREAKER_COOLDOWN = 5.0
    #: error-rate EWMA above which a node counts as degraded for
    #: placement de-prioritization even before its breaker trips
    DEGRADED_ERR = 0.5
    #: adaptive hedge delay ceiling, as a multiple of the floor
    CEILING_FACTOR = 20.0
    #: recent success latencies pooled for the p95 hedge delay
    SAMPLE_WINDOW = 128

    def __init__(self, hedge_ms: float = 0.0,
                 hedge_ratio: float = 0.05,
                 hedge_burst: float = 8.0,
                 clock: Callable[[], float] = _clock.monotonic) -> None:
        self._lock = threading.Lock()
        self._nodes: dict[tuple[str, str], _Node] = {}
        self._clock = clock
        self.hedge_ms = max(float(hedge_ms), 0.0)
        self._hedge_ratio = hedge_ratio
        self._hedge_burst = hedge_burst
        # the bucket starts FULL: a cold cluster's first read may have
        # several parts stalling on the same slow node at once, and
        # each deserves a hedge before any budget has accrued.
        # Sustained amplification still converges to hedge_ratio
        # because accrual is per-primary and capped at the burst.
        self._hedge_tokens = hedge_burst
        self._samples: deque[float] = deque(maxlen=self.SAMPLE_WINDOW)
        self._p95: Optional[float] = None  # memoized; None = recompute
        #: optional QoS hedge gate (cluster/qos.py allow_hedge):
        #: consulted before any token is consumed, so a suppressed
        #: launch never burns budget.  None = no gate (pre-QoS
        #: behavior).  The callable must be thread-safe to READ (the
        #: scheduler's is: counter reads + a ring scan).
        self._hedge_gate: Optional[Callable[[], bool]] = None
        self.hedges_fired = 0
        self.hedges_won = 0
        self.hedges_cancelled = 0
        self.primaries = 0
        # weakly self-register with the process metrics registry: the
        # scoreboard is already thread-safe, so a /metrics scrape just
        # takes an extra stats() snapshot
        from chunky_bits_tpu.obs.metrics import get_registry

        get_registry().register_source("health", self)

    @property
    def hedge_ratio(self) -> float:
        """Budget accrued per primary fetch — the amplification
        bound's slope.  Public so external assertions (the simulator's
        hedge-budget verdict) read the SAME numbers the accrual uses:
        fired <= ratio * primaries + burst."""
        return self._hedge_ratio

    @property
    def hedge_burst(self) -> float:
        """Token ceiling (and starting balance) — the amplification
        bound's intercept."""
        return self._hedge_burst

    # ---- recording (the location.py instrument hooks call these) ----

    def _node(self, location) -> _Node:
        key = location_key(location)
        node = self._nodes.get(key)
        if node is None:
            node = self._nodes[key] = _Node()
        return node

    def begin(self, location) -> None:
        """An I/O against ``location`` started (in-flight count)."""
        with self._lock:
            self._node(location).inflight += 1

    def finish(self, location, ok: Optional[bool],
               seconds: Optional[float]) -> None:
        """Paired with :meth:`begin`: the I/O completed.  ``ok=None``
        closes the in-flight count without a verdict — a cancelled
        hedge loser says nothing about the node's health."""
        with self._lock:
            node = self._node(location)
            node.inflight = max(node.inflight - 1, 0)
            if ok is not None:
                self._record_locked(node, ok, seconds)

    def record(self, location, ok: bool,
               seconds: Optional[float] = None) -> None:
        """An unpaired completion (streaming opens, mapper hits, or a
        corruption verdict with ``seconds=None``)."""
        with self._lock:
            self._record_locked(self._node(location), ok, seconds)

    def record_latency_floor(self, location, seconds: float) -> None:
        """A lower-bound latency observation WITHOUT a verdict: a
        cancelled hedge loser ran at least this long before losing.
        Feeds the EWMA and the p95 pool (so ordering learns the
        straggler and the hedge delay adapts) but leaves error rate,
        consecutive-failure count and breaker state untouched — losing
        a race is not a success, and must not close an open breaker."""
        with self._lock:
            node = self._node(location)
            a = self.ALPHA
            node.ewma = (seconds if node.ewma is None
                         else node.ewma + a * (seconds - node.ewma))
            self._samples.append(seconds)
            self._p95 = None

    def _record_locked(self, node: _Node, ok: bool,
                       seconds: Optional[float]) -> None:
        node.reads += 1
        a = self.ALPHA
        node.err += a * ((0.0 if ok else 1.0) - node.err)
        if ok:
            node.consec_failures = 0
            if node.breaker != CLOSED:
                node.breaker = CLOSED
            if seconds is not None:
                node.ewma = (seconds if node.ewma is None
                             else node.ewma + a * (seconds - node.ewma))
                self._samples.append(seconds)
                self._p95 = None
        else:
            node.errors += 1
            node.consec_failures += 1
            if (node.breaker == HALF_OPEN
                    or node.consec_failures >= self.BREAKER_FAILURES):
                node.breaker = OPEN
                node.opened_at = self._clock()

    # ---- breaker / scoring ----

    def _state_locked(self, node: _Node) -> str:
        if node.breaker == OPEN and (self._clock() - node.opened_at
                                     >= self.BREAKER_COOLDOWN):
            # cooldown elapsed: the next attempt is the half-open probe
            node.breaker = HALF_OPEN
        return node.breaker

    def breaker_state(self, location) -> str:
        with self._lock:
            return self._state_locked(self._node(location))

    def degraded(self, location) -> bool:
        """True when placement should prefer other nodes: breaker not
        closed, or error rate above the degraded threshold."""
        with self._lock:
            node = self._nodes.get(location_key(location))
            if node is None:
                return False
            return (self._state_locked(node) != CLOSED
                    or node.err > self.DEGRADED_ERR)

    def degraded_keys(self) -> frozenset:
        """The ``location_key`` of every currently-degraded node, as
        one set — the scrub priority pre-scan intersects a meta-log
        index's per-ref node keys against this instead of calling
        :meth:`degraded` once per replica of every ref in the
        namespace.  Same predicate as :meth:`degraded`; the set is
        small (nodes, not objects) and a point-in-time snapshot like
        any single ``degraded`` call."""
        with self._lock:
            return frozenset(
                key for key, node in self._nodes.items()
                if (self._state_locked(node) != CLOSED
                    or node.err > self.DEGRADED_ERR))

    def order(self, locations: Sequence) -> list:
        """``locations`` sorted best-health-first: closed breakers
        before half-open before open, lower error rate, lower EWMA
        latency, fewer in-flight.  The sort is stable, so locations the
        scoreboard knows nothing about keep their metadata order — a
        fresh scoreboard reproduces the reference's walk exactly."""
        penalty = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

        def score(location) -> tuple:
            with self._lock:
                node = self._nodes.get(location_key(location))
                if node is None:
                    return (0, 0.0, 0.0, 0)
                return (penalty[self._state_locked(node)],
                        round(node.err, 2),
                        node.ewma or 0.0,
                        node.inflight)

        return sorted(locations, key=score)

    # ---- hedge machinery ----

    @property
    def hedge_enabled(self) -> bool:
        return self.hedge_ms > 0.0

    def note_primary(self) -> None:
        """A primary (non-hedge) fetch started: accrue hedge budget."""
        with self._lock:
            self.primaries += 1
            self._hedge_tokens = min(
                self._hedge_tokens + self._hedge_ratio,
                self._hedge_burst)

    def set_hedge_gate(
            self, fn: Optional[Callable[[], bool]]) -> None:
        """Install (or clear) the QoS hedge gate: a callable the
        scheduler owns that returns False when speculative load should
        yield (admission pressure, or ample p99 headroom worth
        conserving budget for).  Gate-denied launches consume NO
        token — suppression must never tax the budget it protects."""
        with self._lock:
            self._hedge_gate = fn

    def hedge_allowed(self) -> bool:
        """Cheap gate pre-check (no token movement): lets the read
        path skip arming a hedge timeout it would be denied anyway
        (file/file_part.py).  True when no gate is installed."""
        gate = self._hedge_gate
        return gate is None or gate()

    def try_fire_hedge(self) -> bool:
        """Consume one hedge token if available AND the QoS gate (when
        installed) allows.  False = budget exhausted or suppressed,
        the caller keeps waiting on its primary."""
        gate = self._hedge_gate
        if gate is not None and not gate():
            return False
        with self._lock:
            if self._hedge_tokens < 1.0:
                return False
            self._hedge_tokens -= 1.0
            self.hedges_fired += 1
            return True

    def hedge_won(self) -> None:
        with self._lock:
            self.hedges_won += 1

    def hedge_cancelled(self, n: int = 1) -> None:
        with self._lock:
            self.hedges_cancelled += n

    def hedge_delay(self) -> float:
        """Adaptive hedge delay in SECONDS: the p95 of recent success
        latencies, clamped to ``[hedge_ms, CEILING_FACTOR*hedge_ms]``.
        With no samples yet the floor applies — hedging a cold cluster
        after ``hedge_ms`` is the configured intent."""
        floor = self.hedge_ms / 1000.0
        with self._lock:
            if self._p95 is None and self._samples:
                ordered = sorted(self._samples)
                self._p95 = ordered[min(int(len(ordered) * 0.95),
                                        len(ordered) - 1)]
            p95 = self._p95
        if p95 is None:
            return floor
        return min(max(p95, floor), floor * self.CEILING_FACTOR)

    # ---- reporting ----

    def stats(self) -> HealthStats:
        with self._lock:
            rows = []
            for key in sorted(self._nodes):
                node = self._nodes[key]
                rows.append(LocationHealth(
                    key=key,
                    ewma_ms=(None if node.ewma is None
                             else node.ewma * 1000.0),
                    err_rate=node.err,
                    inflight=node.inflight,
                    breaker=self._state_locked(node),
                    completions=node.reads,
                    errors=node.errors,
                ))
            return HealthStats(
                locations=rows,
                hedges_fired=self.hedges_fired,
                hedges_won=self.hedges_won,
                hedges_cancelled=self.hedges_cancelled,
                primaries=self.primaries,
            )
