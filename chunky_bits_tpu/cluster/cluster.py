"""The cluster façade: config-deserialized top level of the system.

Mirrors src/cluster/cluster.rs: ``{destinations, metadata, profiles,
tunables}`` with serde aliases (``nodes``/``node``/``destination``,
``tunable``/``tuning``; cluster.rs:43-56).  Builds write pipelines over the
placement engine, reads files back through the part codec, lists metadata.

The reference's ``get_file_writer`` forgets to set ``parity_chunks``
(cluster.rs:65-71 — profile parity is silently replaced by the library
default of 2); that bug is fixed here, matching the behavior of its own
``write_file_with_report`` (cluster.rs:109-113), per SURVEY §7.
"""

from __future__ import annotations

import asyncio
import threading
import weakref
from collections import OrderedDict
from typing import Optional, Union

from chunky_bits_tpu.cluster.destination import Destination
from chunky_bits_tpu.cluster.metadata import (
    FileOrDirectory,
    MetadataFormat,
    MetadataStore,
    metadata_from_obj,
)
from chunky_bits_tpu.cluster.nodes import ClusterNodes
from chunky_bits_tpu.cluster.profile import ClusterProfile, ClusterProfiles
from chunky_bits_tpu.cluster.tunables import Tunables
from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.file_reference import FileReference
from chunky_bits_tpu.file.location import Location
from chunky_bits_tpu.file.profiler import ProfileReport, new_profiler
from chunky_bits_tpu.file.reader import FileReadBuilder
from chunky_bits_tpu.file.writer import FileWriteBuilder
from chunky_bits_tpu.utils import aio


class Cluster:
    def __init__(self, destinations: ClusterNodes,
                 metadata: MetadataStore,
                 profiles: ClusterProfiles,
                 tunables: Optional[Tunables] = None):
        self.destinations = destinations
        self.metadata = metadata
        self.profiles = profiles
        self.tunables = tunables or Tunables()
        # per-event-loop shared encode batchers (see _encode_batcher)
        self._encode_batchers = weakref.WeakKeyDictionary()
        # per-event-loop shared reconstruct batchers and read caches
        # (see _reconstruct_batcher / _chunk_cache)
        self._reconstruct_batchers = weakref.WeakKeyDictionary()
        self._chunk_caches = weakref.WeakKeyDictionary()
        # FileReference metadata cache (path -> parsed ref), LRU-bounded,
        # active only when the read cache is on; _file_ref_gen fences a
        # read that was in flight across a write of the same path
        self._file_refs: "OrderedDict[str, FileReference]" = OrderedDict()
        self._file_ref_gen = 0
        # cluster-pinned host pipeline (tunables.host_threads > 0), else
        # the process-shared one (see host_pipeline()); the lock makes
        # first-use construction single — clusters are already used from
        # multiple event loops in different threads (see the per-loop
        # batcher maps), and a lost race would leak a worker set
        self._own_host_pipeline = None
        self._host_pipeline_lock = threading.Lock()
        # ONE location-health scoreboard per cluster (cluster/health.py)
        # shared across every loop and worker thread via the shared
        # LocationContext (unlike the per-loop batchers/caches: health
        # memory must span loops — it is thread-safe by construction).
        # Every read/write completion feeds it; hedged reads arm only
        # when `tunables.hedge_ms` > 0.
        from chunky_bits_tpu.cluster.health import HealthScoreboard

        self._health = HealthScoreboard(hedge_ms=self.tunables.hedge_ms)
        self.tunables.location_context().health = self._health

    # ---- serde ----

    @classmethod
    def from_obj(cls, obj: dict) -> "Cluster":
        if not isinstance(obj, dict):
            raise SerdeError("cluster config must be a mapping")
        nodes_obj = None
        for key in ("destinations", "destination", "nodes", "node"):
            if key in obj:
                nodes_obj = obj[key]
                break
        if nodes_obj is None:
            raise SerdeError("cluster config missing destinations")
        meta_obj = obj.get("metadata")
        if meta_obj is None:
            raise SerdeError("cluster config missing metadata")
        if "profiles" not in obj:
            raise SerdeError("cluster config missing profiles")
        tunables_obj = None
        for key in ("tunables", "tunable", "tuning"):
            if key in obj:
                tunables_obj = obj[key]
                break
        return cls(
            destinations=ClusterNodes.from_obj(nodes_obj),
            metadata=metadata_from_obj(meta_obj),
            profiles=ClusterProfiles.from_obj(obj["profiles"]),
            tunables=Tunables.from_obj(tunables_obj),
        )

    def to_obj(self) -> dict:
        return {
            "destinations": self.destinations.to_obj(),
            "metadata": self.metadata.to_obj(),
            "profiles": self.profiles.to_obj(),
            "tunables": self.tunables.to_obj(),
        }

    @classmethod
    async def from_location(cls, location: Union[str, Location],
                            ) -> "Cluster":
        """Load cluster.yaml from any Location (cluster.rs:59-63)."""
        obj = await MetadataFormat("yaml").from_location(location)
        return cls.from_obj(obj)

    # ---- profiles ----

    def get_profile(self, name: Optional[str] = None
                    ) -> Optional[ClusterProfile]:
        return self.profiles.get(name)

    # ---- write path ----

    def get_destination(self, profile: ClusterProfile) -> Destination:
        return Destination(
            self.destinations, profile, self.tunables.location_context())

    def health_scoreboard(self):
        """The cluster's shared location-health scoreboard
        (cluster/health.py): EWMA latency, error rate, breaker state
        per storage node, plus the hedged-read budget/counters."""
        return self._health

    def get_destination_with_profiler(
        self, profile: ClusterProfile
    ) -> tuple[object, Destination]:
        profiler, reporter = new_profiler()
        # write reports carry the per-location health table alongside
        # the I/O log (the read path attaches it in read_buffers)
        profiler.attach_health(self._health)
        cx = self.tunables.location_context().but_with(profiler=profiler)
        return reporter, Destination(self.destinations, profile, cx)

    def _encode_batcher(self):
        """Per-event-loop shared EncodeHashBatcher so concurrent writes
        into this cluster (e.g. parallel gateway PUTs of small objects)
        coalesce into single device dispatches.  Device backends only:
        the native path's fused zero-copy pass beats an extra memcpy."""
        if not self.tunables.is_device_backend():
            return None
        loop = asyncio.get_running_loop()
        batcher = self._encode_batchers.get(loop)
        if batcher is None:
            from chunky_bits_tpu.ops.batching import EncodeHashBatcher

            batcher = EncodeHashBatcher(backend=self.tunables.backend,
                                        host_pipeline=self.host_pipeline())
            self._encode_batchers[loop] = batcher
        return batcher

    def host_pipeline(self):
        """This cluster's host compute executor (per-shard SHA-256 +
        per-stripe GF encode workers, parallel/host_pipeline.py): a
        cluster-pinned instance when ``tunables.host_threads`` is set in
        cluster.yaml, else the process-shared auto-sized pipeline.  Every
        ingest path of this cluster (write_file, gateway PUT) draws from
        it, so the thread budget is one knob, not per-call-site.  Known
        exception: a *device* backend's internal ingest hashing
        (jax_backend.encode_and_hash, mesh async-dispatch) rides the
        process-shared pipeline, whose size the
        ``CHUNKY_BITS_TPU_HOST_THREADS`` env var caps — backends have no
        cluster context to thread the pinned instance through."""
        from chunky_bits_tpu.parallel.host_pipeline import (
            HostPipeline,
            get_host_pipeline,
        )

        n = self.tunables.host_threads
        if n <= 0:
            return get_host_pipeline()
        with self._host_pipeline_lock:
            if self._own_host_pipeline is None:
                self._own_host_pipeline = HostPipeline(threads=n)
            return self._own_host_pipeline

    def get_file_writer(self, profile: ClusterProfile) -> FileWriteBuilder:
        # Staging several parts per encode dispatch amortizes per-part
        # overhead for every backend: device backends save dispatch RPC,
        # and the CPU backends save the per-part to_thread/orchestration
        # machinery (the writer's staging groups full parts as zero-copy
        # slices, so unlike the batcher's concatenate this costs no extra
        # memcpy — measured +17% on config 2 native, more at small d
        # where per-part overhead looms larger).  Device backends
        # additionally coalesce across concurrent writes (shared encode
        # batcher).
        batch_parts = 8
        return (
            FileWriteBuilder()
            .with_destination(self.get_destination(profile))
            .with_chunk_size(profile.get_chunk_size())
            .with_data_chunks(profile.get_data_chunks())
            # deliberate fix of the reference's missing parity setter
            .with_parity_chunks(profile.get_parity_chunks())
            .with_backend(self.tunables.backend)
            .with_batch_parts(batch_parts)
            .with_encode_batcher(self._encode_batcher)
            .with_host_pipeline(self.host_pipeline())
            .with_repair_block_bytes(self.tunables.repair_block_bytes)
            .with_code(profile.get_code())
        )

    async def write_file_ref(self, path: str,
                             file_ref: FileReference) -> None:
        # invalidate around BOTH edges of the durable write: the bump
        # before it fences get_file_ref calls already parsing the old
        # bytes, and the bump after it fences calls that started DURING
        # the write (new generation snapshot, old on-disk bytes) — either
        # way a stale parse can never be re-inserted
        self._file_ref_gen += 1
        self._file_refs.pop(path, None)
        try:
            await self.metadata.write(path, file_ref.to_obj())
        finally:
            self._file_ref_gen += 1
            self._file_refs.pop(path, None)

    async def write_file(self, path: str, reader: aio.AsyncByteReader,
                         profile: ClusterProfile,
                         content_type: Optional[str] = None) -> FileReference:
        file_ref = await self.get_file_writer(profile).write(reader)
        file_ref.content_type = content_type
        await self.write_file_ref(path, file_ref)
        return file_ref

    async def write_file_with_report(
        self, path: str, reader: aio.AsyncByteReader,
        profile: ClusterProfile, content_type: Optional[str] = None,
    ) -> tuple[ProfileReport, FileReference]:
        reporter, destination = self.get_destination_with_profiler(profile)
        file_ref = await (
            self.get_file_writer(profile)
            .with_destination(destination)
            .write(reader)
        )
        file_ref.content_type = content_type
        await self.write_file_ref(path, file_ref)
        return reporter.profile(), file_ref

    # ---- read path ----

    #: FileReference cache bound (entries, not bytes: a parsed ref is
    #: tiny next to the chunk buffers the byte budget governs)
    FILE_REF_CACHE_ENTRIES = 1024

    def _reconstruct_batcher(self):
        """Per-event-loop shared ReconstructBatcher, mirroring
        ``_encode_batcher``: concurrent degraded GETs (and resilver-like
        readers) coalesce into single batched reconstruct dispatches
        instead of one batcher per read stream.  Shared for every
        backend — the decode-layout stacking wins on CPU too (BASELINE
        config 3) — and never aclosed: it owns no OS resources, and its
        in-flight dispatch tasks finish with the reads that await them."""
        loop = asyncio.get_running_loop()
        batcher = self._reconstruct_batchers.get(loop)
        if batcher is None:
            from chunky_bits_tpu.ops.batching import ReconstructBatcher

            batcher = ReconstructBatcher(backend=self.tunables.backend)
            self._reconstruct_batchers[loop] = batcher
        return batcher

    def _chunk_cache(self):
        """Per-event-loop content-addressed read cache, or None when the
        ``cache_bytes`` tunable leaves it off (the default)."""
        if self.tunables.cache_bytes <= 0:
            return None
        loop = asyncio.get_running_loop()
        cache = self._chunk_caches.get(loop)
        if cache is None:
            from chunky_bits_tpu.file.chunk_cache import ChunkCache

            cache = ChunkCache(self.tunables.cache_bytes)
            self._chunk_caches[loop] = cache
        return cache

    async def get_file_ref(self, path: str) -> FileReference:
        cache_on = self.tunables.cache_bytes > 0
        if cache_on:
            ref = self._file_refs.get(path)
            if ref is not None:
                self._file_refs.move_to_end(path)
                return ref
        gen = self._file_ref_gen
        obj = await self.metadata.read(path)
        ref = FileReference.from_obj(obj)
        # insert only if no write invalidated the cache while this read
        # was in flight — otherwise we could durably cache a stale ref
        if cache_on and gen == self._file_ref_gen:
            self._file_refs[path] = ref
            while len(self._file_refs) > self.FILE_REF_CACHE_ENTRIES:
                self._file_refs.popitem(last=False)
        return ref

    def file_read_builder(self, file_ref: FileReference) -> FileReadBuilder:
        """The serve-path read builder: cluster context, backend, the
        per-loop shared reconstruct batcher, and (when enabled) the
        chunk cache.  The gateway and ``read_file`` both come through
        here so every GET shares the same coalescing and cache."""
        return (
            file_ref.read_builder(self.tunables.location_context())
            .with_backend(self.tunables.backend)
            .with_batcher(self._reconstruct_batcher())
            .with_cache(self._chunk_cache())
            .with_pipeline(self.host_pipeline())
        )

    async def read_file(self, path: str) -> aio.AsyncByteReader:
        file_ref = await self.get_file_ref(path)
        return self.file_read_builder(file_ref).reader()

    async def list_files(self, path: str = ".") -> list[FileOrDirectory]:
        return await self.metadata.list(path)
