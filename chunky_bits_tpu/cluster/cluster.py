"""The cluster façade: config-deserialized top level of the system.

Mirrors src/cluster/cluster.rs: ``{destinations, metadata, profiles,
tunables}`` with serde aliases (``nodes``/``node``/``destination``,
``tunable``/``tuning``; cluster.rs:43-56).  Builds write pipelines over the
placement engine, reads files back through the part codec, lists metadata.

The reference's ``get_file_writer`` forgets to set ``parity_chunks``
(cluster.rs:65-71 — profile parity is silently replaced by the library
default of 2); that bug is fixed here, matching the behavior of its own
``write_file_with_report`` (cluster.rs:109-113), per SURVEY §7.
"""

from __future__ import annotations

import asyncio
import weakref
from typing import Optional, Union

from chunky_bits_tpu.cluster.destination import Destination
from chunky_bits_tpu.cluster.metadata import (
    FileOrDirectory,
    MetadataFormat,
    MetadataStore,
    metadata_from_obj,
)
from chunky_bits_tpu.cluster.nodes import ClusterNodes
from chunky_bits_tpu.cluster.profile import ClusterProfile, ClusterProfiles
from chunky_bits_tpu.cluster.tunables import Tunables
from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.file_reference import FileReference
from chunky_bits_tpu.file.location import Location
from chunky_bits_tpu.file.profiler import ProfileReport, new_profiler
from chunky_bits_tpu.file.writer import FileWriteBuilder
from chunky_bits_tpu.utils import aio


class Cluster:
    def __init__(self, destinations: ClusterNodes,
                 metadata: MetadataStore,
                 profiles: ClusterProfiles,
                 tunables: Optional[Tunables] = None):
        self.destinations = destinations
        self.metadata = metadata
        self.profiles = profiles
        self.tunables = tunables or Tunables()
        # per-event-loop shared encode batchers (see _encode_batcher)
        self._encode_batchers = weakref.WeakKeyDictionary()

    # ---- serde ----

    @classmethod
    def from_obj(cls, obj: dict) -> "Cluster":
        if not isinstance(obj, dict):
            raise SerdeError("cluster config must be a mapping")
        nodes_obj = None
        for key in ("destinations", "destination", "nodes", "node"):
            if key in obj:
                nodes_obj = obj[key]
                break
        if nodes_obj is None:
            raise SerdeError("cluster config missing destinations")
        meta_obj = obj.get("metadata")
        if meta_obj is None:
            raise SerdeError("cluster config missing metadata")
        if "profiles" not in obj:
            raise SerdeError("cluster config missing profiles")
        tunables_obj = None
        for key in ("tunables", "tunable", "tuning"):
            if key in obj:
                tunables_obj = obj[key]
                break
        return cls(
            destinations=ClusterNodes.from_obj(nodes_obj),
            metadata=metadata_from_obj(meta_obj),
            profiles=ClusterProfiles.from_obj(obj["profiles"]),
            tunables=Tunables.from_obj(tunables_obj),
        )

    def to_obj(self) -> dict:
        return {
            "destinations": self.destinations.to_obj(),
            "metadata": self.metadata.to_obj(),
            "profiles": self.profiles.to_obj(),
            "tunables": self.tunables.to_obj(),
        }

    @classmethod
    async def from_location(cls, location: Union[str, Location],
                            ) -> "Cluster":
        """Load cluster.yaml from any Location (cluster.rs:59-63)."""
        obj = await MetadataFormat("yaml").from_location(location)
        return cls.from_obj(obj)

    # ---- profiles ----

    def get_profile(self, name: Optional[str] = None
                    ) -> Optional[ClusterProfile]:
        return self.profiles.get(name)

    # ---- write path ----

    def get_destination(self, profile: ClusterProfile) -> Destination:
        return Destination(
            self.destinations, profile, self.tunables.location_context())

    def get_destination_with_profiler(
        self, profile: ClusterProfile
    ) -> tuple[object, Destination]:
        profiler, reporter = new_profiler()
        cx = self.tunables.location_context().but_with(profiler=profiler)
        return reporter, Destination(self.destinations, profile, cx)

    def _encode_batcher(self):
        """Per-event-loop shared EncodeHashBatcher so concurrent writes
        into this cluster (e.g. parallel gateway PUTs of small objects)
        coalesce into single device dispatches.  Device backends only:
        the native path's fused zero-copy pass beats an extra memcpy."""
        if not self.tunables.is_device_backend():
            return None
        loop = asyncio.get_running_loop()
        batcher = self._encode_batchers.get(loop)
        if batcher is None:
            from chunky_bits_tpu.ops.batching import EncodeHashBatcher

            batcher = EncodeHashBatcher(backend=self.tunables.backend)
            self._encode_batchers[loop] = batcher
        return batcher

    def get_file_writer(self, profile: ClusterProfile) -> FileWriteBuilder:
        # Staging several parts per encode dispatch amortizes per-part
        # overhead for every backend: device backends save dispatch RPC,
        # and the CPU backends save the per-part to_thread/orchestration
        # machinery (the writer's staging groups full parts as zero-copy
        # slices, so unlike the batcher's concatenate this costs no extra
        # memcpy — measured +17% on config 2 native, more at small d
        # where per-part overhead looms larger).  Device backends
        # additionally coalesce across concurrent writes (shared encode
        # batcher).
        batch_parts = 8
        return (
            FileWriteBuilder()
            .with_destination(self.get_destination(profile))
            .with_chunk_size(profile.get_chunk_size())
            .with_data_chunks(profile.get_data_chunks())
            # deliberate fix of the reference's missing parity setter
            .with_parity_chunks(profile.get_parity_chunks())
            .with_backend(self.tunables.backend)
            .with_batch_parts(batch_parts)
            .with_encode_batcher(self._encode_batcher)
        )

    async def write_file_ref(self, path: str,
                             file_ref: FileReference) -> None:
        await self.metadata.write(path, file_ref.to_obj())

    async def write_file(self, path: str, reader: aio.AsyncByteReader,
                         profile: ClusterProfile,
                         content_type: Optional[str] = None) -> FileReference:
        file_ref = await self.get_file_writer(profile).write(reader)
        file_ref.content_type = content_type
        await self.write_file_ref(path, file_ref)
        return file_ref

    async def write_file_with_report(
        self, path: str, reader: aio.AsyncByteReader,
        profile: ClusterProfile, content_type: Optional[str] = None,
    ) -> tuple[ProfileReport, FileReference]:
        reporter, destination = self.get_destination_with_profiler(profile)
        file_ref = await (
            self.get_file_writer(profile)
            .with_destination(destination)
            .write(reader)
        )
        file_ref.content_type = content_type
        await self.write_file_ref(path, file_ref)
        return reporter.profile(), file_ref

    # ---- read path ----

    async def get_file_ref(self, path: str) -> FileReference:
        obj = await self.metadata.read(path)
        return FileReference.from_obj(obj)

    async def read_file(self, path: str) -> aio.AsyncByteReader:
        file_ref = await self.get_file_ref(path)
        builder = file_ref.read_builder(self.tunables.location_context())
        return builder.with_backend(self.tunables.backend).reader()

    async def list_files(self, path: str = ".") -> list[FileOrDirectory]:
        return await self.metadata.list(path)
