"""Multi-tenant QoS: weighted-fair admission + priority classes.

ROADMAP item 4.  The gateway used to shed load *globally* (503 past
``--max-concurrent-gets``), so one hot tenant starved everyone.  This
module is the per-tenant scheduler the serving plane puts in front of
GET body streaming and PUT ingest:

**Closed, bounded tenant table.**  Tenants are *named in config* (the
YAML ``qos:`` mapping — API keys and/or path prefixes per tenant) plus
exactly one ``other`` bucket for everything unmatched.  Resolution can
therefore never mint a new tenant at runtime: an attacker rotating
10k API keys still lands in ``other``, and the ``tenant`` metric label
stays a CLOSED set (CB107) with the ``MAX_LABEL_SETS`` ceiling safely
out of reach (:data:`MAX_TENANTS` named tenants + ``other``).

**Deficit round robin** (Shreedhar & Varghese, SIGCOMM '95).  Each
class ("read", "write") has a concurrency capacity; when it is
saturated, arrivals queue *per tenant* and grants rotate tenants,
crediting each visit ``weight x QUANTUM`` bytes of deficit and
releasing waiters while their byte cost fits.  Cost is the response
(or request) byte size, so fairness is measured in *bytes served*,
not request count — a tenant of tiny objects is not starved by a
tenant of huge ones.  Optional per-tenant byte-rate ``TokenBucket``s
(reusing the scrub bucket, clock-seam timed) bound sustained
throughput *before* a slot is contended.

**Priority classes: client reads > writes > hedges > scrub/repair.**
Reads never wait on writes (separate capacities); write grants are
deferred while read waiters queue (:meth:`QosScheduler._write_gated`);
the :meth:`pressure` signal (read in-flight / capacity, saturating to
1.0 once readers queue) feeds two downstream throttles the gateway
wires up: the scrub/repair ``TokenBucket.set_pressure`` hook (accrual
scaled by ``1 - pressure``) and the scoreboard's hedge gate
(:meth:`allow_hedge`), so background I/O and speculative hedge load
yield *before* client traffic queues.

**SLO-aware hedging.**  ``allow_hedge`` spends the scoreboard's <=5%
hedge budget where p99 headroom is worst: under admission pressure
hedges are suppressed outright; with ample read-p99 headroom (observed
p99 below half the objective, from the same ``note_request`` samples
the access log feeds) the budget is conserved for when the tail
actually threatens the objective.  No signal (cold ring) means allow —
exactly the pre-QoS behavior.

**Degrade, never hang** (CB404): a queued waiter is bounded by
``QUEUE_TIMEOUT_S`` via ``asyncio.wait_for`` — a wedged scheduler
sheds (503, clients back off) instead of parking requests forever.

Loop discipline: queue state is mutated only from the owning event
loop (the gateway is single-loop per worker BY DESIGN); counters are
plain ints read lock-free by ``stats()`` / the metrics adapter
(CPython atomic loads — same contract as the cache's counters).
Time goes through the clock seam (CB108), so the SAME scheduler runs
in compressed virtual time under ``sim.run`` — the ``noisy_neighbor``
scenario proves isolation deterministically at N=100.

Default OFF via ``tunables.qos_enabled`` / ``$CHUNKY_BITS_TPU_QOS``
(YAML ``qos.enabled`` wins when present): nothing constructs a
scheduler until the gateway asks, zero overhead off (bench --config 19
pins the A/B).
"""

from __future__ import annotations

import asyncio
from collections import deque
from dataclasses import dataclass
from typing import Optional

from chunky_bits_tpu.cluster import clock as _clock
from chunky_bits_tpu.cluster.scrub import TokenBucket
from chunky_bits_tpu.obs import metrics as obs_metrics

__all__ = [
    "MAX_TENANTS",
    "OTHER",
    "QosConfig",
    "QosScheduler",
    "QosShedError",
    "QosStats",
    "TenantSpec",
]

#: the reserved catch-all tenant — always present, never configurable
#: beyond its weight; unmatched keys/paths land here so the tenant
#: label set is closed by construction
OTHER = "other"

#: hard bound on *named* tenants (plus ``other``) — keeps the
#: per-tenant metric families far under ``MAX_LABEL_SETS`` even with
#: the class dimension multiplied in
MAX_TENANTS = 32

#: DRR quantum per unit weight, bytes.  One weight-1 visit credits a
#: typical chunk-sized response; costs above the quantum simply take
#: several rotations to accrue (classic DRR latency behavior).
QUANTUM = 64 * 1024

#: nominal cost when the byte size is unknown (PUT without
#: Content-Length, HEAD-shaped internals) — one quantum, so unknown
#: costs neither starve nor dominate a rotation
DEFAULT_COST = QUANTUM

#: per-tenant queue bound — arrivals past this shed (503) instead of
#: queueing; bounds waiter memory AND worst-case queue latency
MAX_QUEUE = 64

#: admission-wait deadline ("degrade, never hang"): a waiter not
#: granted within this window sheds instead of parking forever
QUEUE_TIMEOUT_S = 30.0

#: pressure at/above which hedge launches are suppressed — half the
#: read capacity in flight means speculative load is about to compete
#: with client traffic
HEDGE_SUPPRESS_PRESSURE = 0.5

#: latency samples per class for the SLO-aware hedge advisor (matches
#: the scoreboard's SAMPLE_WINDOW scale)
SAMPLE_WINDOW = 128

#: below this many read samples the advisor has no p99 signal and
#: allows hedging (the pre-QoS default)
MIN_SAMPLES = 16

#: admission classes — also the closed value set of the ``class``
#: metric label (CB107)
CLASSES = ("read", "write")


class QosShedError(Exception):
    """Admission refused: per-tenant queue full or wait deadline hit.
    The gateway maps this to 503 + derived ``Retry-After``."""


@dataclass(frozen=True)
class TenantSpec:
    """One named tenant from the YAML ``qos:`` mapping."""

    name: str
    weight: float = 1.0
    #: sustained byte-rate bound, 0 = unbounded
    rate_bytes_per_sec: float = 0.0
    #: exact API-key matches (``X-Api-Key`` header)
    keys: tuple = ()
    #: path prefixes (longest match wins across tenants)
    prefixes: tuple = ()


def _spec_from_obj(name: str, obj: object) -> TenantSpec:
    if not isinstance(obj, dict):
        raise ValueError(f"tenant {name!r}: expected a mapping, "
                         f"got {type(obj).__name__}")
    unknown = set(obj) - {"weight", "rate_bytes_per_sec", "keys",
                          "prefixes"}
    if unknown:
        raise ValueError(
            f"tenant {name!r}: unknown keys {sorted(unknown)}")
    weight = obj.get("weight", 1.0)
    if not isinstance(weight, (int, float)) or isinstance(weight, bool) \
            or weight < 1:
        raise ValueError(f"tenant {name!r}: weight must be a number "
                         f">= 1, got {weight!r}")
    rate = obj.get("rate_bytes_per_sec", 0.0)
    if not isinstance(rate, (int, float)) or isinstance(rate, bool) \
            or rate < 0:
        raise ValueError(f"tenant {name!r}: rate_bytes_per_sec must "
                         f"be a number >= 0, got {rate!r}")
    keys = obj.get("keys", ())
    prefixes = obj.get("prefixes", ())
    for label, seq in (("keys", keys), ("prefixes", prefixes)):
        if not isinstance(seq, (list, tuple)) \
                or not all(isinstance(s, str) and s for s in seq):
            raise ValueError(f"tenant {name!r}: {label} must be a "
                             "list of non-empty strings")
    return TenantSpec(name=name, weight=float(weight),
                      rate_bytes_per_sec=float(rate),
                      keys=tuple(keys), prefixes=tuple(prefixes))


@dataclass(frozen=True)
class QosConfig:
    """Parsed+validated ``qos:`` mapping: the closed tenant table and
    the resolution maps.  ``enabled`` tri-state: True/False from YAML,
    None = defer to ``tunables.qos_enabled()`` (the env flag)."""

    tenants: tuple = ()
    enabled: Optional[bool] = None
    other_weight: float = 1.0

    @classmethod
    def from_obj(cls, obj: object) -> "QosConfig":
        """Loud validation (unknown keys raise) — the same contract as
        ``SloObjectives.from_obj``; ``cluster/tunables.py`` wraps the
        ValueError in a SerdeError with the config path context."""
        if not isinstance(obj, dict):
            raise ValueError(
                f"expected a mapping, got {type(obj).__name__}")
        unknown = set(obj) - {"enabled", "tenants", OTHER}
        if unknown:
            raise ValueError(f"unknown keys {sorted(unknown)} "
                             f"(expected enabled/tenants/{OTHER})")
        enabled = obj.get("enabled")
        if enabled is not None and not isinstance(enabled, bool):
            raise ValueError(
                f"enabled must be a bool, got {enabled!r}")
        other_weight = 1.0
        other_v = obj.get(OTHER)
        if other_v is not None:
            if not isinstance(other_v, dict) \
                    or set(other_v) - {"weight"}:
                raise ValueError(
                    f"{OTHER!r} accepts only a weight mapping")
            other_weight = other_v.get("weight", 1.0)
            if not isinstance(other_weight, (int, float)) \
                    or isinstance(other_weight, bool) \
                    or other_weight < 1:
                raise ValueError(f"{OTHER!r}: weight must be a number "
                                 f">= 1, got {other_weight!r}")
        tenants_v = obj.get("tenants", {})
        if not isinstance(tenants_v, dict):
            raise ValueError("tenants must be a mapping of "
                             "name -> tenant spec")
        if len(tenants_v) > MAX_TENANTS:
            raise ValueError(f"{len(tenants_v)} named tenants exceeds "
                             f"MAX_TENANTS={MAX_TENANTS}")
        specs = []
        seen_keys: dict = {}
        for name, spec_obj in tenants_v.items():
            if not isinstance(name, str) or not name:
                raise ValueError(
                    f"tenant names must be non-empty strings, "
                    f"got {name!r}")
            if name == OTHER:
                raise ValueError(
                    f"{OTHER!r} is reserved (configure its weight "
                    f"under the top-level {OTHER!r} key)")
            spec = _spec_from_obj(name, spec_obj)
            for key in spec.keys:
                if key in seen_keys:
                    raise ValueError(
                        f"api key {key!r} claimed by both "
                        f"{seen_keys[key]!r} and {name!r}")
                seen_keys[key] = name
            specs.append(spec)
        return cls(tenants=tuple(specs), enabled=enabled,
                   other_weight=float(other_weight))

    def __post_init__(self) -> None:
        by_key = {}
        prefixes = []
        for spec in self.tenants:
            for key in spec.keys:
                by_key[key] = spec.name
            for prefix in spec.prefixes:
                prefixes.append((prefix, spec.name))
        # longest prefix wins; resolution scans in sorted order
        prefixes.sort(key=lambda kv: len(kv[0]), reverse=True)
        object.__setattr__(self, "_by_key", by_key)
        object.__setattr__(self, "_prefixes", tuple(prefixes))

    def resolve(self, api_key: Optional[str], path: str) -> str:
        """Tenant for a request: exact API-key match wins, else the
        longest matching path prefix, else ``other``.  Total: every
        (key, path) maps to exactly one tenant in the closed table."""
        if api_key:
            name = self._by_key.get(api_key)
            if name is not None:
                return name
        for prefix, name in self._prefixes:
            if path.startswith(prefix):
                return name
        return OTHER

    def tenant_names(self) -> tuple:
        """The CLOSED tenant label set: every configured name plus
        ``other`` — nothing else can ever appear on a metric."""
        return tuple(s.name for s in self.tenants) + (OTHER,)

    def to_obj(self) -> dict:
        obj: dict = {}
        if self.enabled is not None:
            obj["enabled"] = self.enabled
        if self.other_weight != 1.0:
            obj[OTHER] = {"weight": self.other_weight}
        tenants = {}
        for s in self.tenants:
            row: dict = {}
            if s.weight != 1.0:
                row["weight"] = s.weight
            if s.rate_bytes_per_sec:
                row["rate_bytes_per_sec"] = s.rate_bytes_per_sec
            if s.keys:
                row["keys"] = list(s.keys)
            if s.prefixes:
                row["prefixes"] = list(s.prefixes)
            tenants[s.name] = row
        if tenants:
            obj["tenants"] = tenants
        return obj


@dataclass
class TenantRow:
    """Per-tenant counter snapshot (one ``Qos<...>`` stanza row, one
    label set per ``cb_qos_*`` family)."""

    tenant: str
    admitted: int
    shed: int
    bytes: int
    throttle_waits: int
    queued: int
    queue_peak: int

    def to_obj(self) -> dict:
        return {
            "admitted": self.admitted,
            "shed": self.shed,
            "bytes": self.bytes,
            "throttle_waits": self.throttle_waits,
            "queued": self.queued,
            "queue_peak": self.queue_peak,
        }


@dataclass
class QosStats:
    """Scheduler snapshot — the ``Qos<...>`` profiler stanza, the
    ``/stats`` qos stanza, and the ``cb_qos_*`` metric families all
    read THIS (one set of numbers everywhere)."""

    enabled: bool
    pressure: float
    hedge_suppressed: int
    hedge_conserved: int
    read_in_flight: int
    write_in_flight: int
    rows: tuple = ()

    def to_obj(self) -> dict:
        return {
            "enabled": self.enabled,
            "pressure": round(self.pressure, 4),
            "hedge_suppressed": self.hedge_suppressed,
            "hedge_conserved": self.hedge_conserved,
            "read_in_flight": self.read_in_flight,
            "write_in_flight": self.write_in_flight,
            "tenants": {r.tenant: r.to_obj() for r in self.rows},
        }

    def __str__(self) -> str:
        rows = ", ".join(
            f"{r.tenant}: adm={r.admitted} shed={r.shed} "
            f"q={r.queued}/{r.queue_peak}" for r in self.rows)
        return (f"Qos<pressure={self.pressure:.2f}, "
                f"in_flight={self.read_in_flight}r/"
                f"{self.write_in_flight}w, "
                f"hedge_suppressed={self.hedge_suppressed}, "
                f"{rows}>")


class _TenantState:
    """Mutable per-tenant scheduler state (loop-confined)."""

    __slots__ = ("name", "weight", "bucket", "deficit", "queues",
                 "admitted", "shed", "bytes", "throttle_waits",
                 "queue_peak")

    def __init__(self, name: str, weight: float,
                 rate: float = 0.0) -> None:
        self.name = name
        self.weight = max(float(weight), 1.0)
        self.bucket = TokenBucket(rate) if rate > 0 else None
        self.deficit = {cls: 0.0 for cls in CLASSES}
        #: per-class FIFO of [future, cost] waiter records
        self.queues = {cls: deque() for cls in CLASSES}
        self.admitted = 0
        self.shed = 0
        self.bytes = 0
        self.throttle_waits = 0
        self.queue_peak = 0


class QosScheduler:
    """Weighted-fair (DRR) admission over the closed tenant table.

    One per gateway worker (caches/scoreboards are per-worker BY
    DESIGN); the ``noisy_neighbor`` scenario drives one directly over
    cluster reads in virtual time.  Self-registers as a ``"qos"``
    stats source so ``/metrics`` folds ``cb_qos_*`` in with zero
    wiring (the PR-8 discipline)."""

    def __init__(self, config: QosConfig, *,
                 read_capacity: int = 256,
                 write_capacity: int = 32,
                 max_queue: int = MAX_QUEUE,
                 queue_timeout_s: float = QUEUE_TIMEOUT_S,
                 read_p99_objective_ms: float = 500.0) -> None:
        self.config = config
        self._capacity = {"read": max(int(read_capacity), 1),
                          "write": max(int(write_capacity), 1)}
        self._in_flight = {cls: 0 for cls in CLASSES}
        self._max_queue = max(int(max_queue), 1)
        self._queue_timeout_s = float(queue_timeout_s)
        self._read_p99_objective_ms = float(read_p99_objective_ms)
        self._tenants: dict = {}
        for spec in config.tenants:
            self._tenants[spec.name] = _TenantState(
                spec.name, spec.weight, spec.rate_bytes_per_sec)
        self._tenants[OTHER] = _TenantState(OTHER, config.other_weight)
        #: DRR rotation order per class (index into _order)
        self._order = tuple(self._tenants.values())
        self._rotor = {cls: 0 for cls in CLASSES}
        #: per-class completion-latency rings for the hedge advisor
        self._latency = {cls: deque(maxlen=SAMPLE_WINDOW)
                         for cls in CLASSES}
        self.hedge_suppressed = 0
        self.hedge_conserved = 0
        obs_metrics.get_registry().register_source("qos", self)

    # ---- admission ----

    def queued(self, cls: str) -> int:
        """Waiters currently queued in ``cls`` across all tenants
        (the gateway's derived Retry-After counts them as 'ahead')."""
        return sum(len(t.queues[cls]) for t in self._order)

    def _write_gated(self) -> bool:
        """Priority: client reads > writes — defer write grants while
        read waiters queue (writes already admitted keep running)."""
        return self.queued("read") > 0

    async def acquire(self, cls: str, tenant: str,
                      cost: Optional[int] = None) -> None:
        """Admit one ``cls`` request for ``tenant`` costing ``cost``
        bytes (None = :data:`DEFAULT_COST`).  Returns when a slot is
        granted; raises :class:`QosShedError` when the tenant queue is
        full or the wait deadline passes.  MUST be paired with
        :meth:`release` (the gateway does it in a finally)."""
        state = self._tenants.get(tenant) or self._tenants[OTHER]
        nbytes = DEFAULT_COST if cost is None else max(int(cost), 1)
        if state.bucket is not None:
            t0 = _clock.monotonic()
            await state.bucket.take(nbytes)
            if _clock.monotonic() - t0 > 0:
                state.throttle_waits += 1
        gated = cls == "write" and self._write_gated()
        if (not gated and self.queued(cls) == 0
                and self._in_flight[cls] < self._capacity[cls]):
            # fast path: nothing queued anywhere in this class — a
            # grant here cannot jump any tenant's line
            self._grant(state, cls, nbytes)
            return
        if len(state.queues[cls]) >= self._max_queue:
            state.shed += 1
            raise QosShedError(
                f"tenant {state.name!r} {cls} queue full "
                f"({self._max_queue})")
        fut: asyncio.Future = \
            asyncio.get_running_loop().create_future()
        record = [fut, nbytes]
        state.queues[cls].append(record)
        depth = len(state.queues[cls])
        if depth > state.queue_peak:
            state.queue_peak = depth
        try:
            await asyncio.wait_for(fut, self._queue_timeout_s)
        except asyncio.TimeoutError:
            # degrade, never hang: shed instead of parking forever
            self._discard(state, cls, record)
            state.shed += 1
            raise QosShedError(
                f"tenant {state.name!r} {cls} admission wait "
                f"exceeded {self._queue_timeout_s:.0f}s") from None
        except asyncio.CancelledError:
            # caller gone (client disconnect): leave the line, and if
            # the grant already landed give the slot back
            granted = fut.done() and not fut.cancelled()
            self._discard(state, cls, record)
            if granted:
                self.release(cls)
            raise

    @staticmethod
    def _discard(state: "_TenantState", cls: str, record: list) -> None:
        try:
            state.queues[cls].remove(record)
        except ValueError:
            pass  # already granted+popped

    def _grant(self, state: "_TenantState", cls: str,
               nbytes: int) -> None:
        self._in_flight[cls] += 1
        state.admitted += 1
        state.bytes += nbytes

    def release(self, cls: str) -> None:
        """Return one ``cls`` slot and run the DRR grant pass."""
        self._in_flight[cls] = max(self._in_flight[cls] - 1, 0)
        self._kick(cls)
        if cls == "read" and not self._write_gated():
            # read queue drained: un-gate deferred writes
            self._kick("write")

    def _kick(self, cls: str) -> None:
        """DRR grant pass: rotate tenants, credit weight x QUANTUM per
        visit, grant while the head waiter's cost fits the deficit and
        capacity remains.  A tenant with an empty queue forfeits its
        deficit (classic DRR — credit never accrues while idle)."""
        if cls == "write" and self._write_gated():
            return
        n = len(self._order)
        idle_streak = 0
        while (self._in_flight[cls] < self._capacity[cls]
                and idle_streak < n):
            state = self._order[self._rotor[cls] % n]
            queue = state.queues[cls]
            # drop waiters whose future died (timeout/disconnect races)
            while queue and queue[0][0].done():
                queue.popleft()
            if not queue:
                state.deficit[cls] = 0.0
                self._rotor[cls] += 1
                idle_streak += 1
                continue
            state.deficit[cls] += state.weight * QUANTUM
            granted_any = False
            while (queue
                    and self._in_flight[cls] < self._capacity[cls]
                    and queue[0][1] <= state.deficit[cls]):
                fut, nbytes = queue.popleft()
                if fut.done():
                    continue
                state.deficit[cls] -= nbytes
                self._grant(state, cls, nbytes)
                fut.set_result(None)
                granted_any = True
            if not queue:
                state.deficit[cls] = 0.0
            self._rotor[cls] += 1
            idle_streak = 0 if granted_any else idle_streak + 1
        if self._in_flight[cls] == 0:
            # work-conserving escape: with the pipe idle there is no
            # future release() to run another grant pass, so a waiter
            # whose cost out-sizes one rotation's deficit credit would
            # park until the shed deadline.  Serving it outright is
            # strictly better than idling — grant the next head in
            # rotor order regardless of deficit.
            for _ in range(n):
                state = self._order[self._rotor[cls] % n]
                self._rotor[cls] += 1
                queue = state.queues[cls]
                while queue and queue[0][0].done():
                    queue.popleft()
                if queue:
                    fut, nbytes = queue.popleft()
                    state.deficit[cls] = 0.0
                    self._grant(state, cls, nbytes)
                    fut.set_result(None)
                    break

    # ---- pressure + hedge advisor ----

    def pressure(self) -> float:
        """Gateway pressure in [0, 1]: read slots in flight over
        capacity, saturating to 1.0 the moment readers queue.  Feeds
        the scrub/repair bucket throttle and the hedge gate."""
        if self.queued("read") > 0:
            return 1.0
        return min(self._in_flight["read"] / self._capacity["read"],
                   1.0)

    def note_request(self, cls: str, duration_s: float) -> None:
        """Completion-latency sample from the access log — the hedge
        advisor's p99 signal (same numbers the profiler logs)."""
        ring = self._latency.get(cls)
        if ring is not None:
            ring.append(float(duration_s))

    def _read_p99_ms(self) -> Optional[float]:
        ring = self._latency["read"]
        if len(ring) < MIN_SAMPLES:
            return None
        ordered = sorted(ring)
        # same nearest-rank shape as file/profiler.percentile, inline
        # to keep this module import-light
        idx = min(int(len(ordered) * 0.99), len(ordered) - 1)
        return ordered[idx] * 1000.0

    def allow_hedge(self) -> bool:
        """The scoreboard's hedge gate: suppress speculative load
        under admission pressure; with ample read-p99 headroom,
        conserve the budget for when the tail threatens the
        objective.  No signal -> allow (pre-QoS behavior)."""
        if self.pressure() >= HEDGE_SUPPRESS_PRESSURE:
            self.hedge_suppressed += 1
            return False
        p99_ms = self._read_p99_ms()
        if p99_ms is not None \
                and p99_ms <= 0.5 * self._read_p99_objective_ms:
            self.hedge_conserved += 1
            return False
        return True

    # ---- stats ----

    def stats(self) -> QosStats:
        rows = tuple(
            TenantRow(
                tenant=t.name, admitted=t.admitted, shed=t.shed,
                bytes=t.bytes, throttle_waits=t.throttle_waits,
                queued=sum(len(q) for q in t.queues.values()),
                queue_peak=t.queue_peak)
            for t in self._order)
        return QosStats(
            enabled=True, pressure=self.pressure(),
            hedge_suppressed=self.hedge_suppressed,
            hedge_conserved=self.hedge_conserved,
            read_in_flight=self._in_flight["read"],
            write_in_flight=self._in_flight["write"],
            rows=rows)
