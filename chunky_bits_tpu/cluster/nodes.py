"""Node inventory with zone tagging.

Mirrors src/cluster/nodes.rs: a ``ClusterNode`` is a flattened
WeightedLocation plus a zone set and a ``repeat`` count (extra placement
slots, :65-73).  The deserializer accepts a single node, a list, or a map of
zone-name -> nodes — map members are auto-tagged with the zone name,
recursively (:26-63).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.weighted_location import (
    WeightedLocation,
)


@dataclass
class ClusterNode:
    location: WeightedLocation
    zones: set[str] = field(default_factory=set)
    repeat: int = 0

    @classmethod
    def from_obj(cls, obj) -> "ClusterNode":
        if isinstance(obj, str):
            return cls(location=WeightedLocation.parse(obj))
        if not isinstance(obj, dict) or "location" not in obj:
            raise SerdeError(f"invalid cluster node: {obj!r}")
        return cls(
            location=WeightedLocation.from_obj(obj),
            zones=set(obj.get("zones", []) or []),
            repeat=int(obj.get("repeat", 0) or 0),
        )

    def to_obj(self) -> dict:
        obj = {
            "weight": self.location.weight,
            "location": str(self.location.location),
        }
        if self.zones:
            obj["zones"] = sorted(self.zones)
        if self.repeat:
            obj["repeat"] = self.repeat
        return obj


class ClusterNodes:
    def __init__(self, nodes: list[ClusterNode]):
        self.nodes = nodes

    def __iter__(self):
        return iter(self.nodes)

    def __len__(self) -> int:
        return len(self.nodes)

    def __getitem__(self, i: int) -> ClusterNode:
        return self.nodes[i]

    @classmethod
    def from_obj(cls, obj) -> "ClusterNodes":
        return cls(cls._flatten(obj))

    @staticmethod
    def _flatten(obj) -> list[ClusterNode]:
        """Single / list / zone-map flattening (nodes.rs:26-63)."""
        if isinstance(obj, list):
            out: list[ClusterNode] = []
            for sub in obj:
                out.extend(ClusterNodes._flatten(sub))
            return out
        if isinstance(obj, dict) and "location" not in obj:
            out = []
            for zone_name, sub in sorted(obj.items()):
                for node in ClusterNodes._flatten(sub):
                    node.zones.add(zone_name)
                    out.append(node)
            return out
        return [ClusterNode.from_obj(obj)]

    def to_obj(self) -> list:
        return [n.to_obj() for n in self.nodes]

    def total_slots(self) -> int:
        """Placement capacity: sum of repeat+1
        (src/cluster/destination.rs:69-72)."""
        return sum(node.repeat + 1 for node in self.nodes)
