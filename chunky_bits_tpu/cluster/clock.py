"""The process-wide clock seam (canonical surface).

``Clock`` / ``VirtualClock`` / ``monotonic`` / ``sleep`` / ``install``
/ ``active`` / ``system_clock`` — every time-sensitive policy in the
cluster and file planes resolves time through this seam so the
deterministic cluster simulator (``chunky_bits_tpu/sim``) can swap the
system clock for a virtual one and run thousand-node fault scenarios
in compressed virtual time.  Lint rule CB108 (analysis/rules.py) pins
the discipline: direct ``time.monotonic()`` / ``time.time()`` /
``loop.time()`` reads in ``cluster/``, ``file/`` and
``ops/batching.py`` are flagged unless they carry a
``# lint: clock-ok <reason>`` justification (wall-clock timestamps for
humans — access-log times, slab publish stamps — stay real
deliberately).

The implementation lives in ``chunky_bits_tpu/utils/clock.py`` and is
re-exported here whole: ``file/`` modules must be importable without
triggering the ``cluster`` package ``__init__`` (which imports
``destination.py`` -> ``file.location`` and would cycle), the same
import-cycle hygiene that keeps ``TRANSIENT_HTTP_STATUSES`` in
``errors.py`` re-exported by ``cluster/health.py``.  Both names are
the same module-level state: ``install`` through either rebinds the
one active clock.
"""

from __future__ import annotations

#: re-exported whole — see the module docstring for why the
#: implementation lives on the utils side of the package graph
from chunky_bits_tpu.utils.clock import (  # noqa: F401
    Clock,
    VirtualClock,
    active,
    install,
    monotonic,
    sleep,
    system_clock,
)

__all__ = [
    "Clock",
    "VirtualClock",
    "active",
    "install",
    "monotonic",
    "sleep",
    "system_clock",
]
