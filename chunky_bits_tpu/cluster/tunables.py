"""Cluster-wide I/O knobs.

Mirrors src/cluster/tunables.rs:52-95: ``https_only`` (default false),
``on_conflict`` (default ignore — chunk files are content-addressed, so an
existing file with the right name is already correct), ``user_agent``, plus
the erasure ``backend`` selection (this framework's addition — the
north-star's cluster.yaml switch between cpu and TPU erasure backends).

``backend`` names: ``numpy`` / ``native`` (C++, all host cores) /
``native:4`` (C++ capped at 4 threads) / ``jax`` (single device) /
``jax:dp4,sp2`` / ``jax:tp4`` (device-mesh sharded; parallel/backend.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.location import IGNORE, OVERWRITE, LocationContext


@dataclass
class Tunables:
    https_only: bool = False
    on_conflict: str = IGNORE
    user_agent: Optional[str] = None
    backend: Optional[str] = None  # erasure backend name (None = auto)

    def is_device_backend(self) -> bool:
        """True when the erasure plane runs on an accelerator ("jax" or a
        mesh spec like "jax:dp4,sp2") — the regime where batching layers
        amortize dispatch overhead."""
        return (self.backend or "").startswith("jax")

    def __post_init__(self) -> None:
        self._location_context = LocationContext(
            on_conflict=self.on_conflict,
            https_only=self.https_only,
            user_agent=self.user_agent,
        )

    @classmethod
    def from_obj(cls, obj) -> "Tunables":
        if obj is None:
            return cls()
        if not isinstance(obj, dict):
            raise SerdeError("tunables must be a mapping")
        on_conflict = obj.get("on_conflict", IGNORE)
        if on_conflict not in (IGNORE, OVERWRITE):
            raise SerdeError(f"invalid on_conflict {on_conflict!r}")
        return cls(
            https_only=bool(obj.get("https_only", False)),
            on_conflict=on_conflict,
            user_agent=obj.get("user_agent"),
            backend=obj.get("backend"),
        )

    def to_obj(self) -> dict:
        obj = {
            "https_only": self.https_only,
            "on_conflict": self.on_conflict,
            "user_agent": self.user_agent,
        }
        if self.backend is not None:
            obj["backend"] = self.backend
        return obj

    def location_context(self) -> LocationContext:
        return self._location_context
