"""Cluster-wide I/O knobs.

Mirrors src/cluster/tunables.rs:52-95: ``https_only`` (default false),
``on_conflict`` (default ignore — chunk files are content-addressed, so an
existing file with the right name is already correct), ``user_agent``, plus
the erasure ``backend`` selection (this framework's addition — the
north-star's cluster.yaml switch between cpu and TPU erasure backends).

``backend`` names: ``numpy`` / ``native`` (C++, all host cores) /
``native:4`` (C++ capped at 4 threads) / ``jax`` (single device) /
``jax:dp4,sp2`` / ``jax:tp4`` (device-mesh sharded; parallel/backend.py).

``cache_bytes`` (TPU-repo extension, default 0 = off per the
measure-before-defaulting invariant) budgets the content-addressed read
cache on the serve path: verified chunk buffers keyed by sha256 digest,
plus the cluster's FileReference metadata cache.  YAML wins; the
``CHUNKY_BITS_TPU_CACHE_BYTES`` env var supplies the default so an
operator can turn the cache on without editing cluster.yaml.

``host_threads`` (TPU-repo extension, default 0 = auto) sizes the host
compute pipeline (parallel/host_pipeline.py) that runs per-shard
SHA-256 and per-stripe GF(2^8) encode for this cluster's ingest and
verify paths; same YAML-wins/env-default split via
``CHUNKY_BITS_TPU_HOST_THREADS``.

``hedge_ms`` (TPU-repo extension, default 0 = off) arms hedged chunk
reads (cluster/health.py + file/file_part.py): after an adaptive delay
(scoreboard p95 clamped to [hedge_ms, 20x]) a read races the next-best
location for the same chunk.  ``read_retries`` (default 1) gives
transient HTTP errors (408/429/5xx minus 507) one jittered-backoff
retry per location before fall-through/invalidation.  Both follow the
YAML-wins/env-default split (``CHUNKY_BITS_TPU_HEDGE_MS`` /
``CHUNKY_BITS_TPU_READ_RETRIES``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.location import IGNORE, OVERWRITE, LocationContext

CACHE_BYTES_ENV = "CHUNKY_BITS_TPU_CACHE_BYTES"

#: host compute worker count for the shared host pipeline
#: (parallel/host_pipeline.py): per-shard SHA-256 + per-stripe GF encode
#: workers.  0/unset = auto (one per core).  Read at first dispatch —
#: the shared pipeline is built once per process.
HOST_THREADS_ENV = "CHUNKY_BITS_TPU_HOST_THREADS"

#: the backend-selection handoff: the CLI --backend flag writes it, the
#: default resolution in ops/backend.get_backend reads it
BACKEND_ENV = "CHUNKY_BITS_TPU_BACKEND"

#: bounded in-flight depth of the device dispatch window
#: (ops/dispatch_pipeline.py, used by the ``mesh`` backend): 2 (the
#: default) is the classic double buffer — batch k+1 stages while
#: batch k computes and batch k-1 drains; 1 keeps a single dispatch in
#: flight; 0 disables overlap (every dispatch materializes
#: synchronously — bench --config 17's "off" leg).  Read at pipeline
#: construction (first backend use).
DISPATCH_DEPTH_ENV = "CHUNKY_BITS_TPU_DISPATCH_DEPTH"

#: hedged-read delay floor in milliseconds (cluster/health.py): after
#: this long (adaptively stretched to the scoreboard's p95, ceiling
#: 20x) a chunk read races the next-best location.  0/unset = hedging
#: off (the default — opt-in until measured, per CLAUDE.md; bench
#: --config 8 is the A/B).  YAML `hedge_ms` wins; the env var supplies
#: the default.
HEDGE_MS_ENV = "CHUNKY_BITS_TPU_HEDGE_MS"

#: per-location retry count for *transient* HTTP errors (408/429/5xx
#: minus 507) on the read fall-through and the shard-write failover
#: loop; one jittered backoff per retry.  Default 1; 0 restores
#: immediate fall-through.
READ_RETRIES_ENV = "CHUNKY_BITS_TPU_READ_RETRIES"

#: writer stagger: writer i waits this long for writer i-1's first
#: placement decision (the reference hardcodes 100 ms, writer.rs:246;
#: routed through here so the knob is discoverable and CB102-clean)
STAGGER_SECONDS_ENV = "CHUNKY_BITS_TPU_STAGGER_SECONDS"

#: gateway worker-process count (gateway/workers.py): N > 1 pre-forks
#: N SO_REUSEPORT serving processes, each with its own loop, host
#: pipeline, chunk cache, and health scoreboard.  A deployment knob,
#: not a cluster property, so it is env/CLI-only (no YAML field); the
#: ``serve --workers`` flag wins.  Read at serve start.
GATEWAY_WORKERS_ENV = "CHUNKY_BITS_TPU_GATEWAY_WORKERS"

#: zero-copy local-chunk streaming on the gateway GET path
#: (gateway/http.py): ranges covered by one verified whole chunk on a
#: local Location stream via loop.sendfile, bypassing reassembly.
#: Default on (bench --config 9 is the A/B; BASELINE.md records it);
#: set to a falsy value to force every GET through the reassembly
#: path.  Read at app build.
GATEWAY_SENDFILE_ENV = "CHUNKY_BITS_TPU_GATEWAY_SENDFILE"

#: continuous scrub/repair byte-rate bound (cluster/scrub.py): the
#: scrub daemon verifies chunks against their golden digests at most
#: this many bytes per second (token bucket, 1 s burst).  0/unset =
#: scrub off — the daemon is never constructed, zero overhead (the
#: measure-before-defaulting invariant: background repair traffic is
#: load, so it is opt-in).  YAML ``scrub_bytes_per_sec`` wins; the env
#: var supplies the default.  Read when the daemon starts (gateway
#: serve / `chunky-bits scrub`).
SCRUB_BYTES_PER_SEC_ENV = "CHUNKY_BITS_TPU_SCRUB_BYTES_PER_SEC"

#: per-chunk block-digest tree granularity in bytes (file/chunk.py
#: BlockDigests + cluster/repair.py): chunks longer than this get a
#: sha256-per-block tree written into their file-reference metadata on
#: the normal encode path, so scrub/verify localize corruption to block
#: ranges and the repair planner moves ≈damage bytes off helpers
#: instead of d whole chunks.  0/unset = off (the default — the tree
#: costs metadata bytes and one extra hash pass, so it is opt-in per
#: the measure-before-defaulting invariant; bench --config 11 is the
#: A/B).  YAML ``repair_block_bytes`` wins; the env var supplies the
#: default.  Read when a file writer is built.
REPAIR_BLOCK_BYTES_ENV = "CHUNKY_BITS_TPU_REPAIR_BLOCK_BYTES"

#: slow-request tracing threshold in milliseconds (obs/tracing.py +
#: gateway/http.py): requests at least this slow are retained — with
#: per-plane spans — in the slowest-N buffer served at /debug/traces.
#: 0/unset = tracing off entirely (the default — the trace ring is
#: opt-in per the measure-before-defaulting invariant; the metrics
#: registry itself is always on).  YAML ``trace_slow_ms`` wins; the env
#: var supplies the default.  Read at gateway app build.
TRACE_SLOW_MS_ENV = "CHUNKY_BITS_TPU_TRACE_SLOW_MS"

#: scheduled-XOR erasure engine for the CPU plane (ops/xor_schedule.py
#: + native/gf256.cpp `cb_xor_exec`): lower the GF(2^8) coding matrix
#: to a CSE'd pure-XOR program over bit-planes and execute it with
#: runtime-dispatched wide XORs instead of per-byte table lookups.
#: Byte-identical output either way (conformance fuzz + golden pin
#: it).  Off by default per the measure-before-defaulting invariant —
#: bench --config 12 is the A/B grid; on GFNI hosts the table path
#: wins, the XOR engine's domain is hosts/builds without SIMD table
#: kernels.  Read at first dispatch of each NativeBackend instance
#: (like every routing flag: set it before the first encode).
XOR_SCHEDULE_ENV = "CHUNKY_BITS_TPU_XOR_SCHEDULE"

#: default erasure code for write profiles that do not pin one in YAML
#: (cluster/profile.py ``code`` key; file/writer.py FileWriteBuilder):
#: "rs" (classic Reed-Solomon — the default) or "pm-msr" (product-
#: matrix MSR regenerating code, ops/pm_msr.py — single-chunk repair
#: from 2(d-1) helper projections at ~2x chunk bytes instead of d x).
#: A DEFAULT, not a force: profiles whose geometry cannot run pm-msr
#: (parity < data-1, alpha-indivisible chunk size) stay rs, so a
#: fleet-wide env flip — the CI pm-msr matrix leg — never breaks
#: incompatible profiles; explicit YAML ``code:`` wins both ways and
#: validates loudly.  Read when a write profile resolves its code
#: (cluster profile access / writer build) — per the
#: read-at-first-dispatch contract, set it before the first write.
CODE_ENV = "CHUNKY_BITS_TPU_CODE"

#: fleet-wide metadata-store kind override (cluster/metadata.py
#: ``metadata_from_obj``): ``meta-log`` rebuilds every ``type: path``
#: store (without a ``put_script`` — the log has no per-write hook) as
#: the indexed meta-log over the same root (cluster/meta_log.py).
#: Per-cluster YAML ``metadata: {type: meta-log}`` is the explicit
#: opt-in; this env var flips the default fleet-wide, like
#: ``CHUNKY_BITS_TPU_CODE`` does for erasure codes — and like it,
#: silently stays on the configured kind when incompatible.  Read when
#: a cluster config is loaded — set it before the cluster is built.
METADATA_KIND_ENV = "CHUNKY_BITS_TPU_METADATA_KIND"

#: SLO engine evaluation cadence in seconds (obs/slo.py +
#: gateway/http.py): > 0 runs the windowed burn-rate alert engine —
#: a bounded ring of registry snapshots evaluated against the closed
#: rule set every this-many seconds, surfaced at ``GET /alerts``, in
#: ``/stats``, and as ``cb_slo_*``/``cb_alerts_*`` metric families.
#: 0/unset = engine off entirely (the default — no ring, no ticker,
#: zero overhead, per the measure-before-defaulting invariant; bench
#: --config 15 is the overhead A/B).  Objective thresholds come from
#: the YAML ``slo:`` mapping (SloObjectives.from_obj — loud on unknown
#: keys).  YAML ``slo_eval_s`` wins; the env var supplies the default.
#: Read at gateway app build.
SLO_EVAL_S_ENV = "CHUNKY_BITS_TPU_SLO_EVAL_S"

#: multi-tenant QoS admission (cluster/qos.py + gateway/qos.py): on,
#: the gateway fronts GET body streaming and PUT ingest with a
#: deficit-round-robin scheduler over the closed tenant table (the
#: YAML ``qos:`` mapping), throttles the scrub/repair buckets and
#: suppresses hedge launches under admission pressure, and spends the
#: hedge budget by read-p99 headroom.  Off by default (zero overhead —
#: no scheduler object at all; bench --config 19 is the A/B).  YAML
#: ``qos.enabled`` wins when present; this env flag decides when the
#: YAML leaves it unset.  Read at gateway app build.
QOS_ENV = "CHUNKY_BITS_TPU_QOS"

#: opt-in runtime concurrency sanitizer (analysis/sanitizer.py):
#: event-loop stall watchdog, task-leak registry, host-pipeline handoff
#: checks.  Off by default (and force-disabled by bench.py — the
#: sanitizer is a correctness tool, not a perf mode); read at the
#: activation points (HostPipeline construction, gateway serve,
#: tests/conftest session start), so set it before the process builds
#: its first pipeline or loop.
SANITIZE_ENV = "CHUNKY_BITS_TPU_SANITIZE"


# ---- environment accessors (the ONE home for CHUNKY_BITS_TPU_* reads) ----
#
# Every ``CHUNKY_BITS_TPU_*`` read in the tree goes through these three
# accessors (lint rule CB102, chunky_bits_tpu/analysis).  Two contracts
# they deliberately do NOT change:
#
# - **Read-at-first-dispatch.**  Callers invoke the accessor at the
#   moment the knob takes effect (first backend resolution, first
#   device dispatch, first mmap decision) — never at import time and
#   never cached here.  Values feeding jit-compiled routing are baked
#   into compiled executables by the caller's jit cache, so flipping a
#   flag after the first encode of a process has no effect; set flags
#   before the first dispatch (CLAUDE.md "Measure before defaulting").
# - **One parse per knob shape.**  Truthiness (env_flag) and duration
#   (env_seconds) parse identically for every flag, so operators learn
#   one spelling; per-knob defaults stay at the call site where the
#   behavior they gate is defined.

_FALSY = ("", "0", "false", "no", "off")


def env_str(name: str, default: str = "") -> str:
    """Raw string value of an env knob; unset reads as ``default``."""
    return os.environ.get(name, default)


def env_flag(name: str, *, default: bool = False) -> bool:
    """Standard boolean env-flag parsing: unset -> ``default``;
    "", "0", "false", "no", "off" (any case/whitespace) -> False;
    anything else -> True."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSY


def env_seconds(name: str, *, default: float) -> float:
    """Duration env knob in seconds; unset/empty -> ``default``.  A
    malformed value raises ``ValueError`` — a config typo must fail the
    caller loudly, not read as a device outage and silently degrade."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"bad ${name}={raw!r} (want seconds)") from None


def host_threads(*, default: int = 0) -> int:
    """Requested host compute worker count from
    ``$CHUNKY_BITS_TPU_HOST_THREADS``; unset/malformed/non-positive reads
    as ``default`` (0 = auto: one worker per core).  Lenient like
    ``cache_bytes`` — a perf knob can only *tune*, never crash, process
    startup.  The scheduler itself clamps to ``min(N, nproc)`` for the
    shared pipeline (parallel/host_pipeline.get_host_pipeline); explicit
    ``HostPipeline(threads=N)`` instances honor N exactly so scaling
    sweeps and tests can oversubscribe deliberately."""
    raw = os.environ.get(HOST_THREADS_ENV, "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def dispatch_depth(*, default: int = 2) -> int:
    """Requested dispatch-window depth from
    ``$CHUNKY_BITS_TPU_DISPATCH_DEPTH``; unset/malformed/negative reads
    as ``default``.  Lenient like ``host_threads`` — a perf knob can
    only *tune*, never crash, process startup.  0 is a valid value
    (overlap off, fully serial dispatch)."""
    raw = os.environ.get(DISPATCH_DEPTH_ENV, "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def sanitize_enabled() -> bool:
    """True when ``$CHUNKY_BITS_TPU_SANITIZE`` asks for the runtime
    concurrency sanitizer.  Callers gate BOTH the instrumentation and
    the ``analysis.sanitizer`` import on this, so the off path never
    even loads the instrumentation module (pinned by
    tests/test_sanitizer.py's zero-overhead check)."""
    return env_flag(SANITIZE_ENV)


def xor_schedule_enabled(*, default: bool = False) -> bool:
    """True when ``$CHUNKY_BITS_TPU_XOR_SCHEDULE`` asks the native
    erasure backend to run GF(2^8) matrix applies as scheduled wide
    XORs over bit-planes (ops/xor_schedule.py) instead of per-byte
    table kernels.  Output is byte-identical either way; the knob only
    moves compute between engines, so it parses as a standard flag and
    is read at first dispatch (baked per backend instance)."""
    return env_flag(XOR_SCHEDULE_ENV, default=default)


def erasure_code(*, default: str = "rs") -> str:
    """Requested default erasure code from ``$CHUNKY_BITS_TPU_CODE``
    for write profiles that do not pin ``code:`` in YAML.  Lenient like
    every perf knob — an unknown value reads as ``default`` (the knob
    can only *select between shipped codes*, never crash config
    loading); geometry compatibility is the caller's check
    (cluster/profile.py resolves to "rs" when the profile cannot run
    the requested code)."""
    from chunky_bits_tpu.ops.backend import KNOWN_CODES

    raw = os.environ.get(CODE_ENV, "").strip()
    return raw if raw in KNOWN_CODES else default


def metadata_kind(*, default: str = "") -> str:
    """Requested fleet-wide metadata-store kind from
    ``$CHUNKY_BITS_TPU_METADATA_KIND`` for clusters whose YAML says
    ``type: path``.  Lenient like ``erasure_code`` — only the shipped
    override value ``meta-log`` is honored, anything else reads as
    ``default`` ("" = no override, file-per-ref stays the default);
    compatibility is the caller's check (``metadata_from_obj`` keeps
    ``path`` when a ``put_script`` is configured)."""
    raw = os.environ.get(METADATA_KIND_ENV, "").strip()
    return raw if raw == "meta-log" else default


def gateway_workers(*, default: int = 1) -> int:
    """Requested gateway worker-process count from
    ``$CHUNKY_BITS_TPU_GATEWAY_WORKERS``; unset/malformed/non-positive
    reads as ``default`` (1 = the classic single-process gateway).
    Lenient like ``host_threads`` — a scale knob can only *tune*, never
    crash, serve startup.  The ``http-gateway --workers`` CLI flag wins
    over the env var."""
    raw = os.environ.get(GATEWAY_WORKERS_ENV, "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def gateway_sendfile(*, default: bool = True) -> bool:
    """True when the gateway may stream verified whole local chunks via
    ``loop.sendfile`` (gateway/http.py).  Default on — measured in
    bench --config 9 (BASELINE.md); a falsy
    ``$CHUNKY_BITS_TPU_GATEWAY_SENDFILE`` forces the reassembly path
    everywhere (e.g. storage shared with external truncating writers,
    the same caveat as ``CHUNKY_BITS_TPU_NO_MMAP``).  Read at app
    build."""
    return env_flag(GATEWAY_SENDFILE_ENV, default=default)


def stagger_seconds(*, default: float = 0.1) -> float:
    """Shard-writer stagger window: how long writer ``i`` waits for
    writer ``i-1``'s first placement decision before proceeding
    (cluster/destination.py).  The reference pins 100 ms
    (src/cluster/writer.rs:246); this accessor keeps that default while
    making the knob visible and env-tunable like every other.  Lenient
    parse — a perf knob can only tune, never crash, placement."""
    raw = os.environ.get(STAGGER_SECONDS_ENV, "")
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def hedge_ms(*, default: float = 0.0) -> float:
    """Env-supplied default for the ``hedge_ms`` tunable (YAML wins;
    0 = hedged reads off).  Lenient like ``host_threads`` — malformed
    or negative values read as off."""
    raw = os.environ.get(HEDGE_MS_ENV, "")
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def scrub_bytes_per_sec(*, default: float = 0.0) -> float:
    """Env-supplied default for the ``scrub_bytes_per_sec`` tunable
    (YAML wins; 0 = the scrub daemon stays off).  Lenient like
    ``hedge_ms`` — malformed or negative values read as off."""
    raw = os.environ.get(SCRUB_BYTES_PER_SEC_ENV, "")
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def repair_block_bytes(*, default: int = 0) -> int:
    """Env-supplied default for the ``repair_block_bytes`` tunable
    (YAML wins; 0 = no block-digest trees written).  Lenient like
    ``cache_bytes`` — malformed or negative values read as off."""
    raw = os.environ.get(REPAIR_BLOCK_BYTES_ENV, "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def trace_slow_ms(*, default: float = 0.0) -> float:
    """Env-supplied default for the ``trace_slow_ms`` tunable (YAML
    wins; 0 = request tracing off).  Lenient like ``hedge_ms`` —
    malformed or negative values read as off."""
    raw = os.environ.get(TRACE_SLOW_MS_ENV, "")
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def slo_eval_s(*, default: float = 0.0) -> float:
    """Env-supplied default for the ``slo_eval_s`` tunable (YAML wins;
    0 = the SLO engine stays off).  Lenient like ``hedge_ms`` —
    malformed or negative values read as off."""
    raw = os.environ.get(SLO_EVAL_S_ENV, "")
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def qos_enabled(*, default: bool = False) -> bool:
    """True when ``$CHUNKY_BITS_TPU_QOS`` asks for multi-tenant QoS
    admission (cluster/qos.py).  YAML ``qos.enabled`` wins when the
    mapping sets it; this flag decides when it is absent — the same
    YAML-wins/env-default split every serving knob follows.  Read at
    gateway app build (gateway/qos.maybe_build)."""
    return env_flag(QOS_ENV, default=default)


def read_retries(*, default: int = 1) -> int:
    """Env-supplied default for the ``read_retries`` tunable (YAML
    wins): per-location transient-HTTP retry count on the read
    fall-through and the shard-write failover loop.  Lenient parse;
    negative reads as the default."""
    raw = os.environ.get(READ_RETRIES_ENV, "")
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def _default_hedge_ms() -> float:
    """Env-supplied default for the ``hedge_ms`` tunable."""
    return hedge_ms(default=0.0)


def _default_read_retries() -> int:
    """Env-supplied default for the ``read_retries`` tunable."""
    return read_retries(default=1)


def _default_scrub_bytes_per_sec() -> float:
    """Env-supplied default for the ``scrub_bytes_per_sec`` tunable
    (YAML wins; 0 = scrub daemon off)."""
    return scrub_bytes_per_sec(default=0.0)


def _default_trace_slow_ms() -> float:
    """Env-supplied default for the ``trace_slow_ms`` tunable (YAML
    wins; 0 = request tracing off)."""
    return trace_slow_ms(default=0.0)


def _default_slo_eval_s() -> float:
    """Env-supplied default for the ``slo_eval_s`` tunable (YAML wins;
    0 = SLO engine off)."""
    return slo_eval_s(default=0.0)


def _default_repair_block_bytes() -> int:
    """Env-supplied default for the ``repair_block_bytes`` tunable
    (YAML wins; 0 = block-digest trees off)."""
    return repair_block_bytes(default=0)


def _default_host_threads() -> int:
    """Env-supplied default for the ``host_threads`` tunable (YAML wins;
    0 = auto/shared pipeline)."""
    return host_threads(default=0)


def _default_cache_bytes() -> int:
    """Env-supplied default; malformed or negative values read as off
    (the knob can only *enable*, never crash, config loading)."""
    raw = os.environ.get(CACHE_BYTES_ENV, "")
    try:
        v = int(raw)
    except ValueError:
        return 0
    return max(v, 0)


@dataclass
class Tunables:
    https_only: bool = False
    on_conflict: str = IGNORE
    user_agent: Optional[str] = None
    backend: Optional[str] = None  # erasure backend name (None = auto)
    #: read-cache byte budget; 0 disables (the default — opt-in until
    #: measured, per CLAUDE.md)
    cache_bytes: int = field(default_factory=_default_cache_bytes)
    #: host pipeline worker count for this cluster's ingest/verify
    #: compute (per-shard SHA-256 + per-stripe GF encode); 0 = use the
    #: process-shared auto-sized pipeline.  YAML wins; the
    #: ``CHUNKY_BITS_TPU_HOST_THREADS`` env var supplies the default.
    host_threads: int = field(default_factory=_default_host_threads)
    #: hedged-read delay floor in milliseconds (cluster/health.py);
    #: 0 disables hedging (the default — opt-in until measured).  YAML
    #: wins; ``CHUNKY_BITS_TPU_HEDGE_MS`` supplies the default.
    hedge_ms: float = field(default_factory=_default_hedge_ms)
    #: per-location transient-HTTP retry count (reads fall-through +
    #: shard-write failover); YAML wins over
    #: ``CHUNKY_BITS_TPU_READ_RETRIES``.
    read_retries: int = field(default_factory=_default_read_retries)
    #: continuous-scrub byte-rate bound (cluster/scrub.py); 0 keeps the
    #: daemon off (the default — zero overhead when off).  YAML wins;
    #: ``CHUNKY_BITS_TPU_SCRUB_BYTES_PER_SEC`` supplies the default.
    scrub_bytes_per_sec: float = field(
        default_factory=_default_scrub_bytes_per_sec)
    #: slow-request tracing threshold in ms (obs/tracing.py); 0 keeps
    #: tracing off (the default — the trace ring is opt-in; the metrics
    #: registry is always on).  YAML wins;
    #: ``CHUNKY_BITS_TPU_TRACE_SLOW_MS`` supplies the default.
    trace_slow_ms: float = field(default_factory=_default_trace_slow_ms)
    #: block-digest tree granularity for damage localization
    #: (file/chunk.py BlockDigests); 0 keeps the trees off (the
    #: default).  YAML wins; ``CHUNKY_BITS_TPU_REPAIR_BLOCK_BYTES``
    #: supplies the default.
    repair_block_bytes: int = field(
        default_factory=_default_repair_block_bytes)
    #: SLO engine evaluation cadence in seconds (obs/slo.py); 0 keeps
    #: the engine off (the default — zero overhead when off).  YAML
    #: wins; ``CHUNKY_BITS_TPU_SLO_EVAL_S`` supplies the default.
    slo_eval_s: float = field(default_factory=_default_slo_eval_s)
    #: SLO objective overrides (the YAML ``slo:`` mapping, validated
    #: loudly against obs/slo.py SloObjectives' field set); empty =
    #: the conservative defaults
    slo: dict = field(default_factory=dict)
    #: multi-tenant QoS config (the YAML ``qos:`` mapping, validated
    #: loudly against cluster/qos.py QosConfig's key set); empty =
    #: no named tenants, scheduler on only via the env flag
    qos: dict = field(default_factory=dict)

    def is_device_backend(self) -> bool:
        """True when the erasure plane runs on an accelerator ("jax", a
        mesh spec like "jax:dp4,sp2", or the auto-laid-out "mesh") — the
        regime where batching layers amortize dispatch overhead."""
        b = self.backend or ""
        return b.startswith("jax") or b == "mesh"

    def __post_init__(self) -> None:
        self._location_context = LocationContext(
            on_conflict=self.on_conflict,
            https_only=self.https_only,
            user_agent=self.user_agent,
            read_retries=self.read_retries,
        )

    @classmethod
    def from_obj(cls, obj: object) -> "Tunables":
        if obj is None:
            return cls()
        if not isinstance(obj, dict):
            raise SerdeError("tunables must be a mapping")
        on_conflict = obj.get("on_conflict", IGNORE)
        if on_conflict not in (IGNORE, OVERWRITE):
            raise SerdeError(f"invalid on_conflict {on_conflict!r}")
        cache_bytes = obj.get("cache_bytes", None)
        if cache_bytes is not None:
            try:
                cache_bytes = int(cache_bytes)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid cache_bytes {cache_bytes!r}") from err
            if cache_bytes < 0:
                raise SerdeError(
                    f"cache_bytes must be >= 0, got {cache_bytes}")
        host_threads_v = obj.get("host_threads", None)
        if host_threads_v is not None:
            try:
                host_threads_v = int(host_threads_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid host_threads {host_threads_v!r}") from err
            if host_threads_v < 0:
                raise SerdeError(
                    f"host_threads must be >= 0, got {host_threads_v}")
        hedge_ms_v = obj.get("hedge_ms", None)
        if hedge_ms_v is not None:
            try:
                hedge_ms_v = float(hedge_ms_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid hedge_ms {hedge_ms_v!r}") from err
            if hedge_ms_v < 0:
                raise SerdeError(
                    f"hedge_ms must be >= 0, got {hedge_ms_v}")
        read_retries_v = obj.get("read_retries", None)
        if read_retries_v is not None:
            try:
                read_retries_v = int(read_retries_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid read_retries {read_retries_v!r}") from err
            if read_retries_v < 0:
                raise SerdeError(
                    f"read_retries must be >= 0, got {read_retries_v}")
        scrub_v = obj.get("scrub_bytes_per_sec", None)
        if scrub_v is not None:
            try:
                scrub_v = float(scrub_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid scrub_bytes_per_sec {scrub_v!r}") from err
            if scrub_v < 0:
                raise SerdeError(
                    f"scrub_bytes_per_sec must be >= 0, got {scrub_v}")
        trace_v = obj.get("trace_slow_ms", None)
        if trace_v is not None:
            try:
                trace_v = float(trace_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid trace_slow_ms {trace_v!r}") from err
            if trace_v < 0:
                raise SerdeError(
                    f"trace_slow_ms must be >= 0, got {trace_v}")
        repair_v = obj.get("repair_block_bytes", None)
        if repair_v is not None:
            try:
                repair_v = int(repair_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid repair_block_bytes {repair_v!r}") from err
            if repair_v < 0:
                raise SerdeError(
                    f"repair_block_bytes must be >= 0, got {repair_v}")
        slo_eval_v = obj.get("slo_eval_s", None)
        if slo_eval_v is not None:
            try:
                slo_eval_v = float(slo_eval_v)
            except (TypeError, ValueError) as err:
                raise SerdeError(
                    f"invalid slo_eval_s {slo_eval_v!r}") from err
            if slo_eval_v < 0:
                raise SerdeError(
                    f"slo_eval_s must be >= 0, got {slo_eval_v}")
        slo_v = obj.get("slo", None)
        if slo_v is not None:
            # validate LOUDLY at config-load time (a typo'd objective
            # must fail the cluster parse, not silently never alert);
            # obs/slo.py owns the field set
            from chunky_bits_tpu.obs.slo import SloObjectives

            try:
                SloObjectives.from_obj(slo_v)
            except ValueError as err:
                raise SerdeError(f"invalid slo mapping: {err}") from err
            slo_v = dict(slo_v)
        qos_v = obj.get("qos", None)
        if qos_v is not None:
            # same loud-at-load contract as ``slo:`` — a typo'd tenant
            # table must fail the cluster parse, not silently admit
            # everyone as ``other``; cluster/qos.py owns the key set
            from chunky_bits_tpu.cluster.qos import QosConfig

            try:
                QosConfig.from_obj(qos_v)
            except ValueError as err:
                raise SerdeError(f"invalid qos mapping: {err}") from err
            qos_v = dict(qos_v)
        return cls(
            https_only=bool(obj.get("https_only", False)),
            on_conflict=on_conflict,
            user_agent=obj.get("user_agent"),
            backend=obj.get("backend"),
            **({"cache_bytes": cache_bytes}
               if cache_bytes is not None else {}),
            **({"host_threads": host_threads_v}
               if host_threads_v is not None else {}),
            **({"hedge_ms": hedge_ms_v}
               if hedge_ms_v is not None else {}),
            **({"read_retries": read_retries_v}
               if read_retries_v is not None else {}),
            **({"scrub_bytes_per_sec": scrub_v}
               if scrub_v is not None else {}),
            **({"trace_slow_ms": trace_v}
               if trace_v is not None else {}),
            **({"repair_block_bytes": repair_v}
               if repair_v is not None else {}),
            **({"slo_eval_s": slo_eval_v}
               if slo_eval_v is not None else {}),
            **({"slo": slo_v} if slo_v is not None else {}),
            **({"qos": qos_v} if qos_v is not None else {}),
        )

    def to_obj(self) -> dict:
        obj = {
            "https_only": self.https_only,
            "on_conflict": self.on_conflict,
            "user_agent": self.user_agent,
        }
        if self.backend is not None:
            obj["backend"] = self.backend
        if self.cache_bytes > 0:
            obj["cache_bytes"] = self.cache_bytes
        if self.host_threads > 0:
            obj["host_threads"] = self.host_threads
        if self.hedge_ms > 0:
            obj["hedge_ms"] = self.hedge_ms
        if self.read_retries != 1:
            obj["read_retries"] = self.read_retries
        if self.scrub_bytes_per_sec > 0:
            obj["scrub_bytes_per_sec"] = self.scrub_bytes_per_sec
        if self.trace_slow_ms > 0:
            obj["trace_slow_ms"] = self.trace_slow_ms
        if self.repair_block_bytes > 0:
            obj["repair_block_bytes"] = self.repair_block_bytes
        if self.slo_eval_s > 0:
            obj["slo_eval_s"] = self.slo_eval_s
        if self.slo:
            obj["slo"] = dict(self.slo)
        if self.qos:
            obj["qos"] = dict(self.qos)
        return obj

    def location_context(self) -> LocationContext:
        return self._location_context
