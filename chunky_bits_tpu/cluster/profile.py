"""Encoding profiles and zone rules.

Mirrors src/cluster/profile.rs: a profile is ``{chunk_size (log2),
data_chunks, parity_chunks, zone_rules}`` (:77-90) with serde aliases
``data``/``parity`` and ``zone``/``zones``/``rules``; ``ClusterProfiles``
holds a required ``default`` plus custom profiles that **inherit from
default** field-by-field (the "hollow" merge, :133-250) — a zone rule set to
null in a custom profile removes the inherited rule.  The name "default"
is reserved case-insensitively (:65-74).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from chunky_bits_tpu.cluster import sized_int
from chunky_bits_tpu.errors import SerdeError


@dataclass
class ZoneRule:
    """i8 budgets (profile.rs:124-131): ``minimum`` writes required in the
    zone, ``maximum`` allowed (None = unlimited), ``ideal`` preferred."""

    minimum: int = 0
    maximum: Optional[int] = None
    ideal: int = 0

    @classmethod
    def from_obj(cls, obj) -> "ZoneRule":
        if obj is None:
            return cls()
        maximum = obj.get("maximum")
        return cls(
            minimum=int(obj.get("minimum", 0) or 0),
            maximum=int(maximum) if maximum is not None else None,
            ideal=int(obj.get("ideal", 0) or 0),
        )

    def to_obj(self) -> dict:
        return {
            "minimum": self.minimum,
            "maximum": self.maximum,
            "ideal": self.ideal,
        }

    def copy(self) -> "ZoneRule":
        return ZoneRule(self.minimum, self.maximum, self.ideal)


@dataclass
class ClusterProfile:
    chunk_size: int = sized_int.CHUNK_SIZE_DEFAULT  # log2
    data_chunks: int = sized_int.DATA_DEFAULT
    parity_chunks: int = sized_int.PARITY_DEFAULT
    zone_rules: dict[str, ZoneRule] = field(default_factory=dict)
    #: erasure code for parts written under this profile: "rs" /
    #: "pm-msr" when pinned in YAML (validated against the geometry at
    #: parse time), or None = unset — ``get_code`` then resolves the
    #: ``$CHUNKY_BITS_TPU_CODE`` env default, honored only when this
    #: profile's geometry supports it (an env default must tune, never
    #: break, a fleet of mixed profiles)
    code: Optional[str] = None

    def get_chunk_size(self) -> int:
        return 1 << self.chunk_size

    def get_data_chunks(self) -> int:
        return self.data_chunks

    def get_parity_chunks(self) -> int:
        return self.parity_chunks

    def get_code(self) -> str:
        if self.code is not None:
            return self.code
        from chunky_bits_tpu.cluster import tunables

        want = tunables.erasure_code(default="rs")
        if want != "rs" and _code_geometry_error(want, self) is not None:
            return "rs"
        return want

    @classmethod
    def from_obj(cls, obj: dict) -> "ClusterProfile":
        if not isinstance(obj, dict):
            raise SerdeError("profile must be a mapping")
        out = cls()
        if "chunk_size" in obj:
            out.chunk_size = sized_int.chunk_size(obj["chunk_size"])
        data = obj.get("data_chunks", obj.get("data"))
        if data is None:
            raise SerdeError("profile missing data chunk count")
        out.data_chunks = sized_int.data_chunk_count(data)
        parity = obj.get("parity_chunks", obj.get("parity"))
        if parity is None:
            raise SerdeError("profile missing parity chunk count")
        out.parity_chunks = sized_int.parity_chunk_count(parity)
        rules = _zone_rules_obj(obj)
        if rules:
            out.zone_rules = {
                zone: ZoneRule.from_obj(rule) for zone, rule in rules.items()
            }
        if "code" in obj and obj["code"] is not None:
            out.code = _validated_code(obj["code"], out)
        return out

    def to_obj(self) -> dict:
        out = {
            "chunk_size": self.chunk_size,
            "data_chunks": self.data_chunks,
            "parity_chunks": self.parity_chunks,
            "rules": {z: r.to_obj() for z, r in self.zone_rules.items()},
        }
        if self.code is not None:
            out["code"] = self.code
        return out

    def copy(self) -> "ClusterProfile":
        return ClusterProfile(
            chunk_size=self.chunk_size,
            data_chunks=self.data_chunks,
            parity_chunks=self.parity_chunks,
            zone_rules={z: r.copy() for z, r in self.zone_rules.items()},
            code=self.code,
        )


def _zone_rules_obj(obj: dict):
    for key in ("zone_rules", "rules", "zones", "zone"):
        if key in obj and obj[key] is not None:
            return obj[key]
    return None


def _code_geometry_error(code: str, profile: "ClusterProfile"):
    """Why ``profile``'s geometry cannot run ``code``, or None."""
    if code == "rs":
        return None
    from chunky_bits_tpu.ops.pm_msr import geometry_error

    return geometry_error(profile.get_data_chunks(),
                          profile.get_parity_chunks(),
                          profile.get_chunk_size())


def _validated_code(value: object, profile: "ClusterProfile") -> str:
    """An explicit YAML ``code:`` must be a shipped code AND fit the
    profile's geometry — config typos and impossible geometries fail at
    cluster load, not at the first write."""
    from chunky_bits_tpu.ops.backend import KNOWN_CODES

    if value not in KNOWN_CODES:
        raise SerdeError(
            f"profile code must be one of "
            f"{', '.join(repr(c) for c in KNOWN_CODES)}, got {value!r}")
    err = _code_geometry_error(str(value), profile)
    if err is not None:
        raise SerdeError(f"profile cannot use code {value!r}: {err}")
    return str(value)


class ClusterProfiles:
    def __init__(self, default: ClusterProfile,
                 custom: Optional[dict[str, ClusterProfile]] = None):
        self.default = default
        self.custom = dict(custom or {})

    def get_default(self) -> ClusterProfile:
        return self.default

    def get(self, name: Optional[str]) -> Optional[ClusterProfile]:
        if name is None or name.lower() == "default":
            return self.default
        return self.custom.get(name)

    def insert(self, name: Optional[str], profile: ClusterProfile
               ) -> Optional[ClusterProfile]:
        if name is None or name.lower() == "default":
            old, self.default = self.default, profile
            return old
        old = self.custom.get(name)
        self.custom[name] = profile
        return old

    @classmethod
    def from_obj(cls, obj: dict) -> "ClusterProfiles":
        if not isinstance(obj, dict):
            raise SerdeError("profiles must be a mapping")
        default_obj = None
        customs: dict[str, dict] = {}
        for key, value in obj.items():
            if key.lower() == "default":
                if default_obj is not None:
                    raise SerdeError("duplicate field `default`")
                default_obj = value
            else:
                customs[key] = value
        if default_obj is None:
            raise SerdeError("profiles missing field `default`")
        default = ClusterProfile.from_obj(default_obj)
        custom = {}
        for name, hollow in customs.items():
            custom[name] = _merge_with_default(hollow, default)
        return cls(default, custom)

    def to_obj(self) -> dict:
        out = {"default": self.default.to_obj()}
        for name, profile in self.custom.items():
            out[name] = profile.to_obj()
        return out


def _merge_with_default(hollow: dict, default: ClusterProfile
                        ) -> ClusterProfile:
    """Partial custom profile over the default (profile.rs:220-248)."""
    if not isinstance(hollow, dict):
        raise SerdeError("profile must be a mapping")
    out = default.copy()
    if "chunk_size" in hollow and hollow["chunk_size"] is not None:
        out.chunk_size = sized_int.chunk_size(hollow["chunk_size"])
    data = hollow.get("data_chunks", hollow.get("data"))
    if data is not None:
        out.data_chunks = sized_int.data_chunk_count(data)
    parity = hollow.get("parity_chunks", hollow.get("parity"))
    if parity is not None:
        out.parity_chunks = sized_int.parity_chunk_count(parity)
    rules = _zone_rules_obj(hollow)
    if rules:
        for zone, rule in rules.items():
            if rule is None:
                out.zone_rules.pop(zone, None)
            else:
                out.zone_rules[zone] = ZoneRule.from_obj(rule)
    if "code" in hollow:
        # null removes the inherited pin (back to the env default),
        # mirroring the zone-rule null semantics
        out.code = (None if hollow["code"] is None
                    else _validated_code(hollow["code"], out))
    elif out.code is not None:
        # an inherited explicit code must still fit the merged
        # geometry — a custom profile that widens data past the
        # default's pm-msr parity budget is a config error, not a
        # silent fallback (explicit pins are guarantees)
        err = _code_geometry_error(out.code, out)
        if err is not None:
            raise SerdeError(
                f"profile inherits code {out.code!r} but its geometry "
                f"cannot run it: {err}")
    return out
