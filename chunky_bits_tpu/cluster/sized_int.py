"""Range-validated config numerics.

Mirrors the reference's sized_int newtypes (src/cluster/sized_int.rs:139-162):
``chunk_size`` is a log2 exponent in 10..=32 (default 20 => 1 MiB),
``data_chunks`` 1..=256 (default 3), ``parity_chunks`` 0..=256 (default 2).
"""

from __future__ import annotations

from chunky_bits_tpu.errors import SerdeError

CHUNK_SIZE_MIN, CHUNK_SIZE_MAX, CHUNK_SIZE_DEFAULT = 10, 32, 20
DATA_MIN, DATA_MAX, DATA_DEFAULT = 1, 256, 3
PARITY_MIN, PARITY_MAX, PARITY_DEFAULT = 0, 256, 2


def _validate(name: str, value, lo: int, hi: int) -> int:
    try:
        i = int(value)
    except (TypeError, ValueError) as err:
        raise SerdeError(f"{name} must be an integer, got {value!r}") from err
    if not (lo <= i <= hi):
        raise SerdeError(
            f"{name} must be greater than {lo} and less than {hi}"
        )
    return i


def chunk_size(value) -> int:
    """Validated log2 chunk size."""
    return _validate("ChunkSize", value, CHUNK_SIZE_MIN, CHUNK_SIZE_MAX)


def data_chunk_count(value) -> int:
    return _validate("DataChunkCount", value, DATA_MIN, DATA_MAX)


def parity_chunk_count(value) -> int:
    return _validate("ParityChunkCount", value, PARITY_MIN, PARITY_MAX)
