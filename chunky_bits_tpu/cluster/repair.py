"""Targeted repair planner: block-localized, health-scheduled,
byte-metered resilver.

A TPU-repo extension past the reference's part-granular repair
(``FilePart::resilver``, src/file/file_part.rs:253-389, re-reads every
replica of every chunk of a damaged part): at production scale repair
traffic dwarfs client traffic, and PAPERS.md's "Fast Product-Matrix
Regenerating Codes" (1412.3022) frames the goal — rebuild lost data
from *sub-chunk* reads at a fraction of the network cost.  RS here is
applied stripe-wise (byte ``s`` of every shard forms an independent
GF(2^8) stripe), so a damaged byte range of one chunk can be rebuilt by
reading *the same range* of ``d`` helpers — no code change, no new
wire format for the shards themselves, the byte-identity invariant
untouched.  The planner captures most of the regenerating-code win by
localizing damage first (the optional per-chunk block-digest tree,
file/chunk.py ``BlockDigests``, written on the normal encode path when
``tunables.repair_block_bytes`` is set) and repairing only the stripes
that need it.

Four plan kinds, cheapest first:

* **copy** — the damaged chunk still has a healthy replica: read the
  damaged ranges (or, without a digest tree, the whole chunk) from that
  ONE replica and rewrite the victims in place.  1x bytes per rebuilt
  byte instead of the d x a decode would cost.
* **msr** — a ``pm-msr`` part (ops/pm_msr.py) lost exactly one chunk:
  regenerate it from β-sized GF projections off the healthiest
  ``d' = 2(d-1)`` helper chunks instead of ``d`` full reads.  Each
  local/slab helper replica is hash-verified and projected on the
  shared HostPipeline (the node-side compute of a real deployment —
  only the β-sized projection enters the repair plane), the combine is
  one ``[α, d']`` matmul through the part's backend, and the result
  passes the same end-to-end hash gate.  ``d'·β = 2·chunksize`` repair
  bytes instead of Reed-Solomon's information-theoretic ``d·chunksize``
  floor.  Multi-loss, non-local helpers, or any projection shortfall
  fall through to the decode plan exactly as today.
* **decode** — no replica of the chunk verifies anywhere: read the same
  damaged ranges from the healthiest ``d`` of the part's other chunks
  (``HealthScoreboard.order`` picks them — never metadata order), feed
  the rebuild matmuls through the shared ``ReconstructBatcher`` (many
  concurrent ranges coalesce into one ``[B, d, S]`` dispatch), splice,
  and rewrite in place.  ``d x damage`` bytes instead of
  ``d x chunksize``.  For ``pm-msr`` parts the ranges are whole chunks
  (byte position t of a stripe belongs to a different codeword than
  byte t of the chunk, so sub-chunk splicing is rs-only).
* **fallback** — the planner cannot finish in place (fewer than ``d``
  healthy helpers, an end-to-end hash failure after rebuild, a chunk
  that needs *new* placement, or a part declaring a code this build
  does not implement): the part is handed back to the caller for the
  classic full ``resilver`` (which can allocate new locations and
  republish metadata).

Every counter carries the part's ``code`` (closed set ``{rs, pm-msr}``
— CB107), so ``cb_repair_*``, ``/scrub/status`` and the bench config-13
A/B read per-code repair traffic from the same numbers.

**Byte metering.**  Every byte the planner touches — victim re-reads
for localization, helper range reads, repair writes — is charged to the
caller's token bucket (``cluster/scrub.py``'s
``tunables.scrub_bytes_per_sec`` bound) BEFORE the I/O, with exact
per-plan counts replacing scrub's old part-granular estimate.  The same
numbers feed the ``cb_repair_*`` metric families (closed label sets per
CB107) through the process registry: the planner self-registers as a
polled source, so ``/metrics``, ``/stats``, ``/scrub/status`` and the
profiler stanza all report the one set of counters.

**End-to-end safety.**  A spliced chunk is only written back after its
FULL content hash verifies — a lying helper or a stale digest tree can
waste a plan, never publish wrong bytes.  Helper range reads are
additionally pre-checked against the helper's own block digests when
the range aligns to its grid.  Repair writes are in-place overwrites of
content-addressed chunks (the same rationale as resilver's overwrite
deviation), so the planner never has to touch metadata at all — the
single-chunk-damage case stops republishing the whole part.

**Concurrency shape** (the CB204 audience): ``repair_part`` runs on its
caller's loop; hash/digest compute hops to the shared ``HostPipeline``;
the scoreboard and the stats counters are thread-safe (a ``/metrics``
scrape reads them from the gateway thread).  The per-call
``ReconstructBatcher`` is drained before ``repair_part`` returns, so no
dispatch task outlives a pass (the no-leaked-tasks contract,
``CHUNKY_BITS_TPU_SANITIZE=1``).
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

import numpy as np

from chunky_bits_tpu.cluster import clock as _clock
from chunky_bits_tpu.cluster.health import location_key
from chunky_bits_tpu.errors import ErasureError, LocationError
from chunky_bits_tpu.ops.backend import KNOWN_CODES
from chunky_bits_tpu.file.location import (
    OVERWRITE,
    Location,
    LocationContext,
    Range,
)
from chunky_bits_tpu.utils import aio

if TYPE_CHECKING:  # typing-only: avoid import cycles at runtime
    from chunky_bits_tpu.file.chunk import Chunk
    from chunky_bits_tpu.file.file_part import FilePart
    from chunky_bits_tpu.parallel.host_pipeline import HostPipeline

#: a chunk verdict list as collected by the scrub verify phase: one
#: ``(location, verdict)`` per replica — True verified, False corrupt,
#: None unreadable
Verdicts = list[list[tuple[Location, Optional[bool]]]]


def merge_ranges(ranges: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Union of ``(start, length)`` ranges, merged where they overlap or
    touch — the per-part read schedule when several chunks localized
    different damage."""
    if not ranges:
        return []
    ordered = sorted(ranges)
    out = [ordered[0]]
    for start, length in ordered[1:]:
        last_start, last_len = out[-1]
        if start <= last_start + last_len:
            out[-1] = (last_start,
                       max(last_len, start + length - last_start))
        else:
            out.append((start, length))
    return out


#: the per-code counter keys (one dict per code in ``RepairStats.
#: by_code`` and the planner's internals); ``plans_msr`` /
#: ``helper_bytes_msr`` stay zero for rs parts
COUNTER_KEYS = ("plans_copy", "plans_decode", "plans_msr",
                "plans_fallback", "helper_bytes_replica",
                "helper_bytes_decode", "helper_bytes_msr",
                "bytes_localized", "bytes_rebuilt", "bytes_written",
                "ranges_rebuilt", "verify_failures")

#: the closed ``code`` label set (CB107) — the shipped codes, ONE
#: definition (ops/backend.py): every part the planner touches is
#: counted under one of these; a foreign/unknown code is clamped to
#: "rs" on its (only possible) fallback bump
CODES = KNOWN_CODES


@dataclass
class RepairStats:
    """Counter snapshot: the ``cb_repair_*`` families, the
    ``/scrub/status`` ``repair`` stanza, and the bench --config 11/13
    reports are all this one shape.  Top-level fields are cross-code
    totals; ``by_code`` carries the same keys per erasure code."""

    plans_copy: int
    plans_decode: int
    plans_msr: int
    plans_fallback: int
    helper_bytes_replica: int
    helper_bytes_decode: int
    helper_bytes_msr: int
    bytes_localized: int
    bytes_rebuilt: int
    bytes_written: int
    ranges_rebuilt: int
    verify_failures: int
    by_code: dict = None  # type: ignore[assignment]

    def helper_bytes(self) -> int:
        return (self.helper_bytes_replica + self.helper_bytes_decode
                + self.helper_bytes_msr)

    def savings_ratio(self) -> Optional[float]:
        """Helper bytes read per rebuilt byte — the headline number the
        planner exists to shrink (d for classic decode of whole chunks,
        approaching 1x for copy plans / d x damage for localized
        decode / 2x for msr regeneration).  None before any rebuild."""
        if self.bytes_rebuilt <= 0:
            return None
        return self.helper_bytes() / self.bytes_rebuilt

    def to_obj(self) -> dict:
        ratio = self.savings_ratio()
        return {
            "plans_copy": self.plans_copy,
            "plans_decode": self.plans_decode,
            "plans_msr": self.plans_msr,
            "plans_fallback": self.plans_fallback,
            "helper_bytes_replica": self.helper_bytes_replica,
            "helper_bytes_decode": self.helper_bytes_decode,
            "helper_bytes_msr": self.helper_bytes_msr,
            "bytes_localized": self.bytes_localized,
            "bytes_rebuilt": self.bytes_rebuilt,
            "bytes_written": self.bytes_written,
            "ranges_rebuilt": self.ranges_rebuilt,
            "verify_failures": self.verify_failures,
            "by_code": {code: dict(counters)
                        for code, counters in (self.by_code or {}).items()},
            **({"helper_bytes_per_rebuilt_byte": round(ratio, 4)}
               if ratio is not None else {}),
        }


@dataclass
class PartRepairOutcome:
    """What ``repair_part`` accomplished for one part."""

    repaired: int  # replicas rewritten with verified bytes
    failures: int  # victims that could not be rewritten this pass
    fallback: bool  # the part still needs the classic full resilver


class RepairPlanner:
    """One cluster's repair scheduler; see the module docstring.

    ``health`` is the cluster's ``HealthScoreboard`` (or None — helper
    choice falls back to metadata order, the reference's walk);
    ``bucket`` is the byte-rate ``TokenBucket`` repair I/O charges
    (or None — unmetered, e.g. ``--once`` CLI runs at rate 0);
    ``backend`` names the erasure backend for decode dispatches.

    ``replace_after_s`` is the **re-placement escalation threshold**: a
    victim replica whose in-place repair writes have been failing
    continuously for this long is treated as permanently gone, and its
    part is handed to the classic resilver to allocate a NEW location.
    Below the threshold the planner just retries next pass — a
    transient partition must be *waited out*, not answered with a
    republish storm that moves every partitioned chunk somewhere else
    (the distinction the simulator's az-outage vs correlated-failures
    scenarios pin: partitioned nodes come back with their bytes,
    dead disks never do).  Times run on the cluster clock seam
    (``cluster/clock.py``), so the simulator compresses the wait.
    """

    def __init__(self, health=None, bucket=None,
                 backend: Optional[str] = None,
                 replace_after_s: float = 900.0,
                 stale_after_s: Optional[float] = None) -> None:
        from chunky_bits_tpu.cluster.scrub import TokenBucket

        self.health = health
        # rate 0 = take() returns immediately (scrub's documented
        # no-op), so direct planner use outside a daemon stays unmetered
        self.bucket = bucket if bucket is not None else TokenBucket(0.0)
        self.backend = backend
        self.replace_after_s = max(float(replace_after_s), 0.0)
        #: the continuity bound: a gap between failures longer than
        #: this RESETS the window below.  Defaults to replace_after_s;
        #: callers whose retry cadence is slower than the threshold
        #: (ScrubDaemon passes max(replace_after_s, 2 x pass interval))
        #: must widen it, or consecutive-pass failures would always
        #: look stale and escalation could never fire.
        self.stale_after_s = max(
            float(stale_after_s) if stale_after_s is not None else 0.0,
            self.replace_after_s)
        #: node key -> (first, last) failure times of in-place repair
        #: writes — the persistence memory the re-placement escalation
        #: reads.  Cleared by any later success; a gap between
        #: failures longer than ``stale_after_s`` RESETS the window,
        #: so a recovered node's ancient stamp can never make a future
        #: one-pass blip escalate instantly.  Bounded by node count.
        self._unwritable_since: dict[tuple[str, str],
                                     tuple[float, float]] = {}
        # counters are read by /metrics scrapes and /scrub/status
        # handlers, possibly from other threads than the repair loop's;
        # one dict per code so every family carries the code label
        self._lock = threading.Lock()
        self._counters = {code: dict.fromkeys(COUNTER_KEYS, 0)
                          for code in CODES}
        # weakly self-register with the process metrics registry so a
        # /metrics scrape reports repair progress (same pattern as the
        # scrub daemon and the health scoreboard)
        from chunky_bits_tpu.obs.metrics import get_registry

        get_registry().register_source("repair", self)

    # ---- reporting ----

    def _bump(self, code: str, **deltas: int) -> None:
        counters = self._counters[code if code in self._counters
                                  else "rs"]
        with self._lock:
            for key, delta in deltas.items():
                counters[key] += delta

    def stats(self) -> RepairStats:
        with self._lock:
            by_code = {code: dict(counters)
                       for code, counters in self._counters.items()}
        totals = {key: sum(c[key] for c in by_code.values())
                  for key in COUNTER_KEYS}
        return RepairStats(by_code=by_code, **totals)

    # ---- shared plumbing ----

    def _order(self, locations: list[Location]) -> list[Location]:
        """Best-health-first (stable: a fresh scoreboard — or none —
        reproduces metadata order)."""
        if self.health is None or len(locations) < 2:
            return list(locations)
        return self.health.order(locations)

    async def _read_range(self, location: Location, start: int,
                          length: int, cx: LocationContext) -> bytes:
        """Exactly ``length`` bytes at chunk offset ``start`` from one
        replica, charged to the byte bucket BEFORE the I/O.  Short reads
        are failures — a truncated replica must not masquerade as
        content.  Replicas carrying their own range view (never the
        case for destination-written chunks) are refused so offsets
        cannot silently compose wrong."""
        if location.range.is_specified():
            raise LocationError(
                f"cannot range-read ranged replica {location}")
        await self.bucket.take(length)
        data = await location.with_range(Range(start, length)).read(cx)
        if len(data) != length:
            raise LocationError(
                f"short range read from {location}: "
                f"{len(data)} != {length}")
        return data

    async def _read_full(self, location: Location, cx: LocationContext
                         ) -> bytes:
        """A whole replica, metered (length probed first so the budget
        is charged before the transfer, like scrub verification)."""
        nbytes = await location.file_len(cx)
        await self.bucket.take(nbytes)
        return await location.read(cx)

    async def _localize(self, ci: int, chunk: "Chunk", chunksize: int,
                        corrupt: list[Location], cx: LocationContext,
                        pipe: "HostPipeline",
                        payloads: Optional[dict] = None,
                        code: str = "rs"
                        ) -> tuple[Optional[bytearray],
                                   list[tuple[int, int]]]:
        """(base bytes to splice into, damaged ranges) for one damaged
        chunk.  With a digest tree and a *readable* corrupt replica the
        damage localizes to block ranges; otherwise the whole chunk is
        the range and the base starts as zeros (every byte will be
        rewritten).  ``payloads`` maps ``(chunk index, location)`` to
        corrupt-replica bytes the caller's verify phase already read
        (the generic read path surfaces them; the fused hash path does
        not) — when present, localization costs no I/O at all.  A
        victim re-read, when needed, is metered like any repair I/O."""
        whole = [(0, chunksize)]
        if chunk.blocks is None:
            return None, whole
        for location in corrupt:
            base = (payloads or {}).get((ci, location))
            if base is None:
                try:
                    base = await self._read_full(location, cx)
                except LocationError:
                    continue
                self._bump(code, bytes_localized=len(base))
            blocks = chunk.blocks
            ranges = await pipe.run(
                "verify",
                lambda base=base: blocks.damaged_ranges(base),
                nbytes=len(base))
            if ranges:  # localized: splice into this replica's bytes
                return bytearray(base), ranges
            # None (length mismatch) or [] (raced a writer/repair —
            # the full-hash gate downstream decides): whole-chunk
            return None, whole
        return None, whole

    async def _verify_full(self, chunk: "Chunk", buf, pipe: "HostPipeline",
                           code: str = "rs") -> bool:
        """The end-to-end gate: the spliced chunk must match its
        content hash before any write."""
        ok = await pipe.run(
            "verify", lambda: chunk.hash.verify(bytes(buf)),
            nbytes=len(buf))
        if not ok:
            self._bump(code, verify_failures=1)
        return bool(ok)

    async def _write_victims(self, chunk: "Chunk", payload: bytes,
                             victims: list[Location],
                             cx: LocationContext,
                             code: str = "rs") -> tuple[int, int]:
        """Rewrite ``victims`` in place with verified bytes (metered);
        returns (repaired, failures).  Content-addressed overwrite is
        always safe — the same rationale as resilver's overwrite
        deviation."""
        overwrite_cx = cx.but_with(on_conflict=OVERWRITE)
        repaired = failures = 0
        for victim in victims:
            await self.bucket.take(len(payload))
            key = location_key(victim)
            try:
                await victim.write(payload, overwrite_cx)
            except LocationError:
                # node still down/full: counted, retried next pass —
                # and remembered, so a node that STAYS unwritable past
                # replace_after_s escalates to re-placement.  A stale
                # window (no failure observed for replace_after_s)
                # restarts at now: "continuously" means failures keep
                # recurring, not "failed once, ever"
                failures += 1
                now = _clock.monotonic()
                prev = self._unwritable_since.get(key)
                if prev is None or now - prev[1] > self.stale_after_s:
                    self._unwritable_since[key] = (now, now)
                else:
                    self._unwritable_since[key] = (prev[0], now)
                continue
            self._unwritable_since.pop(key, None)
            self._bump(code, bytes_written=len(payload))
            repaired += 1
        return repaired, failures

    # ---- the plans ----

    async def _copy_plan(self, ci: int, chunk: "Chunk", chunksize: int,
                         good: list[Location], corrupt: list[Location],
                         missing: list[Location], cx: LocationContext,
                         pipe: "HostPipeline",
                         payloads: Optional[dict] = None,
                         code: str = "rs"
                         ) -> tuple[int, int]:
        """1x repair from a healthy replica: ranged reads for localized
        corrupt victims, one whole-chunk read (cached across victims)
        for the rest.  Sources fail over best-health-first — a replica
        that verified a moment ago may be gone by repair time, and the
        next one serves the same bytes.  Returns (repaired, failures)."""
        self._bump(code, plans_copy=1)
        sources = self._order(good)
        repaired = failures = 0
        full: Optional[bytes] = None  # whole-source cache

        async def full_payload() -> Optional[bytes]:
            nonlocal full
            if full is None:
                for source in sources:
                    try:
                        data = await self._read_full(source, cx)
                    except LocationError:
                        continue  # replica vanished: next-best source
                    self._bump(code, helper_bytes_replica=len(data))
                    if not await self._verify_full(chunk, data, pipe,
                                                   code):
                        continue  # raced a writer; try another replica
                    full = data
                    break
            return full

        async def read_range_failover(start: int, length: int
                                      ) -> Optional[bytes]:
            for source in sources:
                try:
                    seg = await self._read_range(source, start, length,
                                                 cx)
                except LocationError:
                    continue
                self._bump(code, helper_bytes_replica=length)
                return seg
            return None

        for victim in corrupt:
            spliced = False
            if chunk.blocks is not None and full is None:
                base, ranges = await self._localize(
                    ci, chunk, chunksize, [victim], cx, pipe, payloads,
                    code)
                if base is not None:
                    buf, ok = bytearray(base), True
                    for start, length in ranges:
                        seg = await read_range_failover(start, length)
                        if seg is None:
                            ok = False
                            break
                        buf[start: start + length] = seg
                    if ok and await self._verify_full(chunk, buf, pipe,
                                                      code):
                        r, f = await self._write_victims(
                            chunk, bytes(buf), [victim], cx, code)
                        if r:
                            self._bump(code, bytes_rebuilt=sum(
                                ln for _s, ln in ranges),
                                ranges_rebuilt=len(ranges))
                        repaired += r
                        failures += f
                        spliced = True
            if spliced:
                continue
            payload = await full_payload()
            if payload is None:
                failures += 1
                continue
            r, f = await self._write_victims(chunk, payload, [victim],
                                             cx, code)
            if r:
                self._bump(code, bytes_rebuilt=len(payload),
                           ranges_rebuilt=1)
            repaired += r
            failures += f
        for victim in missing:
            payload = await full_payload()
            if payload is None:
                failures += 1
                continue
            r, f = await self._write_victims(chunk, payload, [victim],
                                             cx, code)
            if r:
                self._bump(code, bytes_rebuilt=len(payload),
                           ranges_rebuilt=1)
            repaired += r
            failures += f
        return repaired, failures

    async def _read_helper_range(self, ci: int, chunk: "Chunk",
                                 location: Location, start: int,
                                 length: int, cx: LocationContext,
                                 pipe: "HostPipeline",
                                 code: str = "rs") -> bytes:
        """One helper's contribution to a decode range: metered, and
        pre-checked against the helper's own block digests when the
        range aligns to its grid (a corrupt helper fails here instead
        of poisoning the decode and costing a verify_failure)."""
        data = await self._read_range(location, start, length, cx)
        if chunk.blocks is not None:
            blocks = chunk.blocks
            verdict = await pipe.run(
                "verify",
                lambda data=data: blocks.verify_range(data, start),
                nbytes=length)
            if verdict is False:
                if self.health is not None:
                    self.health.record(location, False)
                raise LocationError(
                    f"helper block digest mismatch at {location}")
        self._bump(code, helper_bytes_decode=length)
        return data

    async def _decode_ranges(self, part: "FilePart",
                             helpers: list[tuple[int, Location]],
                             ranges: list[tuple[int, int]],
                             cx: LocationContext, pipe: "HostPipeline",
                             batcher) -> Optional[dict[int, dict]]:
        """Read each range from ``d`` healthy helpers and rebuild every
        absent chunk's bytes for it through the reconstruct batcher
        (ranges run concurrently, so same-shape rebuilds coalesce into
        one [B, d, S] dispatch).  Returns {range_start: {ci: bytes}}
        for the rebuilt (non-helper) chunk indices, or None when any
        range cannot gather ``d`` helpers."""
        chunks = part.all_chunks()
        d, p = len(part.data), len(part.parity)
        code = part.code

        async def one(start: int, length: int) -> Optional[tuple]:
            slots: list = [None] * (d + p)
            got = 0
            for ci, location in helpers:
                if got >= d:
                    break
                try:
                    data = await self._read_helper_range(
                        ci, chunks[ci], location, start, length, cx,
                        pipe, code)
                except LocationError:
                    continue
                slots[ci] = np.frombuffer(data, dtype=np.uint8)
                got += 1
            if got < d:
                return None  # not enough live helpers for this range
            arrays = await batcher.reconstruct(d, p, slots,
                                               data_only=False,
                                               code=code)
            rebuilt = {
                ci: np.ascontiguousarray(arr).tobytes()
                for ci, arr in enumerate(arrays)
                if slots[ci] is None and arr is not None
            }
            return (start, rebuilt)

        results = await aio.gather_or_cancel(
            [one(start, length) for start, length in ranges])
        if any(res is None for res in results):
            return None
        return {start: rebuilt for start, rebuilt in results}

    async def _helper_projection(self, ci: int, chunk: "Chunk",
                                 locations: list[Location], coder,
                                 chunksize: int, cx: LocationContext,
                                 pipe: "HostPipeline"
                                 ) -> Optional[np.ndarray]:
        """One helper's β-sized contribution to regenerating chunk
        ``ci``: read a verified replica and project its α stripes
        through ``φ_ci`` on the shared HostPipeline — the node-side
        compute of a real MSR deployment, where only the projection
        crosses the network.  The scrub bucket is charged the FULL
        replica read BEFORE the I/O: the byte-rate bound exists to
        protect foreground traffic on the disks this process actually
        touches, and computing a local projection reads chunksize even
        though only β enters the repair plane (``helper_bytes_msr``
        records β — the network bytes a distributed deployment would
        move — while the bucket meters the disk).  Failing/corrupt
        replicas fail over best-health-first; corrupt content demerits
        the serving node.  Returns the ``[β]`` projection, or None when
        no replica verifies (the caller drops this helper)."""
        for location in locations:
            await self.bucket.take(chunksize)
            try:
                data = await location.read(cx)
            except LocationError:
                continue
            if len(data) != chunksize:
                continue  # truncated replica cannot project soundly
            ok = await pipe.run(
                "verify", lambda data=data: chunk.hash.verify(data),
                nbytes=len(data))
            if not ok:
                # a lying helper would survive to the end-to-end gate
                # anyway, but catching it here costs one hash and saves
                # the whole plan
                if self.health is not None:
                    self.health.record(location, False)
                continue
            arr = np.frombuffer(data, dtype=np.uint8)[None, :]
            return await pipe.run(
                "encode",
                lambda arr=arr: coder.project_batch(ci, arr)[0],
                nbytes=chunksize)
        return None

    async def _msr_plan(self, part: "FilePart", ci: int,
                        chunks: list["Chunk"], good: list[list[Location]],
                        victims: list[Location], cx: LocationContext,
                        pipe: "HostPipeline"
                        ) -> Optional[tuple[int, int]]:
        """Regenerate the single lost chunk ``ci`` of a ``pm-msr`` part
        from ``d' = 2(d-1)`` helper projections (module docstring, plan
        kind **msr**): ``d'·β = 2·chunksize`` repair-plane bytes
        instead of the decode plan's ``d·chunksize``.  Helpers are the
        healthiest chunks with verified local/slab replicas; the
        rebuilt chunk passes the full content-hash gate before any
        write.  Returns (repaired, failures), or None when the plan
        cannot run/finish — the caller falls through to the classic
        decode plan, so an aborted msr attempt costs at most a few β
        reads, never correctness."""
        from chunky_bits_tpu.ops.backend import get_coder

        try:
            coder = await asyncio.to_thread(
                get_coder, len(part.data), len(part.parity),
                self.backend, "pm-msr")
        except ErasureError:
            return None  # geometry this code cannot run (foreign ref)
        if part.chunksize <= 0 or part.chunksize % coder.alpha:
            return None
        beta = part.chunksize // coder.alpha
        candidates: list[tuple[int, list[Location]]] = []
        for hi in range(len(chunks)):
            if hi == ci or not good[hi]:
                continue
            locs = [loc for loc in self._order(good[hi])
                    if loc.is_local() or loc.is_slab() or loc.is_sim()]
            if locs:
                candidates.append((hi, locs))
        if len(candidates) < coder.helpers:
            return None
        # healthiest-first helper order: rank each candidate chunk by
        # its best replica through the scoreboard (same shape as the
        # decode plan's helper ordering)
        by_loc = {id(locs[0]): (hi, locs) for hi, locs in candidates}
        ordered = [by_loc[id(loc)] for loc in
                   self._order([locs[0] for _hi, locs in candidates])]
        used: list[int] = []
        projections: list[np.ndarray] = []
        for hi, locs in ordered:
            if len(used) >= coder.helpers:
                break
            proj = await self._helper_projection(
                ci, chunks[hi], locs, coder, part.chunksize, cx, pipe)
            if proj is None:
                continue
            used.append(hi)
            projections.append(proj)
            self._bump("pm-msr", helper_bytes_msr=beta)
        if len(used) < coder.helpers:
            return None  # helpers vanished since verify: decode decides
        stacked = np.ascontiguousarray(np.stack(projections))[None, ...]
        try:
            rebuilt = await pipe.run(
                "encode",
                lambda: coder.repair_batch(ci, used, stacked)[0],
                nbytes=part.chunksize)
        except ErasureError:
            return None
        payload = np.ascontiguousarray(rebuilt).tobytes()
        if not await self._verify_full(chunks[ci], payload, pipe,
                                       "pm-msr"):
            # helpers inconsistent with this chunk's hash (stale ref,
            # raced writer): the decode plan re-reads and decides
            return None
        self._bump("pm-msr", plans_msr=1)
        r, f = await self._write_victims(chunks[ci], payload, victims,
                                         cx, "pm-msr")
        if r:
            self._bump("pm-msr", bytes_rebuilt=part.chunksize,
                       ranges_rebuilt=1)
        return (r, f)

    def _maybe_replace(self, code: str, chunks: list,
                       corrupt: list, missing: list,
                       fallback: bool) -> bool:
        """The re-placement escalation (see the class docstring): when
        any victim of this part has been unwritable continuously for
        ``replace_after_s``, hand the part to the classic resilver so
        the replica gets a NEW home.  Never fires for nodes that came
        back (success pops the memory) and never below the threshold —
        a transient partition is waited out in place.  The key stays
        after firing (every part with a replica on the dead node must
        escalate, and they arrive one repair_part call at a time);
        staleness is handled on the RECORDING side: a gap between
        failures longer than the threshold resets the window, so the
        entry can never act as a "failed once, ever" stamp."""
        if fallback or self.replace_after_s <= 0 \
                or not self._unwritable_since:
            return fallback
        now = _clock.monotonic()
        for ci in range(len(chunks)):
            for loc in corrupt[ci] + missing[ci]:
                window = self._unwritable_since.get(location_key(loc))
                if (window is not None
                        and now - window[0] >= self.replace_after_s
                        # the streak must still be live: a window whose
                        # last failure is older than the continuity
                        # bound is stale evidence, not a
                        # continuously-dead node
                        and now - window[1] <= self.stale_after_s):
                    self._bump(code, plans_fallback=1)
                    return True
        return fallback

    # ---- the entry point ----

    async def repair_part(self, part: "FilePart", verdicts: Verdicts,
                          cx: LocationContext, pipe: "HostPipeline",
                          payloads: Optional[dict] = None
                          ) -> PartRepairOutcome:
        """Repair one part in place from the scrub verify phase's
        replica verdicts.  Copy plans run first (they may restore a
        replica a decode plan would otherwise have to route around);
        then every chunk with NO verified replica is rebuilt from
        ranged reads off the healthiest ``d`` helpers.  Anything the
        planner cannot finish in place is reported as ``fallback`` for
        the classic full resilver.  ``payloads`` optionally carries
        corrupt-replica bytes the verify phase already surfaced, keyed
        ``(chunk index, location)`` — localization then re-reads
        nothing (see :meth:`_localize`)."""
        chunks = part.all_chunks()
        d = len(part.data)
        code = part.code
        repaired = failures = 0
        fallback = False

        if code not in KNOWN_CODES:
            # a part declaring a code this build does not implement:
            # even copy plans stay hands-off (the bytes' semantics are
            # a newer writer's) — hand it straight to resilver, whose
            # own require_known_code reports it cleanly.  Counted under
            # the clamped "rs" label (the closed-set rule).
            self._bump("rs", plans_fallback=1)
            return PartRepairOutcome(repaired, failures, True)

        good: list[list[Location]] = []
        corrupt: list[list[Location]] = []
        missing: list[list[Location]] = []
        for per_loc in verdicts:
            good.append([loc for loc, v in per_loc if v is True])
            corrupt.append([loc for loc, v in per_loc if v is False])
            missing.append([loc for loc, v in per_loc if v is None])

        if any(not chunk.locations for chunk in chunks):
            # a chunk with no replicas at all needs NEW placement —
            # resilver's job (get_used_writers), not an in-place plan
            fallback = True
            self._bump(code, plans_fallback=1)

        # 1. copy plans: damaged replicas beside a healthy one
        for ci, chunk in enumerate(chunks):
            if good[ci] and (corrupt[ci] or missing[ci]):
                r, f = await self._copy_plan(
                    ci, chunk, part.chunksize, good[ci], corrupt[ci],
                    missing[ci], cx, pipe, payloads, code)
                repaired += r
                failures += f

        # 2. chunks with no verified replica anywhere
        lost = [ci for ci in range(len(chunks))
                if not good[ci] and (corrupt[ci] or missing[ci])]
        if not lost:
            fallback = self._maybe_replace(code, chunks, corrupt,
                                           missing, fallback)
            return PartRepairOutcome(repaired, failures, fallback)

        # 2a. msr regeneration: a pm-msr part that lost exactly ONE
        # chunk rebuilds from d' β-sized helper projections (2x
        # chunksize of repair-plane bytes instead of decode's d x);
        # any shortfall falls through to the decode plan below
        if code == "pm-msr" and len(lost) == 1:
            res = await self._msr_plan(
                part, lost[0], chunks, good,
                corrupt[lost[0]] + missing[lost[0]], cx, pipe)
            if res is not None:
                repaired += res[0]
                failures += res[1]
                fallback = self._maybe_replace(code, chunks, corrupt,
                                               missing, fallback)
                return PartRepairOutcome(repaired, failures, fallback)

        # 2b. decode plans
        helper_pool = [(ci, self._order(good[ci])[0])
                       for ci in range(len(chunks)) if good[ci]]
        if len(helper_pool) < d:
            # unrecoverable in place AND by resilver; hand it back so
            # the classic path reports it (legacy failure accounting)
            self._bump(code, plans_fallback=1)
            return PartRepairOutcome(repaired, failures, True)
        # healthiest-first helper order: sort the candidate locations
        # through the scoreboard, then map back to (chunk, location)
        by_loc = {id(loc): (ci, loc) for ci, loc in helper_pool}
        helpers = [by_loc[id(loc)] for loc in
                   self._order([loc for _ci, loc in helper_pool])]

        self._bump(code, plans_decode=1)
        bases: dict[int, Optional[bytearray]] = {}
        ranges_by_ci: dict[int, list[tuple[int, int]]] = {}
        for ci in lost:
            if code == "pm-msr":
                # stripe-structured code: byte t of the chunk is not
                # byte t of one codeword, so decode works at whole-chunk
                # granularity (block trees still localize COPY plans)
                bases[ci] = None
                ranges_by_ci[ci] = [(0, part.chunksize)]
                continue
            base, ranges = await self._localize(
                ci, chunks[ci], part.chunksize, corrupt[ci], cx, pipe,
                payloads, code)
            bases[ci] = base
            ranges_by_ci[ci] = ranges
        union = merge_ranges(
            [r for ranges in ranges_by_ci.values() for r in ranges])

        from chunky_bits_tpu.ops.batching import ReconstructBatcher

        batcher = ReconstructBatcher(backend=self.backend)
        try:
            rebuilt = await self._decode_ranges(
                part, helpers, union, cx, pipe, batcher)
        except ErasureError:
            # a geometry/shape the codec refuses (e.g. a handcrafted
            # pm-msr ref whose geometry the code cannot run): the
            # classic resilver reports it in its own words
            rebuilt = None
        finally:
            await batcher.aclose()
        if rebuilt is None:
            self._bump(code, plans_fallback=1)
            return PartRepairOutcome(repaired, failures, True)

        for ci in lost:
            base = bases[ci]
            buf = (bytearray(part.chunksize) if base is None
                   else bytearray(base))
            spliced = 0
            for start, length in union:
                seg = rebuilt.get(start, {}).get(ci)
                if seg is None or len(seg) != length:
                    spliced = -1
                    break
                buf[start: start + length] = seg
                spliced += 1
            if spliced < 0 or not await self._verify_full(
                    chunks[ci], buf, pipe, code):
                # helpers inconsistent with this chunk's hash (stale
                # tree, raced writer): the full resilver re-reads
                # everything and decides
                fallback = True
                self._bump(code, plans_fallback=1)
                continue
            victims = corrupt[ci] + missing[ci]
            if not victims:
                fallback = True  # needs NEW placement: resilver's job
                self._bump(code, plans_fallback=1)
                continue
            r, f = await self._write_victims(chunks[ci], bytes(buf),
                                             victims, cx, code)
            if r:
                self._bump(
                    code,
                    bytes_rebuilt=sum(ln for _s, ln in
                                      ranges_by_ci[ci]),
                    ranges_rebuilt=len(ranges_by_ci[ci]))
            repaired += r
            failures += f
        fallback = self._maybe_replace(code, chunks, corrupt, missing,
                                       fallback)
        return PartRepairOutcome(repaired, failures, fallback)
