"""Cluster placement engine: zone-constrained, weighted, hash-seeded,
failure-tolerant shard placement.

Mirrors src/cluster/destination.rs + src/cluster/writer.rs:

* capacity check ``sum(repeat+1) >= count`` (destination.rs:69-72);
* shared writer state: per-node availability, failed nodes, zone budgets,
  error list, one hash-seeded RNG (destination.rs:73-84);
* resilver pre-pass removes availability from nodes already holding the
  part's other shards (destination.rs:85-94);
* writers are chained: writer i waits <=100 ms for writer i-1's first
  placement decision (destination.rs:100-113, writer.rs:245-252);
* ``next_writer`` draws a weighted random node honoring zone rules, RNG
  seeded from the first shard hash for deterministic placement
  (writer.rs:59-97);
* on write failure the node is invalidated, zone budgets are re-inflated,
  and a new node is drawn — loop until success or exhaustion
  (writer.rs:99-122,254-276).

One deliberate deviation: the reference's "banned zone" filter keeps *only*
nodes inside zones whose ``maximum`` budget is exhausted
(writer.rs:167-175), which inverts the evident intent; here nodes in
exhausted zones are excluded.  Zone rules are untested in the reference
(SURVEY §4); they are tested here.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional, Sequence

from chunky_bits_tpu.cluster import clock as _clock
from chunky_bits_tpu.cluster.nodes import ClusterNode, ClusterNodes
from chunky_bits_tpu.cluster.profile import ClusterProfile, ZoneRule
from chunky_bits_tpu.cluster.tunables import stagger_seconds
from chunky_bits_tpu.errors import (
    NotEnoughAvailability,
    NotEnoughWriters,
    ShardError,
    is_transient_error,
)
from chunky_bits_tpu.file.hashing import AnyHash
from chunky_bits_tpu.file.location import Location, LocationContext

#: default writer stagger (writer.rs:246 pins 100 ms); the live value
#: is read through ``tunables.stagger_seconds()`` at each write so the
#: knob is env-tunable and CB102-discoverable like every other
STAGGER_SECONDS = 0.1


class _WriterState:
    """Shared placement state (writer.rs:47-57)."""

    def __init__(self, nodes: ClusterNodes, profile: ClusterProfile,
                 cx: LocationContext):
        self.nodes = nodes
        self.cx = cx
        self.lock = asyncio.Lock()
        self.available: dict[int, int] = {
            i: node.repeat + 1 for i, node in enumerate(nodes)
        }
        self.failed: set[int] = set()
        self.zone_status: dict[str, ZoneRule] = {
            zone: rule.copy() for zone, rule in profile.zone_rules.items()
        }
        self.errors: list[ShardError] = []
        self.rng: Optional[random.Random] = None

    # -- zone filtering (writer.rs:125-199); precedence required > banned >
    #    ideal --

    def _eligible(self) -> list[tuple[int, ClusterNode]]:
        required = {z for z, r in self.zone_status.items() if r.minimum > 0}
        banned = {z for z, r in self.zone_status.items()
                  if r.maximum is not None and r.maximum <= 0}
        ideal = {z for z, r in self.zone_status.items() if r.ideal > 0}
        out = []
        for i, node in enumerate(self.nodes):
            if required:
                if not (node.zones & required):
                    continue
            elif banned:
                if node.zones & banned:  # deviation: exclude exhausted zones
                    continue
            elif ideal:
                if not (node.zones & ideal):
                    continue
            if i in self.failed:
                continue
            if self.available.get(i, 0) >= 1:
                out.append((i, node))
        return out

    def _remove_availability(self, index: int, node: ClusterNode) -> None:
        """Decrement node slot + zone budgets (writer.rs:201-219)."""
        self.available[index] -= 1
        for zone in node.zones:
            rule = self.zone_status.get(zone)
            if rule is not None:
                rule.ideal -= 1
                rule.minimum -= 1
                if rule.maximum is not None:
                    rule.maximum -= 1

    def _prefer_healthy(self, eligible: list[tuple[int, ClusterNode]]
                        ) -> list[tuple[int, ClusterNode]]:
        """Health-aware placement: de-prioritize nodes the scoreboard
        (cluster/health.py, via the shared LocationContext) marks
        degraded — open/half-open breaker or error-EWMA past the
        threshold — BEFORE they hard-fail a write.  Degraded nodes stay
        eligible as a last resort (capacity beats latency when nothing
        healthy remains), and with no health data the draw is
        byte-identical to the reference's (writer.rs:59-97)."""
        health = self.cx.health
        if health is None:
            return eligible
        preferred = [(i, n) for i, n in eligible
                     if not health.degraded(n.location.location)]
        if preferred and sum(n.location.weight
                             for _i, n in preferred) > 0:
            return preferred
        return eligible

    async def next_writer(self, hash_: AnyHash
                          ) -> tuple[int, ClusterNode]:
        async with self.lock:
            if not any(v > 0 for v in self.available.values()):
                raise self._pop_error()
            eligible = self._prefer_healthy(self._eligible())
            total_weight = sum(n.location.weight for _i, n in eligible)
            if total_weight == 0:
                raise self._pop_error()
            if self.rng is None:
                # Deterministic placement, seeded from the first shard's
                # hash (writer.rs:80-85).
                self.rng = random.Random(hash_.value.digest)
            sample = self.rng.randrange(total_weight)
            current = 0
            for index, node in eligible:
                current += node.location.weight
                if current > sample:
                    self._remove_availability(index, node)
                    return index, node
            raise AssertionError("invalid writer sample")

    def _pop_error(self) -> ShardError:
        if self.errors:
            return self.errors.pop()
        return NotEnoughAvailability()

    async def invalidate_index(self, index: int, err: ShardError) -> None:
        """Mark a node failed and re-inflate its zones' budgets
        (writer.rs:99-122)."""
        async with self.lock:
            self.failed.add(index)
            self.errors.append(err)
            if 0 <= index < len(self.nodes):
                for zone in self.nodes[index].zones:
                    rule = self.zone_status.get(zone)
                    if rule is not None:
                        rule.minimum += 1
                        if rule.maximum is not None:
                            rule.maximum += 1


class ClusterWriter:
    """Per-shard placement + retry engine (writer.rs:222-277)."""

    def __init__(self, state: _WriterState,
                 waiter: Optional[asyncio.Event],
                 staller: Optional[asyncio.Event]):
        self.state = state
        self.waiter = waiter
        self.staller = staller

    async def write_shard(self, hash_: AnyHash, data: bytes
                          ) -> list[Location]:
        # Stagger parity (writer.rs:246): writer i waits at most the
        # stagger window for writer i-1's FIRST placement decision, so
        # concurrent shard writers of one part serialize their initial
        # draws (deterministic seeded placement) without ever blocking
        # on a stuck sibling.  The 100 ms reference constant is the
        # default of the `tunables.stagger_seconds()` knob
        # ($CHUNKY_BITS_TPU_STAGGER_SECONDS).
        if self.waiter is not None:
            waiter, self.waiter = self.waiter, None
            try:
                await asyncio.wait_for(
                    waiter.wait(), stagger_seconds(default=STAGGER_SECONDS))
            except asyncio.TimeoutError:
                pass
        while True:
            try:
                index, node = await self.state.next_writer(hash_)
            finally:
                if self.staller is not None:
                    self.staller.set()
                    self.staller = None
            # Transient HTTP failures (408/429/5xx minus 507) get up to
            # `tunables.read_retries` jittered-backoff retries against
            # the SAME node before it is invalidated — the reference
            # invalidates on the first error (writer.rs:99-122), which
            # ejects a briefly-overloaded node from the whole part.
            attempt = 0
            while True:
                try:
                    location = await node.location.location.write_subfile(
                        str(hash_), data, self.state.cx)
                except ShardError as err:
                    if attempt < self.state.cx.read_retries \
                            and is_transient_error(err):
                        attempt += 1
                        await _clock.sleep(
                            random.uniform(0.025, 0.075) * attempt)
                        continue
                    await self.state.invalidate_index(index, err)
                    break  # draw a different node
                else:
                    return [location]


class Destination:
    """CollectionDestination over a cluster (destination.rs:33-115)."""

    def __init__(self, nodes: ClusterNodes, profile: ClusterProfile,
                 cx: LocationContext):
        self.nodes = nodes
        self.profile = profile
        self.cx = cx

    def get_context(self) -> LocationContext:
        return self.cx

    def with_conflict_overwrite(self) -> "Destination":
        """A copy whose writes overwrite existing files — used by resilver
        so repairs can replace corrupt chunk files in place."""
        from chunky_bits_tpu.file.location import OVERWRITE

        return Destination(
            self.nodes, self.profile,
            self.cx.but_with(on_conflict=OVERWRITE))

    def get_writers(self, count: int) -> list[ClusterWriter]:
        return self.get_used_writers([None] * count)

    def get_used_writers(self, locations: Sequence[Optional[Location]]
                         ) -> list[ClusterWriter]:
        count = sum(1 for loc in locations if loc is None)
        if self.nodes.total_slots() < count:
            raise NotEnoughWriters(
                f"cluster has {self.nodes.total_slots()} slots, "
                f"need {count}"
            )
        state = _WriterState(self.nodes, self.profile, self.cx)
        # Nodes already holding one of the part's shards are not eligible
        # for its missing shards (destination.rs:85-94).
        for location in locations:
            if location is None:
                continue
            for index, node in enumerate(self.nodes):
                if node.location.location.is_parent_of(location):
                    state._remove_availability(index, node)
        writers: list[ClusterWriter] = []
        prev_event: Optional[asyncio.Event] = None
        for _ in range(count):
            own_event = asyncio.Event()
            writers.append(ClusterWriter(state, prev_event, own_event))
            prev_event = own_event
        return writers
