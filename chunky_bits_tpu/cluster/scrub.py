"""Continuous scrub/repair daemon: verify data before a client finds it
corrupt.

A TPU-repo extension beyond the reference (``Chunky-Bits`` verifies only
on demand, src/file/file_part.rs:228-251): at scale, latent sector
errors dominate durability math — a chunk that rotted months ago is only
discovered when a read needs it, by which point its stripe may have lost
more than ``p`` chunks.  The scrub daemon walks every file reference in
the cluster's metadata store on a cycle, re-hashes each chunk replica
against its golden digest on the shared ``HostPipeline``, feeds
corruption demerits into the cluster's ``HealthScoreboard``, and
triggers a bounded resilver of any damaged part.  PAPERS.md's
"Fast Product-Matrix Regenerating Codes" (1412.3022) frames repair as a
scheduled, bandwidth-metered background job rather than an on-demand
full re-read; this module is that scheduler for the verification side
(the resilver it triggers reuses the existing repair machinery).

**Byte-rate bound.**  Scrub I/O competes with client traffic, so the
walk is token-bucket bounded: ``tunables.scrub_bytes_per_sec``
(``$CHUNKY_BITS_TPU_SCRUB_BYTES_PER_SEC``; YAML wins) is the sustained
budget, with a one-second burst.  0 (the default) means the daemon is
never constructed — zero overhead when off, per the
measure-before-defaulting invariant.

**Priority.**  Each pass scans files whose chunks live on *degraded*
nodes (open/half-open breaker or high error EWMA, per the scoreboard)
first: data co-resident with a failing disk is the data most likely to
be the next loss, so it gets verified — and repaired — before the
healthy tail of the namespace.  On a meta-log store
(cluster/meta_log.py) a second tier follows: files published since the
previous pass (the bounded ``changes(since_generation)`` tail feed) —
fresh writes are verified before the cold tail.

**Metadata cost.**  On a meta-log store the priority pre-scan is a
pure index scan (``_index_prescan``: publish-time node keys vs the
scoreboard's degraded set — zero ref reads, zero parses, so ordering
the whole namespace costs microseconds per thousand refs) and the
verify walk fetches refs lazily, one ``FETCH_PAGE`` batch of grouped
sequential log reads at a time — each ref's bytes read exactly once
per pass, pass memory bounded by one page.  On the legacy store the
pass reads each ref exactly once into a full snapshot that feeds both
scoring and the walk (``_namespace_refs`` — the old shape read every
ref twice per pass).

**Concurrency shape** (the CB204 audience): the daemon is a plain
asyncio task on its caller's loop; hashing hops to the host pipeline's
worker threads and returns through the pipeline's loop-safe bridge; the
scoreboard is thread-safe by construction.  ``stop()`` cancels and
AWAITS the task — the daemon can never leak past its owner (pinned
under ``CHUNKY_BITS_TPU_SANITIZE=1`` in tests/test_scrub.py).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from chunky_bits_tpu.cluster import clock as _clock
from chunky_bits_tpu.errors import ChunkyBitsError, LocationError

log = logging.getLogger("chunky_bits_tpu.scrub")


def _canonical(obj: object) -> str:
    """Canonical serialization of a metadata object — the scrub repair
    fence compares the stored bytes' *meaning*, so a format-level
    rewrite (key order, yaml vs json) never reads as a client write."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _ref_from_obj(obj: object):
    from chunky_bits_tpu.file.file_reference import FileReference

    return FileReference.from_obj(obj)


class TokenBucket:
    """Sustained byte-rate bound with a one-second burst.  ``take(n)``
    sleeps until ``n`` bytes of budget have accrued; oversized requests
    (one chunk larger than the burst) drive the balance negative so the
    *average* still honors the rate.  A rate of 0 disables the bound
    (take returns immediately) — the daemon itself is not constructed
    at rate 0, but --once CLI runs may scrub unthrottled.

    An optional pressure hook (:meth:`set_pressure` — the QoS plane's
    priority ordering, cluster/qos.py) scales *accrual* by
    ``1 - pressure`` with a :data:`MIN_ACCRUAL` floor: under full
    client-admission pressure scrub/repair I/O degrades to 5% of its
    budget but NEVER stops accruing — a stuck pressure signal slows
    the scrub walk, it cannot hang it (degrade, never hang)."""

    #: bound on a single sleep slice so cancellation (daemon stop)
    #: is always prompt
    MAX_SLEEP = 0.5

    #: accrual floor under full pressure — background I/O yields to
    #: client traffic but keeps a liveness trickle
    MIN_ACCRUAL = 0.05

    def __init__(self, rate: float) -> None:
        self.rate = max(float(rate), 0.0)
        self._balance = self.rate  # start with one second of burst
        self._last = _clock.monotonic()
        self._pressure: Optional[Callable[[], float]] = None

    def set_pressure(self, fn: Optional[Callable[[], float]]) -> None:
        """Install (or clear) the gateway pressure signal in [0, 1];
        accrual scales by ``max(1 - pressure, MIN_ACCRUAL)``."""
        self._pressure = fn

    def _effective_rate(self) -> float:
        """Accrual rate after the pressure throttle — the ONE number
        both accrual and the wait estimate must use: waiting at the
        unthrottled rate while accruing at the throttled one recovers
        only ``1 - pressure`` of each wait, an asymptotic (Zeno) loop
        that never reaches zero."""
        rate = self.rate
        if self._pressure is not None:
            p = min(max(float(self._pressure()), 0.0), 1.0)
            rate *= max(1.0 - p, self.MIN_ACCRUAL)
        return rate

    def _accrue(self) -> None:
        now = _clock.monotonic()
        self._balance = min(
            self._balance + (now - self._last) * self._effective_rate(),
            self.rate)
        self._last = now

    async def take(self, nbytes: int) -> None:
        if self.rate <= 0:
            return
        self._accrue()
        self._balance -= nbytes
        while self._balance < 0:
            wait = min(-self._balance / self._effective_rate(),
                       self.MAX_SLEEP)
            # floor the slice: float rounding (or pressure rising
            # between estimate and accrual) must never shrink waits
            # toward zero without the balance reaching it
            await _clock.sleep(max(wait, 0.001))
            self._accrue()


@dataclass
class ScrubStats:
    """Counter snapshot — the ``Scrub<...>`` profiler stanza and the
    gateway's ``/scrub/status`` payload."""

    passes: int
    files_scanned: int
    chunks_scanned: int
    bytes_verified: int
    corrupt: int
    unavailable: int
    repaired: int
    repair_failures: int
    rate_bytes_per_sec: float
    running: bool
    last_pass_seconds: Optional[float]
    #: the repair planner's counter snapshot (RepairStats.to_obj), or
    #: None when the daemon runs with the legacy repair shape
    repair: Optional[dict] = None

    def to_obj(self) -> dict:
        return {
            "running": self.running,
            "passes": self.passes,
            "files_scanned": self.files_scanned,
            "chunks_scanned": self.chunks_scanned,
            "bytes_verified": self.bytes_verified,
            "corrupt": self.corrupt,
            "unavailable": self.unavailable,
            "repaired": self.repaired,
            "repair_failures": self.repair_failures,
            "rate_bytes_per_sec": self.rate_bytes_per_sec,
            **({"last_pass_seconds": round(self.last_pass_seconds, 3)}
               if self.last_pass_seconds is not None else {}),
            **({"repair": self.repair}
               if self.repair is not None else {}),
        }

    def __str__(self) -> str:
        rate = (f"{self.rate_bytes_per_sec:.0f}B/s"
                if self.rate_bytes_per_sec > 0 else "unbounded")
        plans = ""
        if self.repair is not None:
            # c=copy d=decode m=msr(pm-msr regeneration) f=fallback
            plans = (f" plans={self.repair.get('plans_copy', 0)}c/"
                     f"{self.repair.get('plans_decode', 0)}d/"
                     f"{self.repair.get('plans_msr', 0)}m/"
                     f"{self.repair.get('plans_fallback', 0)}f")
            ratio = self.repair.get("helper_bytes_per_rebuilt_byte")
            if ratio is not None:
                plans += f" helperB/rebuiltB={ratio:.2f}"
        return (f"Scrub<scanned={self.files_scanned}f/"
                f"{self.chunks_scanned}c "
                f"verified={self.bytes_verified}B "
                f"corrupt={self.corrupt} repaired={self.repaired}"
                f"{plans} | rate={rate}>")


class ScrubDaemon:
    """One cluster's scrub/repair loop.

    ``run_once`` is a single full pass (the CLI's ``scrub --once``);
    ``start``/``stop`` run passes continuously with ``interval_seconds``
    of idle between them (the gateway's long-running mode).  ``repair``
    False turns detection-only mode on (report + demerit, never write).

    ``planner`` True (the default) routes repair through the targeted
    ``RepairPlanner`` (cluster/repair.py): block-localized ranged reads,
    health-picked helpers, exact per-plan byte metering, in-place
    rewrites that never republish metadata; the classic full
    ``resilver`` runs only as its fallback.  ``planner`` False keeps
    the legacy shape end to end — whole-replica copy beside a healthy
    one, part-granular resilver for lost chunks — which is the OFF leg
    of bench --config 11's repair-bandwidth A/B.

    ``profiler`` (a file.profiler.Profiler) rides every location I/O
    the pass makes — the per-read byte accounting bench --config 11
    measures helper traffic with; None (the default) keeps the fused
    no-profiler fast paths.

    ``replace_after_s`` is the planner's re-placement escalation
    threshold (cluster/repair.py): a replica unwritable for this long
    is treated as permanently lost and its part resilvered to a NEW
    location; below it, in-place repair retries next pass (transient
    partitions are waited out, never answered with a republish storm).
    """

    #: parsed refs fetched per batch on the index-pre-scan path — the
    #: pass's peak object memory, and the grouped-read granularity
    FETCH_PAGE = 256

    def __init__(self, cluster, bytes_per_sec: Optional[float] = None,
                 interval_seconds: float = 60.0, repair: bool = True,
                 profile_name: Optional[str] = None,
                 planner: bool = True, profiler=None,
                 replace_after_s: float = 900.0) -> None:
        self.cluster = cluster
        rate = (cluster.tunables.scrub_bytes_per_sec
                if bytes_per_sec is None else float(bytes_per_sec))
        self.rate = max(rate, 0.0)
        self.interval_seconds = max(float(interval_seconds), 0.0)
        self.repair = repair
        self.profile_name = profile_name
        self.profiler = profiler
        self._bucket = TokenBucket(self.rate)
        if planner:
            from chunky_bits_tpu.cluster.repair import RepairPlanner

            self._planner: Optional[RepairPlanner] = RepairPlanner(
                health=cluster.health_scoreboard(),
                bucket=self._bucket,
                backend=cluster.tunables.backend,
                replace_after_s=replace_after_s,
                # the continuity bound must out-span the retry cadence:
                # failures recur once per pass, so with interval >
                # replace_after_s every pass would otherwise look like
                # a fresh (stale-reset) streak and escalation could
                # never fire
                stale_after_s=max(replace_after_s,
                                  2.0 * float(interval_seconds)))
        else:
            self._planner = None
        self._task: Optional[asyncio.Task] = None
        #: high-water generation cursor for the meta-log ``changes()``
        #: tail feed (0 = everything is new); only ever touched from
        #: the pass loop, so unguarded
        self._seen_generation = 0
        # counters are read by profiler reports and the gateway status
        # handler (possibly from another thread than the pass loop's)
        self._lock = threading.Lock()
        self._passes = 0
        self._files = 0
        self._chunks = 0
        self._bytes = 0
        self._corrupt = 0
        self._unavailable = 0
        self._repaired = 0
        self._repair_failures = 0
        self._last_pass_seconds: Optional[float] = None
        # weakly self-register with the process metrics registry so a
        # /metrics scrape reports scrub progress (the counters are
        # already lock-guarded for exactly this cross-thread read)
        from chunky_bits_tpu.obs.metrics import get_registry

        get_registry().register_source("scrub", self)

    # ---- reporting ----

    def _bump(self, **deltas: int) -> None:
        with self._lock:
            for key, delta in deltas.items():
                setattr(self, f"_{key}", getattr(self, f"_{key}") + delta)

    def stats(self) -> ScrubStats:
        with self._lock:
            return ScrubStats(
                passes=self._passes,
                files_scanned=self._files,
                chunks_scanned=self._chunks,
                bytes_verified=self._bytes,
                corrupt=self._corrupt,
                unavailable=self._unavailable,
                repaired=self._repaired,
                repair_failures=self._repair_failures,
                rate_bytes_per_sec=self.rate,
                running=self._task is not None and not self._task.done(),
                last_pass_seconds=self._last_pass_seconds,
                repair=(self._planner.stats().to_obj()
                        if self._planner is not None else None),
            )

    # ---- the walk ----

    async def _list_file_paths(self) -> list[str]:
        """Every file path in the metadata store (depth-first; per-dir
        failures skip the subtree rather than abort the pass — a scrub
        must survive a half-broken namespace)."""
        out: list[str] = []
        stack = ["."]
        while stack:
            path = stack.pop()
            try:
                entries = await self.cluster.list_files(path)
            except ChunkyBitsError:
                continue
            for entry in entries:
                if str(entry.path) in (".", path):
                    continue  # the listing's own top entry
                if entry.is_directory():
                    stack.append(entry.path)
                elif entry.is_file():
                    out.append(entry.path)
        return out

    async def _namespace_refs(self) -> list[tuple[str, object]]:
        """(path, parsed metadata obj) for every file in the namespace,
        each ref's bytes read exactly ONCE for the whole pass.  A
        meta-log store serves this from one index scan plus grouped
        sequential log reads (``namespace_snapshot``); the legacy
        file-per-ref store falls back to the recursive walk with one
        read per path.  Either way the priority pre-scan and the verify
        walk below share this single read — the old shape read every
        ref TWICE per pass (once to score, once to scrub), which at
        namespace scale doubled the pass's metadata cost on both
        stores."""
        metadata = self.cluster.metadata
        snapshot = getattr(metadata, "namespace_snapshot", None)
        if snapshot is not None:
            try:
                return list(await snapshot())
            except ChunkyBitsError:
                # a single foreign/corrupt ref poisons the batched
                # read; the per-path walk below skips just that entry
                # (a scrub must survive a half-broken namespace)
                pass
        out: list[tuple[str, object]] = []
        for path in await self._list_file_paths():
            try:
                out.append((path, await metadata.read(path)))
            except ChunkyBitsError:
                continue  # unparseable/foreign metadata: skip
        return out

    async def _recent_paths(self) -> frozenset:
        """Paths published since the previous pass, from the meta-log
        ``changes(since_generation)`` tail feed — empty on stores
        without one (and after a compaction dropped the cursor's
        window, which simply reads as nothing-recent).  One bounded
        page per pass: a hint tier, not an audit log."""
        changes = getattr(self.cluster.metadata, "changes", None)
        if changes is None:
            return frozenset()
        try:
            rows = await changes(self._seen_generation)
        except ChunkyBitsError:
            return frozenset()
        if rows:
            self._seen_generation = max(r.generation for r in rows)
        return frozenset(r.name for r in rows if not r.tombstone)

    async def _index_prescan(self) -> Optional[list[tuple[int, str]]]:
        """Priority-scored (prio, path) for the whole namespace from
        ONE meta-log index scan — zero ref reads, zero parses: each
        ref's publish-time node keys (``namespace_nodes``) are
        intersected with the scoreboard's degraded-key set, and the
        ``changes()`` feed promotes fresh writes, exactly like the
        snapshot path scores below.  None on stores without the
        projection (legacy store, or any ref published without one) —
        the caller falls back to the full snapshot read, so scoring is
        never silently partial."""
        index = getattr(self.cluster.metadata, "namespace_nodes", None)
        if index is None:
            return None
        try:
            rows = await index()
        except ChunkyBitsError:
            return None
        if rows is None:
            return None
        recent = await self._recent_paths()
        degraded = self.cluster.health_scoreboard().degraded_keys()
        out: list[tuple[int, str]] = []
        for path, nodes in rows:
            prio = 2
            if degraded and any(key in degraded for key in nodes):
                prio = 0
            elif path in recent:
                prio = 1
            out.append((prio, path))
        return out

    async def _fetch_objs(self, paths: list) -> dict:
        """path -> parsed metadata obj for one page of the verify walk
        (index-pre-scan path only).  Batched through the meta-log's
        ``read_objs`` (grouped sequential log reads); a poisoned batch
        or a store without one degrades to per-path reads, and per-path
        failures skip just that entry — a scrub must survive a
        half-broken namespace."""
        if not paths:
            return {}
        metadata = self.cluster.metadata
        reader = getattr(metadata, "read_objs", None)
        if reader is not None:
            try:
                return dict(await reader(paths))
            except ChunkyBitsError:
                pass  # isolate the bad entry via the per-path loop
        out: dict = {}
        for path in paths:
            try:
                out[path] = await metadata.read(path)
            except ChunkyBitsError:
                continue
        return out

    def _ref_priority(self, ref) -> int:
        """0 = any chunk replica lives on a degraded node (scan first),
        2 = all-healthy (``run_once`` promotes recently-written
        all-healthy refs to tier 1 via the ``changes()`` feed — fresh
        writes get verified before the cold tail of the namespace).
        With no health data and no recency feed every ref scores 2 and
        the pass order is the plain namespace order."""
        health = self.cluster.health_scoreboard()
        for part in ref.parts:
            for chunk in part.data + part.parity:
                for location in chunk.locations:
                    if health.degraded(location):
                        return 0
        return 2

    async def _verify_chunk(self, chunk, location, cx, pipe
                            ) -> tuple[Optional[bool], Optional[bytes]]:
        """(verdict, corrupt bytes): verdict True = replica matches its
        golden digest, False = corrupt, None = unreadable.  Fused
        native hashing where the replica is local/packed (bytes never
        surface to Python); generic read+verify otherwise — and when
        THAT path finds corruption, the bytes it already holds ride
        back so the repair planner localizes damage without re-reading
        the victim.  The byte budget is taken BEFORE the I/O — the
        bound meters bytes touched, not bytes that happened to
        verify."""
        from chunky_bits_tpu.file.file_part import _hash_local_fused

        nbytes = None
        try:
            nbytes = await location.file_len(cx)
        except LocationError:
            return None, None
        await self._bucket.take(nbytes)
        digest = await _hash_local_fused(chunk, location, cx, pipe)
        if digest is not None:
            self._bump(bytes=nbytes)
            return digest == chunk.hash.value.digest, None
        try:
            data = await location.read(cx)
        except LocationError:
            return None, None
        self._bump(bytes=len(data))
        ok = await pipe.run(
            "verify", lambda: chunk.hash.verify(data),
            nbytes=len(data))
        return bool(ok), (None if ok else bytes(data))

    async def _rewrite_replicas(self, chunk, source, victims, cx,
                                pipe) -> None:
        """Overwrite corrupt/missing replicas of ``chunk`` in place
        with the verified bytes from ``source`` (content-addressed, so
        an overwrite matching the hash is always safe — the same
        rationale as resilver's overwrite deviation).  Reads and
        writes are metered through the byte budget like verification
        is."""
        from chunky_bits_tpu.file.location import OVERWRITE

        try:
            nbytes = await source.file_len(cx)
            await self._bucket.take(nbytes)
            data = await source.read(cx)
        except LocationError:
            return  # the healthy replica vanished: next pass decides
        ok = await pipe.run(
            "verify", lambda: chunk.hash.verify(data),
            nbytes=len(data))
        if not ok:
            return  # raced a writer; don't spread unverified bytes
        overwrite_cx = cx.but_with(on_conflict=OVERWRITE)
        for victim in victims:
            await self._bucket.take(len(data))
            try:
                await victim.write(data, overwrite_cx)
            except LocationError:
                # node still down/full: counted, retried next pass
                self._bump(repair_failures=1)
                continue
            self._bump(repaired=1)

    async def _scrub_ref(self, path: str, ref, cx, pipe,
                         snapshot: str) -> None:
        """Verify every replica of every chunk of one file, then repair
        the damage.  With the planner (the default) repair is targeted
        and in place — block-localized ranged reads, health-picked
        helpers, no metadata republish — and only parts the planner
        hands back fall through to the classic full ``resilver``; with
        ``planner=False`` every damaged part takes the legacy sequence
        (whole-replica rewrite beside a healthy one, part-granular
        resilver for lost chunks), the same as the CLI's ``resilver``
        command.  ``snapshot`` is the canonical serialized form of
        ``ref`` as fetched — the resilver republish is fenced on the
        stored metadata still matching it, so a client overwrite that
        landed while this (rate-bounded, possibly long) scrub was
        running is never clobbered with a stale repaired ref."""
        health = self.cluster.health_scoreboard()
        damaged_parts = []
        for part in ref.parts:
            # verify phase: one verdict per replica (True verified,
            # False corrupt, None unreadable) — the planner's input
            verdicts = []
            # corrupt-replica bytes the generic verify path already
            # surfaced, keyed (chunk index, location) — the planner
            # localizes from these instead of re-reading the victim;
            # scoped to ONE part, so memory stays bounded by the
            # (rare) corrupt replicas of the part in hand
            payloads: dict = {}
            part_damaged = False
            for ci, chunk in enumerate(part.data + part.parity):
                self._bump(chunks=1)
                per_loc = []
                if not chunk.locations:
                    # a chunk with no replicas at all: nothing to
                    # verify, but the part needs repair (resilver
                    # places a new replica — the planner hands it back)
                    part_damaged = True
                for location in chunk.locations:
                    verdict, payload = await self._verify_chunk(
                        chunk, location, cx, pipe)
                    if verdict is False:
                        # corrupt content on a successful transfer is
                        # still a demerit for the node serving it —
                        # the same rule as the read path's _corrupt
                        self._bump(corrupt=1)
                        health.record(location, False)
                        part_damaged = True
                        if payload is not None:
                            payloads[(ci, location)] = payload
                    elif verdict is None:
                        self._bump(unavailable=1)
                        part_damaged = True
                    per_loc.append((location, verdict))
                verdicts.append(per_loc)
            if not part_damaged or not self.repair:
                continue
            if self._planner is not None:
                outcome = await self._planner.repair_part(
                    part, verdicts, cx, pipe, payloads=payloads)
                self._bump(repaired=outcome.repaired,
                           repair_failures=outcome.failures)
                if outcome.fallback:
                    damaged_parts.append(part)
                continue
            # legacy shape (bench --config 11's OFF leg): whole-replica
            # rewrite beside a healthy one — resilver only rebuilds
            # chunks with NO valid replica (chunk_status
            # short-circuit), so without this the same rotten extent
            # would be re-detected (and the node re-demerited) every
            # pass forever — and part-granular resilver for the rest
            part_lost = False
            for chunk, per_loc in zip(part.data + part.parity,
                                      verdicts):
                good = next(
                    (loc for loc, v in per_loc if v is True), None)
                victims = [loc for loc, v in per_loc if v is not True]
                if good is None:
                    part_lost = True
                elif victims:
                    await self._rewrite_replicas(chunk, good, victims,
                                                 cx, pipe)
            if part_lost:
                damaged_parts.append(part)
        self._bump(files=1)
        if not damaged_parts or not self.repair:
            return
        profile = self.cluster.get_profile(self.profile_name)
        if profile is None:
            self._bump(repair_failures=len(damaged_parts))
            return
        destination = self.cluster.get_destination(profile)
        for part in damaged_parts:
            # repair I/O is charged to the same byte budget as
            # verification, at part granularity: resilver re-reads
            # every replica and writes the rebuilt shards, so a
            # mass-repair pass after a node loss must throttle like
            # the scan does instead of saturating disks at full speed
            replicas = sum(len(c.locations)
                           for c in part.data + part.parity)
            await self._bucket.take(part.chunksize * (replicas + 1))
            try:
                report = await part.resilver(
                    destination, cx,
                    backend=self.cluster.tunables.backend,
                    pipeline=pipe)
            # lint: broad-except-ok a failed repair is a counter and a
            # retry next pass, never a dead daemon mid-namespace
            except Exception:
                self._bump(repair_failures=1)
                continue
            if report.successful_writes() and not report.failed_writes():
                self._bump(repaired=1)
            elif report.failed_writes():
                self._bump(repair_failures=1)
        try:
            # republish fence: only write back if the stored metadata
            # still matches what this scrub read — an overwrite that
            # raced the pass wins, and its chunks get scrubbed next
            # pass instead of being reverted to a stale ref.  (The
            # remaining window between this read and the write is one
            # metadata round-trip, not a whole rate-bounded pass.)
            current = _canonical(await self.cluster.metadata.read(path))
            if current != snapshot:
                return
            await self.cluster.write_file_ref(path, ref)
        except ChunkyBitsError:
            self._bump(repair_failures=1)

    async def run_once(self) -> ScrubStats:
        """One full pass over the namespace: degraded-resident files
        first, recently-written files next, the healthy cold tail last.
        Returns the cumulative stats snapshot.

        On a meta-log store the priority pre-scan is a pure INDEX scan
        (``_index_prescan``: per-ref node keys intersected with the
        scoreboard's degraded set — zero ref reads, zero parses), and
        the verify walk fetches parsed refs lazily in priority order,
        one page at a time (``_fetch_objs`` -> ``read_objs``: grouped
        sequential log reads), so pass memory peaks at the index plus
        ONE page of objects and degraded-tier scrubbing starts
        immediately instead of after a full-namespace read.  On the
        legacy store the pass falls back to one full snapshot
        (``_namespace_refs`` — each ref's bytes still read exactly
        once; the old shape read every ref twice).  Holding scored
        paths across a (rate-bounded, possibly hours-long) pass is
        safe from clobbering client writes because the repair
        republish is FENCED on a fresh metadata read still matching
        the obj as fetched (``_scrub_ref``) — a raced overwrite wins,
        and chunk rewrites are content-addressed in-place either way.
        NOTE: scoring and fetching bypass ``get_file_ref`` — a pass
        must not churn the serving path's file-ref LRU (it would evict
        every hot ref the gateway is using)."""
        started = _clock.monotonic()
        cx = self.cluster.tunables.location_context()
        if self.profiler is not None:
            # per-read byte accounting for the pass (bench --config 11
            # measures helper traffic this way); disables the fused
            # no-profiler fast paths, identically for every leg
            cx = cx.but_with(profiler=self.profiler)
        pipe = self.cluster.host_pipeline()
        scored: list[tuple[int, str, object]] = []
        plan = await self._index_prescan()
        if plan is not None:
            scored = [(prio, path, None) for prio, path in plan]
        else:
            refs = await self._namespace_refs()
            recent = await self._recent_paths()
            for path, obj in refs:
                try:
                    ref = _ref_from_obj(obj)
                except ChunkyBitsError:
                    continue  # unparseable/foreign metadata: skip
                prio = self._ref_priority(ref)
                if prio != 0 and path in recent:
                    prio = 1
                scored.append((prio, path, obj))
            del refs, recent
        # stable by priority only: within a tier the index's own order
        # (namespace order) is preserved, like the old pass
        scored.sort(key=lambda t: t[0])
        scored.reverse()  # pop() below consumes from the front
        while scored:
            page = [scored.pop()
                    for _ in range(min(self.FETCH_PAGE, len(scored)))]
            fetched = await self._fetch_objs(
                [path for _prio, path, obj in page if obj is None])
            for _prio, path, obj in page:
                if obj is None:
                    obj = fetched.get(path)
                    if obj is None:
                        continue  # deleted/raced since the pre-scan
                try:
                    snapshot = _canonical(obj)
                    ref = _ref_from_obj(obj)
                except ChunkyBitsError:
                    continue
                await self._scrub_ref(path, ref, cx, pipe, snapshot)
        with self._lock:
            self._passes += 1
            self._last_pass_seconds = _clock.monotonic() - started
        return self.stats()

    # ---- daemon lifetime ----

    def set_pressure(self,
                     fn: Optional[Callable[[], float]]) -> None:
        """Forward the gateway QoS pressure signal to the daemon's
        token bucket — the ONE bucket every scrub and planner-repair
        byte charges, so one hook throttles both (priority ordering:
        client traffic > scrub/repair, cluster/qos.py)."""
        self._bucket.set_pressure(fn)

    async def _run_forever(self) -> None:
        while True:
            try:
                await self.run_once()
            except asyncio.CancelledError:
                raise
            # lint: broad-except-ok a failed pass must never silently
            # end continuous scrubbing for the process's remaining
            # lifetime; logged, and the next interval retries
            except Exception:
                log.exception("scrub pass failed; retrying after "
                              "interval")
            if self.interval_seconds <= 0:
                # rate-bounded back-to-back passes still yield between
                # chunks via the bucket; give the loop one tick anyway
                await asyncio.sleep(0)
                continue
            await _clock.sleep(self.interval_seconds)

    def start(self) -> None:
        """Start the continuous loop on the running event loop.
        Idempotent while running; a finished/crashed task restarts
        (the rolling-restart shape tests/test_chaos.py drives)."""
        if self._task is not None and not self._task.done():
            return
        self._task = asyncio.ensure_future(self._run_forever())

    async def stop(self) -> None:
        """Cancel AND await the pass loop — stop() returning means no
        scrub task survives (the no-leaked-tasks contract)."""
        task, self._task = self._task, None
        if task is None:
            return
        task.cancel()
        try:
            # lint: unbounded-await-ok the task was cancelled on the
            # line above and every wait inside the pass loop is a
            # bounded sleep slice (TokenBucket.MAX_SLEEP) or bounded
            # I/O, so cancellation delivery is prompt by construction
            await task
        except asyncio.CancelledError:
            pass
        # lint: broad-except-ok stop() returning means the task is
        # gone — a pass that died with a stray exception must not
        # re-raise here and abort the caller's shutdown sequence
        # (gateway serve's finally runs runner.cleanup after this)
        except Exception:
            log.exception("scrub task ended with an error")


def maybe_build(cluster, **kwargs) -> Optional[ScrubDaemon]:
    """A daemon for ``cluster`` when its ``scrub_bytes_per_sec`` tunable
    asks for one, else None — THE off-by-default gate: at rate 0 no
    daemon object exists, no task runs, nothing is imported at serve
    time beyond this check."""
    if cluster.tunables.scrub_bytes_per_sec <= 0:
        return None
    return ScrubDaemon(cluster, **kwargs)
