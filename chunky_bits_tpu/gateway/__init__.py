"""Streaming HTTP object gateway (the reference's src/http.rs)."""

from chunky_bits_tpu.gateway.http import make_app, parse_http_range, serve  # noqa: F401
