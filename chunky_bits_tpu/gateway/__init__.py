"""Streaming HTTP object gateway (the reference's src/http.rs), plus
the multi-worker serving plane (gateway/workers.py)."""

from chunky_bits_tpu.gateway.http import (  # noqa: F401
    file_ref_etag,
    make_app,
    parse_http_range,
    serve,
)
