"""Multi-worker serving plane: N pre-forked SO_REUSEPORT gateway
processes under one supervisor.

The reference serves a whole cluster through one process on one runtime
(src/http.rs + main.rs:474-485); this module is the scale-out extension
ROADMAP item 2 names: ``serve(..., workers=N)`` spawns N worker
*processes*, each binding the same (host, port) with ``SO_REUSEPORT`` so
the kernel load-balances accepted connections across them — no
userspace proxy, no shared accept lock, and a worker crash never wedges
the listener (the survivors keep accepting while the supervisor
respawns the dead slot with capped backoff).

**Why processes, and why the serving state is partitioned.**  One
asyncio loop is the gateway's ceiling once compute/host/network planes
scale (BASELINE config 9); the GIL means in-process threads cannot add
loop capacity.  Each worker therefore builds its OWN ``Cluster`` from
the same spec, which per CLAUDE.md's two-plane rules gives it:

- its own event loop and host pipeline (``min(N, nproc)`` daemon
  workers per process — size via ``tunables.host_threads`` when
  oversubscription matters);
- its own chunk cache (the cache is LOOP_BOUND by design — lock-free
  because all bookkeeping stays on one loop thread; sharing across
  processes would mean shared memory + locking on the hottest path.
  Partitioning costs duplicate cached bytes, capped at
  ``workers * cache_bytes`` — size accordingly);
- its own health scoreboard (thread-safe *within* a process, where
  worker threads record too, but deliberately not IPC-shared: each
  worker observes the same nodes and converges on the same ordering,
  and a per-worker hedge budget still caps total hedge amplification
  at the same ~5% of that worker's primaries).

The supervisor holds a bound-but-never-listening ``SO_REUSEPORT``
placeholder socket for the port's lifetime: it pins the concrete port
(``--listen-addr host:0`` works — workers are told the resolved port)
and keeps the address reserved across the respawn gap.  TCP lookup only
considers *listening* sockets, so the placeholder never steals a
connection.

Worker handshake: each child prints ``CHUNKY_BITS_GATEWAY_READY ...``
on stdout once its listener accepts; the supervisor waits (bounded) for
every slot before declaring the gateway up.  Worker count comes from
``serve --workers`` > ``$CHUNKY_BITS_TPU_GATEWAY_WORKERS``
(``tunables.gateway_workers``) > default 1.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import shutil
import signal
import socket
import sys
import tempfile
import time
from typing import Callable, Optional

from chunky_bits_tpu.errors import ChunkyBitsError
from chunky_bits_tpu.obs import metrics as obs_metrics

log = logging.getLogger("chunky_bits_tpu.gateway.workers")

#: stdout line a worker prints once its SO_REUSEPORT listener accepts
READY_MARKER = "CHUNKY_BITS_GATEWAY_READY"

#: respawn backoff: first retry fast, then exponential up to the cap —
#: a crash-looping worker must not melt the box, a one-off crash must
#: not leave the slot dark for long
_BACKOFF_INITIAL = 0.5
_BACKOFF_CAP = 10.0
#: a worker that survived this long resets its slot's backoff
_BACKOFF_RESET_UPTIME = 30.0

#: seconds a SIGTERM'd worker keeps its listener up while /healthz
#: answers 503 draining (in-flight requests finish; balancers observe
#: the drain) before serve is cancelled — well under the supervisor's
#: 5 s SIGKILL escalation
_DRAIN_SECONDS = 0.5


def _reuse_port_supported() -> bool:
    return hasattr(socket, "SO_REUSEPORT")


class GatewaySupervisor:
    """Owns the worker fleet for one (cluster, host, port) gateway:
    spawn, readiness, respawn-on-death, graceful stop.  All bookkeeping
    runs on the creating loop."""

    def __init__(self, cluster_obj: dict, host: str, port: int,
                 workers: int, serve_params: Optional[dict] = None,
                 ready_timeout: float = 60.0):
        if workers < 1:
            raise ChunkyBitsError(f"workers must be >= 1, got {workers}")
        if not _reuse_port_supported():
            raise ChunkyBitsError(
                "multi-worker gateway needs SO_REUSEPORT "
                "(unsupported on this platform); run with --workers 1")
        self.cluster_obj = cluster_obj
        self.host = host
        self.port = port  # resolved (non-zero) after start()
        self.workers = workers
        self.serve_params = dict(serve_params or {})
        self.ready_timeout = ready_timeout
        self._placeholder: Optional[socket.socket] = None
        self._spec_path: Optional[str] = None
        #: fleet metrics spool: every worker publishes its registry
        #: snapshot here (obs/metrics.py) so ANY worker's /metrics can
        #: serve the aggregated fleet view; created at start, removed
        #: at stop
        self.metrics_spool: Optional[str] = None
        self._procs: list = [None] * workers
        self._ready: list = [False] * workers
        self._slot_tasks: list = []
        self._drain_tasks: dict = {}
        self._stopping = False

    # ---- lifecycle ----

    async def start(self) -> None:
        """Reserve the port, write the worker spec, spawn every slot,
        and wait (bounded) until all workers accept connections.  Raises
        on a fleet that never comes up — a half-dead start must fail
        loudly, not serve at reduced capacity silently."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind((self.host, self.port))
        except BaseException as err:
            # close on ANY setup failure (setsockopt included), not
            # just the bind OSError the old shape guarded
            sock.close()
            if isinstance(err, OSError):
                raise ChunkyBitsError(
                    f"cannot bind {self.host}:{self.port}: {err}"
                ) from err
            raise
        self._placeholder = sock
        self.port = sock.getsockname()[1]
        self.metrics_spool = await asyncio.to_thread(
            tempfile.mkdtemp, prefix="cb-gateway-metrics-")
        self.serve_params.setdefault("metrics_spool",
                                     self.metrics_spool)
        self._spec_path = await asyncio.to_thread(self._write_spec)
        self._slot_tasks = [
            asyncio.ensure_future(self._run_slot(i))
            for i in range(self.workers)
        ]
        deadline = time.monotonic() + self.ready_timeout
        while not all(self._ready):
            if time.monotonic() > deadline:
                await self.stop()
                raise ChunkyBitsError(
                    f"gateway workers not ready after "
                    f"{self.ready_timeout:g}s "
                    f"({sum(self._ready)}/{self.workers} up)")
            dead = [t for t in self._slot_tasks if t.done()]
            for t in dead:
                # a slot task can only finish this early by crashing;
                # surface its exception instead of timing out blind
                if t.exception() is not None:
                    await self.stop()
                    raise ChunkyBitsError(
                        "gateway worker slot failed during start"
                    ) from t.exception()
            await asyncio.sleep(0.05)

    def worker_pids(self) -> list:
        """PIDs of the currently-live workers (respawns change them —
        the respawn test keys off exactly that)."""
        return [p.pid for p in self._procs
                if p is not None and p.returncode is None]

    def fleet_snapshot(self) -> dict:
        """The aggregated fleet metrics snapshot straight off the
        spool (counters/histograms summed, gauges worker-labeled) —
        the supervisor-side twin of any worker's ``GET /metrics``, for
        tooling that has the supervisor but not a socket.  Blocking
        file reads (small JSON files); call off-loop from async code.
        Empty until the first worker heartbeat (~2 s after ready)."""
        if self.metrics_spool is None:
            return {"families": []}
        return obs_metrics.fleet_snapshot(self.metrics_spool)

    async def wait(self) -> None:
        """Run until cancelled (the serve loop's park)."""
        while not self._stopping:
            await asyncio.sleep(3600)

    async def stop(self) -> None:
        """Terminate the fleet: SIGTERM, bounded wait, SIGKILL
        stragglers; release the placeholder and the spec file.
        Idempotent."""
        self._stopping = True
        for t in self._slot_tasks:
            t.cancel()
        for t in self._drain_tasks.values():
            t.cancel()
        if self._slot_tasks or self._drain_tasks:
            await asyncio.gather(*self._slot_tasks,
                                 *self._drain_tasks.values(),
                                 return_exceptions=True)
        self._slot_tasks = []
        self._drain_tasks = {}
        for proc in self._procs:
            if proc is None or proc.returncode is not None:
                continue
            try:
                proc.terminate()
            except ProcessLookupError:
                continue
        for proc in self._procs:
            if proc is None or proc.returncode is not None:
                continue
            try:
                await asyncio.wait_for(proc.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                proc.kill()
                try:
                    await asyncio.wait_for(proc.wait(), timeout=5.0)
                except asyncio.TimeoutError:
                    # degrade, never hang: an unkillable (D-state) child
                    # is the kernel's problem, not the shutdown path's
                    log.error("gateway worker pid %d ignored SIGKILL",
                              proc.pid)
        if self._placeholder is not None:
            self._placeholder.close()
            self._placeholder = None
        if self._spec_path is not None:
            path = self._spec_path
            self._spec_path = None
            await asyncio.to_thread(self._unlink_quiet, path)
        if self.metrics_spool is not None:
            spool = self.metrics_spool
            self.metrics_spool = None
            await asyncio.to_thread(shutil.rmtree, spool, True)

    # ---- internals ----

    @staticmethod
    def _unlink_quiet(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def _write_spec(self) -> str:
        """The worker spec file: cluster definition + serve parameters,
        JSON (``Cluster.to_obj`` round-trips through plain types).  One
        file serves every (re)spawn; removed at stop."""
        fd, path = tempfile.mkstemp(prefix="cb-gateway-",
                                    suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump({
                "cluster": self.cluster_obj,
                "host": self.host,
                "port": self.port,
                "serve": self.serve_params,
            }, f)
        return path

    def _child_env(self) -> dict:
        """Child env: inherited, plus the package root on PYTHONPATH so
        ``-m chunky_bits_tpu.gateway.workers`` resolves however the
        parent imported the package."""
        import chunky_bits_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(chunky_bits_tpu.__file__)))
        env = dict(os.environ)
        prior = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = (pkg_root + os.pathsep + prior
                             if prior else pkg_root)
        return env

    async def _run_slot(self, i: int) -> None:
        """One worker slot: spawn, wait for readiness, watch for death,
        respawn with capped backoff.  The slot never gives up while the
        supervisor lives — with other workers healthy the listener
        stays responsive through any one slot's crash loop."""
        backoff = _BACKOFF_INITIAL
        while not self._stopping:
            spawned_at = time.monotonic()
            try:
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m",
                    "chunky_bits_tpu.gateway.workers", self._spec_path,
                    stdout=asyncio.subprocess.PIPE,
                    env=self._child_env())
            except OSError as err:
                log.error("gateway worker %d spawn failed: %s", i, err)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, _BACKOFF_CAP)
                continue
            self._procs[i] = proc
            ok = await self._await_ready(proc)
            if ok:
                self._ready[i] = True
                drain = asyncio.ensure_future(self._drain(proc))
                self._drain_tasks[proc.pid] = drain
            else:
                log.error("gateway worker %d (pid %d) never reported "
                          "ready", i, proc.pid)
                try:
                    proc.terminate()
                except ProcessLookupError:
                    pass
            rc = await self._wait_exit(proc)
            self._drain_tasks.pop(proc.pid, None)
            # reap the dead worker's spool snapshot: the fleet /metrics
            # view must report who is ALIVE — a crashed worker's frozen
            # gauges (in-flight counts, worker_up) must not haunt every
            # scrape until supervisor stop.  Its counters drop out of
            # the fleet totals, which Prometheus-style consumers treat
            # as an ordinary counter reset.
            if self.metrics_spool is not None:
                await asyncio.to_thread(
                    self._unlink_quiet,
                    os.path.join(self.metrics_spool,
                                 f"worker-{proc.pid}.json"))
            if self._stopping:
                return
            uptime = time.monotonic() - spawned_at
            if uptime >= _BACKOFF_RESET_UPTIME:
                backoff = _BACKOFF_INITIAL
            log.warning("gateway worker %d (pid %d) exited rc=%s after "
                        "%.1fs; respawning in %.1fs", i, proc.pid, rc,
                        uptime, backoff)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, _BACKOFF_CAP)

    async def _await_ready(self, proc) -> bool:
        """Bounded readiness handshake: scan the child's stdout for the
        READY marker.  False on exit/EOF/timeout."""
        deadline = time.monotonic() + self.ready_timeout
        while time.monotonic() < deadline:
            try:
                line = await asyncio.wait_for(proc.stdout.readline(),
                                              timeout=1.0)
            except asyncio.TimeoutError:
                if proc.returncode is not None:
                    return False
                continue
            if not line:
                return False
            if line.decode(errors="replace").startswith(READY_MARKER):
                return True
        return False

    async def _drain(self, proc) -> None:
        """Keep the child's stdout pipe from filling after readiness;
        post-READY chatter is relayed to the supervisor log."""
        while True:
            try:
                line = await asyncio.wait_for(proc.stdout.readline(),
                                              timeout=60.0)
            except asyncio.TimeoutError:
                continue
            if not line:
                return
            log.debug("worker pid %d: %s", proc.pid,
                      line.decode(errors="replace").rstrip())

    async def _wait_exit(self, proc) -> Optional[int]:
        """Bounded-poll wait for a worker's exit (the CB101-friendly
        shape of ``await proc.wait()``); returns its exit code."""
        while True:
            try:
                return await asyncio.wait_for(proc.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                if self._stopping:
                    return proc.returncode
                continue


async def serve_workers(cluster, host: str, port: int, workers: int,
                        on_ready: Optional[Callable[[int], None]] = None,
                        **serve_params) -> None:
    """The ``serve(..., workers=N>1)`` body: run a supervisor until
    cancelled (ctrl-c), then tear the fleet down."""
    sup = GatewaySupervisor(cluster.to_obj(), host, port, workers,
                            serve_params=serve_params)
    await sup.start()
    print(f"listening on http://{host}:{sup.port} "
          f"({workers} workers)", flush=True)
    if on_ready is not None:
        on_ready(sup.port)
    try:
        # lint: unbounded-await-ok the serve park itself (internally a
        # bounded-sleep loop); resolves on ctrl-c cancellation exactly
        # like single-process serve's sleep loop
        await sup.wait()
    # lint: cancel-safety-ok ctrl-c/cancel IS the shutdown signal for
    # the supervisor park; swallowing it hands control to the finally's
    # graceful fleet teardown (sup.stop) before exit
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await sup.stop()


# ---- worker child entry (`python -m chunky_bits_tpu.gateway.workers`) ----


async def _worker_amain(spec: dict) -> None:
    from chunky_bits_tpu.cluster import Cluster
    from chunky_bits_tpu.gateway.http import HealthState, serve

    cluster = Cluster.from_obj(spec["cluster"])
    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    health_state = HealthState()

    def request_stop() -> None:
        # flip /healthz to draining FIRST: a balancer polling it stops
        # routing before the listener actually goes away
        health_state.draining = True
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, request_stop)
        except (NotImplementedError, RuntimeError):
            pass  # non-unix / nested-loop harnesses: supervisor kills

    def announce(bound_port: int) -> None:
        print(f"{READY_MARKER} port={bound_port} pid={os.getpid()}",
              flush=True)

    # lint: task-custody-ok cancelled-and-awaited in the finally below;
    # the only statement before the try is ensure_future(stop.wait()),
    # which cannot raise
    serve_task = asyncio.ensure_future(serve(
        cluster, host=spec["host"], port=spec["port"], workers=1,
        reuse_port=True, on_ready=announce,
        health_state=health_state, **spec.get("serve", {})))
    stop_task = asyncio.ensure_future(stop.wait())
    try:
        # lint: unbounded-await-ok the worker's lifetime IS the service
        # lifetime: this resolves on SIGTERM (stop_task) or a serve
        # crash (serve_task), and the supervisor escalates to SIGKILL
        await asyncio.wait({serve_task, stop_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if stop.is_set() and not serve_task.done():
            # drain window: /healthz already answers 503 draining —
            # give in-flight requests (and one balancer poll) a beat
            # before the listener is torn down
            # lint: unbounded-await-ok bounded by timeout=_DRAIN_SECONDS
            # (0.5 s), well under the supervisor's SIGKILL escalation
            await asyncio.wait({serve_task}, timeout=_DRAIN_SECONDS)
    finally:
        serve_task.cancel()
        stop_task.cancel()
        await asyncio.gather(serve_task, stop_task,
                             return_exceptions=True)
        await cluster.tunables.location_context().aclose()
    # surface a serve crash as a nonzero exit so the supervisor logs it
    if serve_task.cancelled():
        return
    err = serve_task.exception()
    if err is not None:
        raise err


def worker_main(argv: Optional[list] = None) -> int:
    """Child entry: load the spec, build this worker's own Cluster
    (partitioned cache/health/pipeline — see the module docstring), and
    serve single-process with ``reuse_port=True``."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m chunky_bits_tpu.gateway.workers "
              "<spec.json>", file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.WARNING, stream=sys.stderr)
    with open(argv[0]) as f:
        spec = json.load(f)
    try:
        asyncio.run(_worker_amain(spec))
    except KeyboardInterrupt:
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
