"""Gateway adapter for the multi-tenant QoS scheduler.

The HTTP-shaped half of the QoS plane: tenant resolution from the
request (API key header first, path prefix second — the order
``QosConfig.resolve`` fixes) and the conditional scheduler build the
gateway runs at ``make_app`` time.  The scheduler itself lives in
``cluster/qos.py`` (clock-seam timed, HTTP-free) so the deterministic
simulator can drive the SAME admission machinery in virtual time
(scenario ``noisy_neighbor``).

Zero overhead off: ``maybe_build`` returns None unless the YAML
``qos.enabled`` is true or (when the YAML leaves it unset)
``$CHUNKY_BITS_TPU_QOS`` is on — the None path costs one attribute
check per request, same discipline as the SLO engine.
"""

from __future__ import annotations

from typing import Optional

from chunky_bits_tpu.cluster.qos import QosConfig, QosScheduler

__all__ = ["TENANT_HEADER", "maybe_build", "resolve_request_tenant"]

#: the API-key header tenants authenticate with; resolution falls back
#: to path prefixes, then the ``other`` bucket (closed table — an
#: unknown or rotating key can never mint a tenant)
TENANT_HEADER = "X-Api-Key"


def resolve_request_tenant(config: QosConfig, request) -> str:
    """Tenant name for an aiohttp request (total: always returns a
    name from the closed table)."""
    return config.resolve(request.headers.get(TENANT_HEADER),
                          request.path)


def maybe_build(cluster, *, read_capacity: int,
                write_capacity: int) -> Optional[QosScheduler]:
    """Build the per-worker scheduler iff QoS is on: YAML
    ``qos.enabled`` wins; absent, the env flag
    (``tunables.qos_enabled``, rule CB102) decides.  Read/write
    capacities are the gateway's existing concurrency bounds so
    QoS-on changes WHO queues, never how much runs."""
    from chunky_bits_tpu.cluster import tunables as _tunables

    config = QosConfig.from_obj(cluster.tunables.qos or {})
    enabled = (config.enabled if config.enabled is not None
               else _tunables.qos_enabled())
    if not enabled:
        return None
    objective_ms = 500.0
    slo_obj = getattr(cluster.tunables, "slo", None)
    if slo_obj:
        # the hedge advisor targets the SAME read-p99 objective the
        # SLO engine alerts on — one number, two consumers
        objective_ms = float(slo_obj.get("read_p99_ms", 500.0))
    return QosScheduler(
        config,
        read_capacity=read_capacity,
        write_capacity=write_capacity,
        read_p99_objective_ms=objective_ms)
