"""HTTP object gateway over one cluster.

Mirrors src/http.rs: GET/HEAD stream a file out of the cluster with full
single-range support (Range/Prefix/Suffix -> seek/take; 206 + Content-Range;
416 on unsatisfiable; :27-95); Content-Length and Content-Type headers
(:77-81); 404 on metadata miss (:86-89); PUT streams the body through
``write_file`` with the default profile, capturing Content-Type (:97-118).

Deviations, documented: the reference's ``bytes=a-b`` handler reads
``b - a`` bytes (an off-by-one against RFC 9110 inclusive ranges,
http.rs:40-42) and emits a Content-Range without the ``bytes `` unit; both
are corrected here.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from aiohttp import web

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.errors import ChunkyBitsError, MetadataReadError
from chunky_bits_tpu.file import FileReadBuilder


class HttpRangeError(ValueError):
    pass


def parse_http_range(s: str):
    """Parse a single ``bytes=`` range header (http.rs:151-220).
    Returns ("range", start, end_inclusive) | ("prefix", start) |
    ("suffix", length)."""
    unit, sep, spec = s.partition("=")
    if not sep:
        raise HttpRangeError("invalid format")
    if unit != "bytes":
        raise HttpRangeError("unknown unit")
    if "," in spec:
        raise HttpRangeError("multi-range not supported")
    start_s, sep, end_s = spec.partition("-")
    if not sep:
        raise HttpRangeError("invalid format")
    try:
        start = int(start_s) if start_s else None
        end = int(end_s) if end_s else None
    except ValueError as err:
        raise HttpRangeError("invalid integer") from err
    if start is not None and end is not None:
        if start > end:
            raise HttpRangeError("invalid length")
        return ("range", start, end)
    if start is not None:
        return ("prefix", start)
    if end is not None:
        return ("suffix", end)
    raise HttpRangeError("no range specified")


def make_app(cluster: Cluster) -> web.Application:
    cx = cluster.tunables.location_context()

    async def handle_get(request: web.Request) -> web.StreamResponse:
        path = request.match_info["path"]
        try:
            file_ref = await cluster.get_file_ref(path)
        except MetadataReadError:
            return web.Response(status=404)
        except ChunkyBitsError:
            return web.Response(status=500)
        builder = FileReadBuilder(file_ref).location_context(cx)
        status = 200
        headers = {}
        range_header = request.headers.get("Range")
        parsed = None
        if range_header is not None:
            try:
                parsed = parse_http_range(range_header)
            except HttpRangeError:
                # RFC 9110: an unparseable/unknown-unit/multi-range header
                # is ignored, not rejected; 416 is only for unsatisfiable
                # ranges.
                parsed = None
        if parsed is not None:
            total = file_ref.len_bytes()
            if parsed[0] == "range":
                _, start, end = parsed
                builder = builder.with_seek(start).with_take(end - start + 1)
            elif parsed[0] == "prefix":
                builder = builder.with_seek(parsed[1])
            else:  # suffix
                length = parsed[1]
                if length > total:
                    return web.Response(status=416)
                builder = builder.with_seek(total - length).with_take(length)
            if builder.len_bytes() == 0:
                return web.Response(status=416)
            seek = builder.seek
            end_excl = seek + builder.len_bytes()
            headers["Content-Range"] = \
                f"bytes {seek}-{end_excl - 1}/{total}"
            status = 206
        headers["Content-Length"] = str(builder.len_bytes())
        if file_ref.content_type:
            headers["Content-Type"] = file_ref.content_type
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        if request.method == "HEAD":
            return resp
        async for chunk in builder.stream():
            await resp.write(chunk)
        await resp.write_eof()
        return resp

    async def handle_put(request: web.Request) -> web.Response:
        path = request.match_info["path"]
        profile = cluster.get_profile(None)
        content_type: Optional[str] = request.headers.get("Content-Type")

        class _BodyReader:
            async def read(self, n: int = -1) -> bytes:
                if n < 0:
                    return await request.content.read()
                return await request.content.read(n)

        try:
            await cluster.write_file(
                path, _BodyReader(), profile, content_type)
        except ChunkyBitsError:
            return web.Response(status=500)
        return web.Response(status=200)

    app = web.Application()
    app.router.add_get("/{path:.*}", handle_get)  # also serves HEAD
    app.router.add_put("/{path:.*}", handle_put)
    return app


async def serve(cluster: Cluster, host: str = "127.0.0.1",
                port: int = 8000) -> None:
    """Bind and serve until cancelled (ctrl-c graceful shutdown,
    main.rs:474-485)."""
    runner = web.AppRunner(make_app(cluster))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    print(f"listening on http://{host}:{port}")
    try:
        while True:
            await asyncio.sleep(3600)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await runner.cleanup()
