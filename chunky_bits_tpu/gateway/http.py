"""HTTP object gateway over one cluster.

Mirrors src/http.rs: GET/HEAD stream a file out of the cluster with full
single-range support (Range/Prefix/Suffix -> seek/take; 206 + Content-Range;
416 on unsatisfiable; :27-95); Content-Length and Content-Type headers
(:77-81); 404 on metadata miss (:86-89); PUT streams the body through
``write_file`` with the default profile, capturing Content-Type (:97-118).

Deviations, documented: the reference's ``bytes=a-b`` handler reads
``b - a`` bytes (an off-by-one against RFC 9110 inclusive ranges,
http.rs:40-42) and emits a Content-Range without the ``bytes `` unit; both
are corrected here.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from typing import Optional

from aiohttp import web

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.errors import ChunkyBitsError, MetadataReadError
from chunky_bits_tpu.utils import aio

log = logging.getLogger("chunky_bits_tpu.gateway")

#: default bound on concurrent PUT ingests; excess requests queue.  The
#: reference accepts unbounded concurrent ingests (http.rs:97-118) — a
#: bound is a deliberate hardening for the one component facing
#: untrusted clients.
DEFAULT_MAX_CONCURRENT_PUTS = 32

#: a PUT slower than this average (bytes/sec, measured after a grace
#: window) is aborted with 408: with bounded concurrent ingests, a
#: trickling client would otherwise hold a slot forever (slow-loris).
#: 0 disables the floor.
DEFAULT_MIN_PUT_RATE = 256
_RATE_GRACE_SECONDS = 30.0


class HttpRangeError(ValueError):
    pass


class _BodyTooLarge(ChunkyBitsError):
    pass


class _BodyTooSlow(ChunkyBitsError):
    pass


class _GuardedBody(aio.CountingReader):
    """Request-body reader enforcing the PUT limits: byte cap (via
    CountingReader) and a minimum average ingest rate.

    The rate floor is a deadline, not a post-read check: each read is
    bounded by the time left until the cumulative average would drop
    below ``min_rate``, so a client that sends *nothing at all* (aiohttp
    has no default body-read timeout) also trips it instead of pinning a
    PUT slot forever."""

    def __init__(self, content, max_bytes: Optional[int],
                 min_rate: int):
        super().__init__(content, max_bytes=max_bytes,
                         exc_factory=_BodyTooLarge)
        self._min_rate = min_rate
        self._started = time.monotonic()

    async def read(self, n: int = -1) -> bytes:
        if self._min_rate <= 0:
            return await super().read(n)
        # Two floors: the cumulative average must stay >= min_rate once
        # past the grace window (anti-trickle), and no single read may
        # stall longer than the grace window (anti burst-then-stall — a
        # client must not bank unbounded credit by front-loading bytes).
        avg_deadline = (self._started + _RATE_GRACE_SECONDS
                        + self.total / self._min_rate)
        timeout = min(_RATE_GRACE_SECONDS,
                      avg_deadline - time.monotonic())
        if timeout <= 0:
            raise _BodyTooSlow(f"ingest below {self._min_rate} B/s")
        try:
            return await asyncio.wait_for(super().read(n), timeout)
        except asyncio.TimeoutError:
            raise _BodyTooSlow(
                f"ingest below {self._min_rate} B/s") from None


def parse_http_range(s: str):
    """Parse a single ``bytes=`` range header (http.rs:151-220).
    Returns ("range", start, end_inclusive) | ("prefix", start) |
    ("suffix", length)."""
    unit, sep, spec = s.partition("=")
    if not sep:
        raise HttpRangeError("invalid format")
    if unit != "bytes":
        raise HttpRangeError("unknown unit")
    if "," in spec:
        raise HttpRangeError("multi-range not supported")
    start_s, sep, end_s = spec.partition("-")
    if not sep:
        raise HttpRangeError("invalid format")
    try:
        start = int(start_s) if start_s else None
        end = int(end_s) if end_s else None
    except ValueError as err:
        raise HttpRangeError("invalid integer") from err
    if start is not None and end is not None:
        if start > end:
            raise HttpRangeError("invalid length")
        return ("range", start, end)
    if start is not None:
        return ("prefix", start)
    if end is not None:
        return ("suffix", end)
    raise HttpRangeError("no range specified")


def make_app(cluster: Cluster,
             max_put_bytes: Optional[int] = None,
             max_concurrent_puts: int = DEFAULT_MAX_CONCURRENT_PUTS,
             min_put_rate: int = DEFAULT_MIN_PUT_RATE
             ) -> web.Application:
    # <=0 means unbounded, like the reference's ingest (and matching
    # min_put_rate's "0 disables" convention)
    put_sem = (asyncio.Semaphore(max_concurrent_puts)
               if max_concurrent_puts > 0 else contextlib.nullcontext())

    # PUT ingest compute (per-shard SHA-256 + per-stripe GF encode) runs
    # on the cluster's host pipeline workers, so the event loop's socket
    # receive overlaps encode+hash on every scheduler core instead of
    # sharing one thread with it.  Resolve (and thereby spawn) the
    # workers now: the first request shouldn't pay the warm-up, and a
    # misconfigured tunables.host_threads should fail at serve start,
    # not mid-ingest.
    cluster.host_pipeline()

    # Every GET/PUT of this app feeds the cluster's ONE location-health
    # scoreboard (cluster/health.py) through the shared LocationContext
    # — concurrent requests therefore share latency/error memory and
    # the hedge budget, the serve-path analogue of the shared encode
    # batcher.  On failures the per-node table goes to the log so a
    # degraded cluster is diagnosable from the gateway side alone.
    health = cluster.health_scoreboard()

    async def handle_get(request: web.Request) -> web.StreamResponse:
        path = request.match_info["path"]
        try:
            file_ref = await cluster.get_file_ref(path)
        except MetadataReadError:
            return web.Response(status=404)
        except ChunkyBitsError as err:
            # detail goes to the log only: error text can embed internal
            # node URLs / filesystem paths untrusted clients must not see
            log.error("GET %s failed: %s", path, err)
            return web.Response(status=500, text="error: internal error\n")
        # the cluster's serve-path builder: per-loop shared reconstruct
        # batcher (concurrent degraded GETs coalesce their decode
        # dispatches) and, when `tunables.cache_bytes` is set, the
        # content-addressed chunk cache.  Range requests ride the same
        # path: the cache only ever holds whole verified chunks — the
        # seek/take trim below happens at the edge, after the cache.
        builder = cluster.file_read_builder(file_ref)
        status = 200
        headers = {}
        range_header = request.headers.get("Range")
        parsed = None
        if range_header is not None:
            try:
                parsed = parse_http_range(range_header)
            except HttpRangeError:
                # RFC 9110: an unparseable/unknown-unit/multi-range header
                # is ignored, not rejected; 416 is only for unsatisfiable
                # ranges.
                parsed = None
        if parsed is not None:
            total = file_ref.len_bytes()
            if parsed[0] == "range":
                _, start, end = parsed
                builder = builder.with_seek(start).with_take(end - start + 1)
            elif parsed[0] == "prefix":
                builder = builder.with_seek(parsed[1])
            else:  # suffix
                # RFC 9110 §14.1.2: a suffix length >= the representation
                # length selects the ENTIRE representation (it is
                # satisfiable), so clamp rather than 416
                length = min(parsed[1], total)
                builder = builder.with_seek(total - length).with_take(length)
            if builder.len_bytes() == 0:
                return web.Response(status=416)
            seek = builder.seek
            end_excl = seek + builder.len_bytes()
            headers["Content-Range"] = \
                f"bytes {seek}-{end_excl - 1}/{total}"
            status = 206
        headers["Content-Length"] = str(builder.len_bytes())
        if file_ref.content_type:
            headers["Content-Type"] = file_ref.content_type
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        if request.method == "HEAD":
            return resp
        try:
            async for chunk in builder.stream():
                await resp.write(chunk)
        except ChunkyBitsError as err:
            # Degraded beyond repair (>p chunks gone) or a storage-node
            # failure mid-file.  Status and Content-Length are already on
            # the wire, so the only honest signal left is an aborted
            # connection — the client sees a short body, never a clean
            # EOF that would pass truncated data off as the object.
            # Detail goes to the log only (error text can embed internal
            # node URLs / filesystem paths).
            log.error("GET %s aborted mid-stream: %s", path, err)
            log.error("location health at abort: %s", health.stats())
            resp.force_close()
            if request.transport is not None:
                request.transport.close()
            return resp
        await resp.write_eof()
        return resp

    def put_reject(status: int, text: str) -> web.Response:
        """An error response for a PUT whose body was not (fully) read.
        The connection is force-closed: answering early and then reusing
        the keep-alive stream leaves the unread body bytes in front of
        the next request's head — observed as the follow-up request
        hanging forever against aiohttp 3.11's client, which returns the
        half-sent connection to its pool once the early response lands."""
        resp = web.Response(status=status, text=text)
        resp.force_close()
        return resp

    async def handle_put(request: web.Request) -> web.Response:
        path = request.match_info["path"]
        profile = cluster.get_profile(None)
        content_type: Optional[str] = request.headers.get("Content-Type")

        if max_put_bytes is not None:
            declared = request.headers.get("Content-Length")
            if declared is not None and int(declared) > max_put_bytes:
                return put_reject(413, "error: body too large\n")

        # A rejected/aborted ingest can leave orphaned shards; they are
        # content-addressed (possibly shared with other files), so they
        # are left for the reference-checking find-unused-hashes GC
        # rather than deleted blindly.
        async with put_sem:
            try:
                await cluster.write_file(
                    path,
                    _GuardedBody(request.content, max_put_bytes,
                                 min_put_rate),
                    profile, content_type)
            except _BodyTooLarge:
                return put_reject(413, "error: body too large\n")
            except _BodyTooSlow:
                return put_reject(408, "error: ingest too slow\n")
            except ChunkyBitsError as err:
                log.error("PUT %s failed: %s", path, err)
                log.error("location health at failure: %s",
                          health.stats())
                return put_reject(500, "error: internal error\n")
        return web.Response(status=200)

    app = web.Application()
    app.router.add_get("/{path:.*}", handle_get)  # also serves HEAD
    app.router.add_put("/{path:.*}", handle_put)
    return app


async def serve(cluster: Cluster, host: str = "127.0.0.1",
                port: int = 8000,
                max_put_bytes: Optional[int] = None,
                max_concurrent_puts: int = DEFAULT_MAX_CONCURRENT_PUTS,
                min_put_rate: int = DEFAULT_MIN_PUT_RATE
                ) -> None:
    """Bind and serve until cancelled (ctrl-c graceful shutdown,
    main.rs:474-485)."""
    from chunky_bits_tpu.cluster.tunables import sanitize_enabled

    if sanitize_enabled():
        # opt-in runtime concurrency sanitizer: instrument the serving
        # loop (stall watchdog + task registry) — read here, at the one
        # moment the gateway's loop is known, like every other
        # first-use tunable
        from chunky_bits_tpu.analysis.sanitizer import get_monitor

        get_monitor().instrument_loop(asyncio.get_running_loop())
    runner = web.AppRunner(
        make_app(cluster, max_put_bytes=max_put_bytes,
                 max_concurrent_puts=max_concurrent_puts,
                 min_put_rate=min_put_rate))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    print(f"listening on http://{host}:{port}")
    try:
        while True:
            await asyncio.sleep(3600)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await runner.cleanup()
