"""HTTP object gateway over one cluster.

Mirrors src/http.rs: GET/HEAD stream a file out of the cluster with full
single-range support (Range/Prefix/Suffix -> seek/take; 206 + Content-Range;
416 on unsatisfiable; :27-95); Content-Length and Content-Type headers
(:77-81); 404 on metadata miss (:86-89); PUT streams the body through
``write_file`` with the default profile, capturing Content-Type (:97-118).

Deviations, documented: the reference's ``bytes=a-b`` handler reads
``b - a`` bytes (an off-by-one against RFC 9110 inclusive ranges,
http.rs:40-42) and emits a Content-Range without the ``bytes `` unit; both
are corrected here.

Serving-plane extensions beyond the reference (the scale-out surface —
src/http.rs has none of these):

- **Conditional GETs.**  Every GET/HEAD answer carries a strong ``ETag``
  derived from the file reference (the content-addressed chunk digests:
  same bytes => same reference => same tag); ``If-None-Match`` hits
  answer 304 with zero body bytes, so repeat readers of unchanged
  objects cost one metadata read.
- **Zero-copy local-chunk streaming.**  A requested range covered by ONE
  data chunk with a verified local replica — a whole chunk file OR a
  live extent inside a packed slab (file/slab.py) — streams via
  ``loop.sendfile`` (page cache -> socket, no userspace copy),
  bypassing the whole fetch/verify/reassemble pipeline; verification
  digests are memoized per (path, offset, length) extent: whole chunk
  files validate by (size, mtime_ns) token (content-addressed, replaced
  only by atomic rename — a stale entry is impossible without an mtime
  change), slab extents by the journaled extent itself (write-once
  bytes; compaction republishes under a new slab path).
  ``tunables.gateway_sendfile`` / ``$CHUNKY_BITS_TPU_GATEWAY_SENDFILE``
  disables it (bench --config 9 is the A/B).
- **Admission control.**  In-flight GET bodies are bounded
  (``max_concurrent_gets``); excess requests get an immediate
  503 + ``Retry-After`` instead of queueing into memory — the read-side
  sibling of the PUT semaphore below.
- **Access log.**  One structured line per request (method, path,
  status, bytes, wall ms, serving source) through the app's
  ``Profiler.log_request``, so production logs and bench --config 9
  percentiles come from the same counters
  (file/profiler.py::request_stats).

Multi-worker serving (``serve(..., workers=N)``) lives in
gateway/workers.py: N pre-forked SO_REUSEPORT processes, each running
this module's app on its own loop.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import logging
import os
import time
from collections import deque
from typing import Callable, Optional

from aiohttp import web

from chunky_bits_tpu.cluster import Cluster
from chunky_bits_tpu.errors import ChunkyBitsError, MetadataReadError
from chunky_bits_tpu.file.file_reference import FileReference
from chunky_bits_tpu.file.profiler import (Profiler, request_stats,
                                           tenant_request_stats)
from chunky_bits_tpu.obs import metrics as obs_metrics
from chunky_bits_tpu.obs import tracing as obs_tracing
from chunky_bits_tpu.utils import aio

log = logging.getLogger("chunky_bits_tpu.gateway")

#: default bound on concurrent PUT ingests; excess requests queue.  The
#: reference accepts unbounded concurrent ingests (http.rs:97-118) — a
#: bound is a deliberate hardening for the one component facing
#: untrusted clients.
DEFAULT_MAX_CONCURRENT_PUTS = 32

#: a PUT slower than this average (bytes/sec, measured after a grace
#: window) is aborted with 408: with bounded concurrent ingests, a
#: trickling client would otherwise hold a slot forever (slow-loris).
#: 0 disables the floor.
DEFAULT_MIN_PUT_RATE = 256
_RATE_GRACE_SECONDS = 30.0

#: default bound on in-flight GET bodies per worker; the 257th
#: concurrent reader gets 503 + Retry-After instead of a queue slot.
#: Unlike the PUT semaphore (which queues — an ingest carries client
#: bytes that would be lost), reads are idempotent and retryable, so
#: shedding beats buffering.  <=0 = unbounded.
DEFAULT_MAX_CONCURRENT_GETS = 256

#: Retry-After fallback on a shed GET when no completion-rate signal
#: exists yet (cold worker) — short: a slot frees as soon as any
#: in-flight body finishes.  With traffic observed, the header is
#: DERIVED per shed: expected wait ≈ waiting requests over the recent
#: GET completion rate (see ``_retry_after`` in make_app), clamped to
#: [1, _RETRY_AFTER_MAX] so clients back off proportionally to the
#: actual queue instead of hammering a saturated worker every second.
_RETRY_AFTER_SECONDS = "1"

#: Retry-After derivation bounds: completion timestamps remembered
#: (rate window) and the clamp ceiling in seconds
_RETRY_AFTER_WINDOW = 64
_RETRY_AFTER_MAX = 30

#: bound on the (path, size, mtime_ns) -> verified-digest memo feeding
#: the sendfile fast path; oldest entries drop past this (FIFO — a
#: dropped entry only costs one re-verify)
_VERIFIED_MEMO_ENTRIES = 4096

#: the app's request-log profiler (``make_app`` stores it here; tests
#: and bench read percentiles off it)
PROFILER_KEY: web.AppKey = web.AppKey("cb_profiler", Profiler)

#: the app's liveness/readiness state (``GET /healthz`` reads it; the
#: worker child flips ``draining`` on SIGTERM)
HEALTH_KEY: web.AppKey = web.AppKey("cb_health", object)

#: seconds between per-worker snapshot publications into the fleet
#: metrics spool (gateway/workers.py) — the staleness bound on OTHER
#: workers' series in an aggregated /metrics scrape (the scraped
#: worker's own series are always live)
_SPOOL_INTERVAL = 2.0


class HealthState:
    """Per-worker liveness/readiness: ``draining`` flips once shutdown
    has been requested, so a load balancer polling ``/healthz`` stops
    routing to this worker before its listener actually closes."""

    def __init__(self) -> None:
        self.started = time.monotonic()
        self.draining = False


class HttpRangeError(ValueError):
    pass


class _BodyTooLarge(ChunkyBitsError):
    pass


class _BodyTooSlow(ChunkyBitsError):
    pass


class _GuardedBody(aio.CountingReader):
    """Request-body reader enforcing the PUT limits: byte cap (via
    CountingReader) and a minimum average ingest rate.

    The rate floor is a deadline, not a post-read check: each read is
    bounded by the time left until the cumulative average would drop
    below ``min_rate``, so a client that sends *nothing at all* (aiohttp
    has no default body-read timeout) also trips it instead of pinning a
    PUT slot forever."""

    def __init__(self, content, max_bytes: Optional[int],
                 min_rate: int):
        super().__init__(content, max_bytes=max_bytes,
                         exc_factory=_BodyTooLarge)
        self._min_rate = min_rate
        self._started = time.monotonic()

    async def read(self, n: int = -1) -> bytes:
        if self._min_rate <= 0:
            return await super().read(n)
        # Two floors: the cumulative average must stay >= min_rate once
        # past the grace window (anti-trickle), and no single read may
        # stall longer than the grace window (anti burst-then-stall — a
        # client must not bank unbounded credit by front-loading bytes).
        avg_deadline = (self._started + _RATE_GRACE_SECONDS
                        + self.total / self._min_rate)
        timeout = min(_RATE_GRACE_SECONDS,
                      avg_deadline - time.monotonic())
        if timeout <= 0:
            raise _BodyTooSlow(f"ingest below {self._min_rate} B/s")
        try:
            return await asyncio.wait_for(super().read(n), timeout)
        except asyncio.TimeoutError:
            raise _BodyTooSlow(
                f"ingest below {self._min_rate} B/s") from None


def parse_http_range(s: str):
    """Parse a single ``bytes=`` range header (http.rs:151-220).
    Returns ("range", start, end_inclusive) | ("prefix", start) |
    ("suffix", length)."""
    unit, sep, spec = s.partition("=")
    if not sep:
        raise HttpRangeError("invalid format")
    if unit != "bytes":
        raise HttpRangeError("unknown unit")
    if "," in spec:
        raise HttpRangeError("multi-range not supported")
    start_s, sep, end_s = spec.partition("-")
    if not sep:
        raise HttpRangeError("invalid format")
    try:
        start = int(start_s) if start_s else None
        end = int(end_s) if end_s else None
    except ValueError as err:
        raise HttpRangeError("invalid integer") from err
    if start is not None and end is not None:
        if start > end:
            raise HttpRangeError("invalid length")
        return ("range", start, end)
    if start is not None:
        return ("prefix", start)
    if end is not None:
        return ("suffix", end)
    raise HttpRangeError("no range specified")


def file_ref_etag(file_ref: FileReference) -> str:
    """Strong ETag for a file reference: sha256 over its CONTENT
    identity — length, content type, and every chunk's content digest
    per part — quoted per RFC 9110.  Locations are deliberately
    excluded: a resilver or rebalance rewrites placement for unchanged
    bytes, and a placement change must not invalidate every client's
    cached validator (nor let two workers with differently-aged
    metadata caches serve different tags for the same bytes).  Chunk
    digests are content-addressed, so equal tags imply byte-identical
    objects across workers and restarts.  Memoized on the ref object:
    the cluster's metadata cache hands the same parsed instance to
    every hot GET."""
    cached = getattr(file_ref, "_gateway_etag", None)
    if cached is not None:
        return cached
    canon = json.dumps({
        "length": file_ref.length,
        "content_type": file_ref.content_type,
        "compression": file_ref.compression,
        "parts": [
            {"chunksize": part.chunksize,
             "data": [str(c.hash) for c in part.data],
             "parity": [str(c.hash) for c in part.parity]}
            for part in file_ref.parts
        ],
    }, sort_keys=True, separators=(",", ":"))
    etag = f'"{hashlib.sha256(canon.encode()).hexdigest()[:32]}"'
    file_ref._gateway_etag = etag
    return etag


def _if_none_match_hits(header: Optional[str], etag: str) -> bool:
    """True when an ``If-None-Match`` header matches ``etag`` (RFC 9110
    §13.1.2: ``*`` matches anything; weak comparison, so a ``W/`` prefix
    on the client's copy still hits)."""
    if header is None:
        return False
    for token in header.split(","):
        token = token.strip()
        if token == "*":
            return True
        if token.startswith("W/"):
            token = token[2:]
        if token == etag:
            return True
    return False


def _covering_chunk(file_ref: FileReference, seek: int, length: int):
    """(chunk, chunksize, offset_in_chunk) when the byte span
    [seek, seek+length) lies inside ONE data chunk of one part — the
    precondition for serving it straight off a local chunk file — else
    None.  Parity chunks never qualify (their bytes are not file
    bytes), nor do spans crossing a chunk or part boundary."""
    from chunky_bits_tpu.ops.backend import KNOWN_CODES

    part_off = 0
    for part in file_ref.parts:
        part_len = part.len_bytes()
        if seek < part_off + part_len:
            if seek + length > part_off + part_len:
                return None  # spans parts
            if part.code not in KNOWN_CODES:
                # a foreign code could be non-systematic — raw chunk
                # bytes may not be file bytes, so the generic path must
                # raise its clean per-part error instead of sendfile
                # serving a guess (file_part.require_known_code)
                return None
            local = seek - part_off
            csize = part.chunksize
            if csize <= 0:
                return None
            idx = local // csize
            if idx >= len(part.data):
                return None
            if local + length > (idx + 1) * csize:
                return None  # spans chunks
            return part.data[idx], csize, local - idx * csize
        part_off += part_len
    return None


def _sha256_extent(path: str, offset: int,
                   length: Optional[int]) -> bytes:
    """Streaming sha256 of a file extent (the sendfile verify fallback
    when the native fused hasher is unavailable); ``length`` None hashes
    to EOF.  Runs on the host pipeline's workers, never the loop."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        f.seek(offset)
        remaining = length
        while remaining is None or remaining > 0:
            n = 1 << 20 if remaining is None else min(1 << 20, remaining)
            data = f.read(n)
            if not data:
                break
            if remaining is not None:
                remaining -= len(data)
            h.update(data)
    return h.digest()


def make_app(cluster: Cluster,
             max_put_bytes: Optional[int] = None,
             max_concurrent_puts: int = DEFAULT_MAX_CONCURRENT_PUTS,
             min_put_rate: int = DEFAULT_MIN_PUT_RATE,
             max_concurrent_gets: int = DEFAULT_MAX_CONCURRENT_GETS,
             sendfile: Optional[bool] = None,
             profiler: Optional[Profiler] = None,
             scrub=None,
             metrics_spool: Optional[str] = None,
             health_state: Optional[HealthState] = None
             ) -> web.Application:
    # <=0 means unbounded, like the reference's ingest (and matching
    # min_put_rate's "0 disables" convention)
    put_sem = (asyncio.Semaphore(max_concurrent_puts)
               if max_concurrent_puts > 0 else contextlib.nullcontext())

    # the process metrics registry: the durable sink every stat source
    # in this app feeds (obs/metrics.py) — /metrics and /stats read it;
    # the fleet spool (multi-worker serve) aggregates it across workers
    registry = obs_metrics.get_registry()
    worker_id = str(os.getpid())
    # the fleet-aggregation probe: one gauge that is 1 for every live
    # worker, so a merged scrape shows exactly which workers reported
    registry.gauge("cb_worker_up",
                   "this worker process is serving").set(1)
    shed_counter = registry.counter(
        "cb_gateway_gets_shed_total",
        "GETs shed with 503 by read admission control")
    put_reject_counter = registry.counter(
        "cb_gateway_puts_rejected_total", "PUT ingests rejected",
        labels=("reason",))
    inflight_gauge = registry.gauge(
        "cb_gateway_gets_in_flight", "GET bodies currently streaming")

    if health_state is None:
        health_state = HealthState()

    # slow-request tracing threshold (obs/tracing.py), read at app
    # build like every other knob; 0 = tracing off (the default)
    trace_slow_s = max(cluster.tunables.trace_slow_ms, 0.0) / 1000.0

    # sendfile defaults from the tunable, read here at app build (the
    # gateway's first-use moment, like every other knob)
    if sendfile is None:
        from chunky_bits_tpu.cluster.tunables import gateway_sendfile

        sendfile = gateway_sendfile()

    # SLO engine (obs/slo.py): windowed burn-rate alerting over this
    # registry's snapshots, default OFF — constructed only when the
    # cluster's `slo_eval_s` tunable asks for it, so the idle cost is
    # literally zero (no ring, no ticker task, /alerts answers
    # enabled:false).  Objectives come from the YAML `slo:` mapping.
    slo_eval = max(cluster.tunables.slo_eval_s, 0.0)
    slo_engine = None
    if slo_eval > 0:
        from chunky_bits_tpu.obs import slo as obs_slo

        slo_engine = obs_slo.SloEngine(
            objectives=obs_slo.SloObjectives.from_obj(
                cluster.tunables.slo or None),
            registry=registry)

    # the app's own profiler collects the per-request access log; the
    # cluster's serve-path counters (cache, health) ride along so one
    # report shows the whole serving picture
    if profiler is None:
        profiler = Profiler()
    profiler.attach_health(cluster.health_scoreboard())
    if scrub is not None:
        profiler.attach_scrub(scrub)
    if slo_engine is not None:
        profiler.attach_slo(slo_engine)

    # Multi-tenant QoS scheduler (gateway/qos.py + cluster/qos.py):
    # weighted-fair admission in front of GET bodies and PUT ingest,
    # default OFF — same zero-idle-cost discipline as the SLO engine:
    # the enablement check below reads only the YAML dict / env flag,
    # so the qos modules are never even imported when off.  When on,
    # the scheduler also becomes the pressure/hedge authority: scrub
    # and planner-repair I/O throttle against gateway read pressure
    # (priority: client reads > writes > hedges > scrub/repair), and
    # the scoreboard's hedge launches route through its SLO-aware
    # advisor (suppress under pressure, conserve when read p99 has
    # ample headroom).
    _qos_cfg = cluster.tunables.qos or {}
    _qos_on = _qos_cfg.get("enabled")
    if _qos_on is None:
        from chunky_bits_tpu.cluster.tunables import qos_enabled

        _qos_on = qos_enabled()
    qos_sched = None
    # the shed exception type the admission sites catch; an empty
    # tuple (qos off) catches nothing, so the off path has no qos
    # reference at all beyond the None check
    qos_shed_exc: tuple = ()
    if _qos_on:
        from chunky_bits_tpu.cluster.qos import QosShedError
        from chunky_bits_tpu.gateway import qos as gw_qos

        qos_shed_exc = (QosShedError,)
        qos_sched = gw_qos.maybe_build(
            cluster,
            read_capacity=(max_concurrent_gets
                           if max_concurrent_gets > 0
                           else DEFAULT_MAX_CONCURRENT_GETS),
            write_capacity=(max_concurrent_puts
                            if max_concurrent_puts > 0
                            else DEFAULT_MAX_CONCURRENT_PUTS))
    if qos_sched is not None:
        profiler.attach_qos(qos_sched)
        # hedges yield to client traffic: the scoreboard consults the
        # scheduler before arming/firing (suppression burns no budget)
        cluster.health_scoreboard().set_hedge_gate(qos_sched.allow_hedge)
        if scrub is not None:
            # scrub/repair I/O rides the same token bucket; pressure
            # scales its accrual down (floor 5% — degrade, never hang)
            scrub.set_pressure(qos_sched.pressure)

    # build/configuration identity for the fleet view: one static
    # gauge whose labels say which version/backend/flags THIS worker
    # runs — merged /metrics labels it per worker, so a mixed-version
    # or mixed-flag supervisor fleet is visible in one scrape
    from chunky_bits_tpu import __version__ as _pkg_version
    from chunky_bits_tpu.cluster.tunables import (erasure_code,
                                                  xor_schedule_enabled)

    obs_metrics.record_build_info(
        _pkg_version, cluster.tunables.backend or "auto",
        {
            "code": erasure_code(),
            "xor_schedule": "on" if xor_schedule_enabled() else "off",
            "sendfile": "on" if sendfile else "off",
            "scrub": "on" if scrub is not None else "off",
            "slo": "on" if slo_engine is not None else "off",
            "qos": "on" if qos_sched is not None else "off",
        }, registry)

    # PUT ingest compute (per-shard SHA-256 + per-stripe GF encode) runs
    # on the cluster's host pipeline workers, so the event loop's socket
    # receive overlaps encode+hash on every scheduler core instead of
    # sharing one thread with it.  Resolve (and thereby spawn) the
    # workers now: the first request shouldn't pay the warm-up, and a
    # misconfigured tunables.host_threads should fail at serve start,
    # not mid-ingest.  The read path's verify hops (incl. the sendfile
    # digest check) draw from the same pipeline.
    pipe = cluster.host_pipeline()

    # Every GET/PUT of this app feeds the cluster's ONE location-health
    # scoreboard (cluster/health.py) through the shared LocationContext
    # — concurrent requests therefore share latency/error memory and
    # the hedge budget, the serve-path analogue of the shared encode
    # batcher.  On failures the per-node table goes to the log so a
    # degraded cluster is diagnosable from the gateway side alone.
    health = cluster.health_scoreboard()

    # in-flight GET bodies (admission control); a plain counter — all
    # bookkeeping happens on the app's loop
    gets_in_flight = {"now": 0}

    # GET-body completion timestamps, bounded ring — the observed
    # service rate the derived Retry-After reads.  Loop-local like
    # gets_in_flight (appended only from handle_get's finally).
    get_done: deque = deque(maxlen=_RETRY_AFTER_WINDOW)

    def _retry_after() -> str:
        """Retry-After for a shed request, derived from load: expected
        wait for a slot ≈ (requests ahead + 1) / observed GET-body
        completion rate over the recent window, clamped to
        [1, _RETRY_AFTER_MAX] seconds.  A cold worker (no completions
        yet, or a stalled window) answers the 1-second fallback — the
        old hardcoded behavior — rather than guessing."""
        if len(get_done) < 2:
            return _RETRY_AFTER_SECONDS
        span = time.monotonic() - get_done[0]
        if span <= 0:
            return _RETRY_AFTER_SECONDS
        rate = len(get_done) / span      # completions per second
        ahead = gets_in_flight["now"]
        if qos_sched is not None:
            ahead += qos_sched.queued("read")
        wait = (ahead + 1) / rate
        return str(max(1, min(int(wait + 0.5), _RETRY_AFTER_MAX)))

    # extent key -> validity token of chunk extents whose digest
    # verified, FIFO-bounded; keyed state is per-app (= per worker
    # process), like the chunk cache — see gateway/workers.py on why
    # serving state is partitioned, not shared, across workers.
    # Whole-file local chunks key (path, 0, size) with token
    # (size, mtime_ns): atomic-rename publication means same path +
    # same mtime_ns + same size is the same inode content (the path
    # itself is the content address).  Packed slab extents additionally
    # bind the CHUNK DIGEST into the key — slab bytes are write-once
    # (appends never rewrite a published extent) but slab *names* can
    # recur (a compact of an emptied store restarts the numbering), so
    # (path, offset, length) alone could alias a different chunk later;
    # with the digest in the key a recycled extent address simply
    # misses and re-verifies.  A file-level mtime token would churn on
    # every unrelated append to the same slab, hence "extent".
    verified_memo: dict[tuple, object] = {}

    def _memo_insert(key: tuple, token: object) -> None:
        verified_memo[key] = token
        while len(verified_memo) > _VERIFIED_MEMO_ENTRIES:
            verified_memo.pop(next(iter(verified_memo)))

    async def _verify_local_chunk(chunk, location, chunksize: int
                                  ) -> Optional[tuple[str, int]]:
        """(file path, byte offset) to stream ``chunk``'s verified
        bytes from — a whole local chunk file, or a live extent inside
        a packed slab — or None when this replica can't serve the
        zero-copy path (wrong size, corrupt, missing, non-local).
        Full digest on first sight; extent-keyed memo afterwards."""
        from chunky_bits_tpu.file.file_part import _hash_local_fused

        if location.is_slab():
            ext = await asyncio.to_thread(location.slab_extent)
            if ext is None:
                return None
            path, base, ext_len = ext
            if ext_len != chunksize:
                return None
            key = (path, base, ext_len, chunk.hash.value.digest)
            if verified_memo.get(key) == "extent":
                return (path, base)
            token: object = "extent"
        else:
            path, base = location.target, 0
            try:
                st = await asyncio.to_thread(os.stat, path)
            except OSError:
                return None
            if st.st_size != chunksize:
                return None
            key = (path, 0, chunksize)
            token = (st.st_size, st.st_mtime_ns)
            if verified_memo.get(key) == token:
                return (path, 0)
        cx = cluster.tunables.location_context()
        digest = await _hash_local_fused(chunk, location, cx, pipe)
        if digest is None:
            try:
                digest = await pipe.run(
                    "verify",
                    lambda: _sha256_extent(path, base, chunksize),
                    nbytes=chunksize)
            except OSError:
                return None
        if digest != chunk.hash.value.digest:
            # corrupt replica: a demerit for the node, and the generic
            # read path (which falls through / reconstructs) takes over
            health.record(location, False)
            return None
        _memo_insert(key, token)
        return (path, base)

    async def _sendfile_response(request: web.Request, status: int,
                                 headers: dict, path: str,
                                 offset: int, count: int
                                 ) -> Optional[web.StreamResponse]:
        """Stream ``count`` bytes of ``path`` from ``offset`` via
        ``loop.sendfile`` (the aiohttp FileResponse pattern: prepare,
        sendfile on the raw transport, write_eof).  Returns None when
        the file cannot be opened (caller falls back to reassembly);
        after headers are on the wire, socket-level failures abort the
        connection exactly like the reassembly path's mid-stream
        abort."""
        try:
            f = await asyncio.to_thread(open, path, "rb")
        except OSError:
            return None
        try:
            resp = web.StreamResponse(status=status, headers=headers)
            await resp.prepare(request)
            transport = request.transport
            if transport is None:  # client already gone
                return resp
            loop = asyncio.get_running_loop()
            try:
                try:
                    await loop.sendfile(transport, f, offset, count)
                except NotImplementedError:
                    # no OS sendfile on this transport: bounded chunked
                    # copy through the normal writer
                    await asyncio.to_thread(f.seek, offset)
                    remaining = count
                    while remaining > 0:
                        data = await asyncio.to_thread(
                            f.read, min(1 << 20, remaining))
                        if not data:
                            break
                        remaining -= len(data)
                        await resp.write(data)
            except (ConnectionError, OSError) as err:
                # the file side verified before we got here, so this is
                # the socket: abort the connection like the reassembly
                # path does mid-stream
                log.error("GET %s sendfile aborted: %s",
                          request.path, err)
                resp.force_close()
                if request.transport is not None:
                    request.transport.close()
                return resp
            await resp.write_eof()
            return resp
        finally:
            await asyncio.to_thread(f.close)

    def _serve_source(file_ref: FileReference, cache,
                      seek: int, length: int) -> str:
        """Access-log tag for a reassembly-path read: "cache" when
        every data chunk of every part the span touches is already in
        the read cache (contains() probes — no hit-count skew), else
        "store"."""
        if cache is None:
            return "store"
        end = seek + length
        part_off = 0
        for part in file_ref.parts:
            part_len = part.len_bytes()
            if part_off < end and part_off + part_len > seek:
                for chunk in part.data:
                    key = chunk.cache_key()
                    if key is None or not cache.contains(key):
                        return "store"
            part_off += part_len
            if part_off >= end:
                break
        return "cache"

    async def handle_get(request: web.Request) -> web.StreamResponse:
        path = request.match_info["path"]
        try:
            file_ref = await cluster.get_file_ref(path)
        except MetadataReadError:
            return web.Response(status=404)
        except ChunkyBitsError as err:
            # detail goes to the log only: error text can embed internal
            # node URLs / filesystem paths untrusted clients must not see
            log.error("GET %s failed: %s", path, err)
            return web.Response(status=500, text="error: internal error\n")
        etag = file_ref_etag(file_ref)
        # conditional GET: evaluated before Range (RFC 9110 §13.2.2) —
        # a matching validator answers 304 with zero body bytes
        if _if_none_match_hits(request.headers.get("If-None-Match"),
                               etag):
            request["cb_source"] = "cond"
            return web.Response(status=304, headers={"ETag": etag})
        total = file_ref.len_bytes()
        # the cluster's serve-path builder: per-loop shared reconstruct
        # batcher (concurrent degraded GETs coalesce their decode
        # dispatches) and, when `tunables.cache_bytes` is set, the
        # content-addressed chunk cache.  Range requests ride the same
        # path: the cache only ever holds whole verified chunks — the
        # seek/take trim below happens at the edge, after the cache.
        builder = cluster.file_read_builder(file_ref)
        status = 200
        headers = {"ETag": etag}
        range_header = request.headers.get("Range")
        parsed = None
        if range_header is not None:
            try:
                parsed = parse_http_range(range_header)
            except HttpRangeError:
                # RFC 9110: an unparseable/unknown-unit/multi-range header
                # is ignored, not rejected; 416 is only for unsatisfiable
                # ranges.
                parsed = None
        if parsed is not None:
            if parsed[0] == "range":
                _, start, end = parsed
                builder = builder.with_seek(start).with_take(end - start + 1)
            elif parsed[0] == "prefix":
                builder = builder.with_seek(parsed[1])
            else:  # suffix
                # RFC 9110 §14.1.2: a suffix length >= the representation
                # length selects the ENTIRE representation (it is
                # satisfiable), so clamp rather than 416
                length = min(parsed[1], total)
                builder = builder.with_seek(total - length).with_take(length)
            if builder.len_bytes() == 0:
                # unsatisfiable: RFC 9110 §14.4 — Content-Range carries
                # the selected representation's length so the client
                # can re-range without a probe request
                return web.Response(
                    status=416,
                    headers={"Content-Range": f"bytes */{total}",
                             "ETag": etag})
            seek = builder.seek
            end_excl = seek + builder.len_bytes()
            headers["Content-Range"] = \
                f"bytes {seek}-{end_excl - 1}/{total}"
            status = 206
        length = builder.len_bytes()
        headers["Content-Length"] = str(length)
        if file_ref.content_type:
            headers["Content-Type"] = file_ref.content_type
        if request.method == "HEAD":
            # shares the whole resolution path above (ETag, ranges,
            # 416, Content-Length/Type) but never touches chunk bytes
            request["cb_source"] = "meta"
            resp = web.StreamResponse(status=status, headers=headers)
            await resp.prepare(request)
            return resp
        # Admission control, HERE and not at handler entry: only
        # in-flight GET *bodies* occupy slots, so HEAD, 304
        # revalidations, 404s and 416s — all body-free and cheap — are
        # always answered even at the bound.  Shed, don't queue: an
        # immediate 503 with Retry-After keeps worker memory bounded
        # under a client storm and tells well-behaved clients exactly
        # what to do.  With QoS on, admission runs through the
        # weighted-fair scheduler instead: requests queue briefly
        # (bounded depth + wait) per tenant so one flooding tenant
        # cannot starve the others; overflow still sheds 503.
        if qos_sched is not None:
            try:
                # lint: lock-discipline-ok a failed acquire grants no
                # slot (shed/cancel paths hold nothing to release);
                # granted slots release in the try/finally just below
                await qos_sched.acquire("read", request["cb_tenant"],
                                        cost=length)
            except qos_shed_exc:
                shed_counter.inc()
                return web.Response(
                    status=503, text="error: too many in-flight reads\n",
                    headers={"Retry-After": _retry_after()})
        elif (max_concurrent_gets > 0
                and gets_in_flight["now"] >= max_concurrent_gets):
            shed_counter.inc()
            return web.Response(
                status=503, text="error: too many in-flight reads\n",
                headers={"Retry-After": _retry_after()})
        try:
            gets_in_flight["now"] += 1
            inflight_gauge.set(gets_in_flight["now"])
            return await _serve_get_body(request, path, file_ref,
                                         builder, status, headers,
                                         length)
        finally:
            gets_in_flight["now"] -= 1
            inflight_gauge.set(gets_in_flight["now"])
            get_done.append(time.monotonic())
            if qos_sched is not None:
                qos_sched.release("read")

    async def _serve_get_body(request: web.Request, path: str,
                              file_ref: FileReference, builder,
                              status: int, headers: dict, length: int
                              ) -> web.StreamResponse:
        cache = builder.cache
        # zero-copy fast path: a span inside ONE data chunk with a
        # verified local replica streams straight from the page cache.
        # A chunk already in the read cache is served from memory by
        # the generic path instead (cheaper than re-stating the file).
        if sendfile and length > 0:
            covered = _covering_chunk(file_ref, builder.seek, length)
            if covered is not None:
                chunk, csize, off = covered
                key = chunk.cache_key()
                in_cache = (cache is not None and key is not None
                            and cache.contains(key))
                if not in_cache:
                    for location in chunk.locations:
                        if not (location.is_local()
                                or location.is_slab()) \
                                or location.range.is_specified():
                            continue
                        served = await _verify_local_chunk(
                            chunk, location, csize)
                        if served is not None:
                            path_, base = served
                            resp = await _sendfile_response(
                                request, status, headers,
                                path_, base + off, length)
                            if resp is not None:
                                request["cb_source"] = "sendfile"
                                return resp
        request["cb_source"] = _serve_source(file_ref, cache,
                                             builder.seek, length)
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)
        try:
            async for chunk in builder.stream():
                await resp.write(chunk)
        except ChunkyBitsError as err:
            # Degraded beyond repair (>p chunks gone) or a storage-node
            # failure mid-file.  Status and Content-Length are already on
            # the wire, so the only honest signal left is an aborted
            # connection — the client sees a short body, never a clean
            # EOF that would pass truncated data off as the object.
            # Detail goes to the log only (error text can embed internal
            # node URLs / filesystem paths).
            log.error("GET %s aborted mid-stream: %s", path, err)
            log.error("location health at abort: %s", health.stats())
            resp.force_close()
            if request.transport is not None:
                request.transport.close()
            return resp
        await resp.write_eof()
        return resp

    def put_reject(status: int, text: str) -> web.Response:
        """An error response for a PUT whose body was not (fully) read.
        The connection is force-closed: answering early and then reusing
        the keep-alive stream leaves the unread body bytes in front of
        the next request's head — observed as the follow-up request
        hanging forever against aiohttp 3.11's client, which returns the
        half-sent connection to its pool once the early response lands."""
        resp = web.Response(status=status, text=text)
        resp.force_close()
        return resp

    async def handle_put(request: web.Request) -> web.Response:
        path = request.match_info["path"]
        profile = cluster.get_profile(None)
        content_type: Optional[str] = request.headers.get("Content-Type")

        declared = request.headers.get("Content-Length")
        if max_put_bytes is not None:
            if declared is not None and int(declared) > max_put_bytes:
                put_reject_counter.labels(reason="too_large").inc()
                return put_reject(413, "error: body too large\n")

        # With QoS on, write admission runs through the weighted-fair
        # scheduler BEFORE the body is read: grants stay <= the write
        # capacity (= the put_sem bound), so put_sem below never
        # actually waits — it stays as the invariant backstop.  Write
        # grants are deferred while client reads queue (priority:
        # reads > writes), bounded by the scheduler's wait deadline.
        if qos_sched is not None:
            cost = int(declared) if declared is not None else None
            try:
                # lint: lock-discipline-ok a failed acquire grants no
                # slot (shed/cancel paths hold nothing to release);
                # granted slots release in the try/finally just below
                await qos_sched.acquire("write", request["cb_tenant"],
                                        cost=cost)
            except qos_shed_exc:
                put_reject_counter.labels(reason="shed").inc()
                resp = put_reject(
                    503, "error: too many in-flight writes\n")
                resp.headers["Retry-After"] = _retry_after()
                return resp
        try:
            # A rejected/aborted ingest can leave orphaned shards; they
            # are content-addressed (possibly shared with other files),
            # so they are left for the reference-checking
            # find-unused-hashes GC rather than deleted blindly.
            async with put_sem:
                try:
                    await cluster.write_file(
                        path,
                        _GuardedBody(request.content, max_put_bytes,
                                     min_put_rate),
                        profile, content_type)
                except _BodyTooLarge:
                    put_reject_counter.labels(reason="too_large").inc()
                    return put_reject(413, "error: body too large\n")
                except _BodyTooSlow:
                    put_reject_counter.labels(reason="too_slow").inc()
                    return put_reject(408, "error: ingest too slow\n")
                except ChunkyBitsError as err:
                    log.error("PUT %s failed: %s", path, err)
                    log.error("location health at failure: %s",
                              health.stats())
                    put_reject_counter.labels(reason="error").inc()
                    return put_reject(500, "error: internal error\n")
        finally:
            if qos_sched is not None:
                qos_sched.release("write")
        return web.Response(status=200)

    @web.middleware
    async def access_log(request: web.Request, handler
                         ) -> web.StreamResponse:
        """One structured record per request — the log line operators
        grep and the counters bench --config 9 reports are the same
        numbers (Profiler.log_request -> request_stats; log_request
        also feeds the metrics registry, so /metrics percentiles are
        the same numbers again).  ``bytes`` is the declared body
        length: an aborted stream still logs the length it promised
        (the abort itself is logged separately).

        When ``tunables.trace_slow_ms`` arms tracing, this middleware
        is also the trace root: it mints (or accepts via
        ``X-Chunky-Trace``) the request's trace id and parks the trace
        in the context — every task the handler spawns inherits it,
        and pipeline jobs carry it across the thread boundary."""
        start = time.monotonic()
        status = 500
        nbytes = 0
        # tenant identity resolves HERE, once per request, before any
        # handler runs — both admission sites and the log read it.
        # Resolution is total over the CLOSED table (unmatched -> the
        # "other" bucket), so the logged value can never mint a label.
        if qos_sched is not None:
            request["cb_tenant"] = gw_qos.resolve_request_tenant(
                qos_sched.config, request)
        trace = token = None
        if trace_slow_s > 0:
            trace_id = obs_tracing.clean_id(
                request.headers.get("X-Chunky-Trace"))
            trace, token = obs_tracing.start(trace_id)
        try:
            resp = await handler(request)
            status = resp.status
            if request.method != "HEAD" and status < 300:
                nbytes = resp.content_length or 0
            return resp
        except web.HTTPException as err:
            # the router answers unroutable methods (405 etc.) by
            # raising; log the status the client actually sees, not a
            # phantom 500 that would inflate error-rate stats
            status = err.status
            raise
        finally:
            duration = time.monotonic() - start
            source = request.get("cb_source", "-")
            tenant = request.get("cb_tenant", "-")
            profiler.log_request(request.method, request.path, status,
                                 nbytes, duration, source, tenant)
            if qos_sched is not None and status < 500:
                # completion-latency sample for the SLO-aware hedge
                # advisor — same numbers the access log just recorded
                if request.method == "GET":
                    qos_sched.note_request("read", duration)
                elif request.method == "PUT":
                    qos_sched.note_request("write", duration)
            if trace is not None and token is not None:
                trace.add("request", "gateway", start, duration,
                          str(status))
                obs_tracing.finish(
                    trace, token, duration=duration,
                    slow_s=trace_slow_s,
                    meta={"method": request.method,
                          "path": request.path, "status": status,
                          "source": source, "worker": worker_id})
            log.info(
                "req method=%s path=%s status=%d bytes=%d ms=%.2f "
                "source=%s tenant=%s", request.method, request.path,
                status, nbytes, duration * 1000.0, source, tenant)

    async def handle_scrub_status(request: web.Request) -> web.Response:
        """Scrub observability: counters + running state as JSON.
        ``enabled: false`` when no daemon is attached (the tunable is
        off, or a multi-worker fleet where scrub runs as its own
        ``chunky-bits scrub`` job instead of per worker)."""
        request["cb_source"] = "meta"
        if scrub is None:
            payload = {"enabled": False}
        else:
            payload = {"enabled": True, **scrub.stats().to_obj()}
        return web.json_response(payload)

    async def handle_metrics(request: web.Request) -> web.Response:
        """Prometheus text exposition.  Single-process: this worker's
        registry.  Under a multi-worker supervisor (``metrics_spool``
        set): the FLEET view — this worker's live snapshot merged with
        every sibling's spooled one (counters/histograms summed, gauges
        labeled by worker) — so one scrape covers the whole
        SO_REUSEPORT fleet no matter which worker the kernel picked."""
        request["cb_source"] = "meta"
        own = registry.snapshot()
        if metrics_spool is not None:
            merged = await asyncio.to_thread(
                obs_metrics.fleet_snapshot, metrics_spool,
                (worker_id, own))
        else:
            merged = obs_metrics.merge_snapshots([(None, own)])
        return web.Response(
            text=obs_metrics.render_exposition(merged),
            content_type="text/plain", charset="utf-8")

    async def handle_alerts(request: web.Request) -> web.Response:
        """SLO alert states as JSON (obs/slo.py).  ``enabled: false``
        when the engine is off (the default — `slo_eval_s` unset).
        Under a multi-worker supervisor the payload adds the FLEET
        view, merged from the same snapshot spool as /metrics: per
        rule, the max state across live workers (firing on any worker
        means firing fleet-wide), with the per-worker breakdown — a
        spool-reaped dead worker drops out of the merge, so a crashed
        sibling can never contribute a stale firing alert."""
        request["cb_source"] = "meta"
        if slo_engine is None:
            return web.json_response({"enabled": False})
        payload = slo_engine.to_obj()
        payload["worker"] = worker_id
        if metrics_spool is not None:
            from chunky_bits_tpu.obs import slo as obs_slo

            entries = await asyncio.to_thread(
                obs_metrics.load_spool, metrics_spool)
            entries = [(wid, snap) for wid, snap in entries
                       if wid != worker_id]
            entries.append((worker_id, registry.snapshot()))
            payload["fleet"] = obs_slo.fleet_alert_states(entries)
        return web.json_response(payload)

    async def handle_stats(request: web.Request) -> web.Response:
        """JSON snapshot twin of /metrics (this worker only — machine
        consumers wanting the fleet read /metrics), plus the access-log
        summary computed by the same ``request_stats``/``percentile``
        code bench --config 9 uses."""
        request["cb_source"] = "meta"
        payload = {
            "worker": worker_id,
            "requests": request_stats(
                profiler.peek_requests()).to_obj(),
            "dropped": profiler.drop_counts(),
            "slo": ({"enabled": True,
                     **slo_engine.stats().to_obj()}
                    if slo_engine is not None
                    else {"enabled": False}),
            "qos": (qos_sched.stats().to_obj()
                    if qos_sched is not None
                    else {"enabled": False}),
            "metrics": registry.snapshot(),
        }
        if qos_sched is not None:
            # per-tenant access-log percentiles, same request_stats
            # code as the aggregate block above
            payload["requests_by_tenant"] = {
                t: s.to_obj() for t, s in tenant_request_stats(
                    profiler.peek_requests()).items()}
        return web.json_response(payload)

    async def handle_healthz(request: web.Request) -> web.Response:
        """Per-worker liveness/readiness: 200 while serving, 503 once
        draining (shutdown requested, listener still up) — the signal a
        balancer needs to stop routing here before connections break."""
        request["cb_source"] = "meta"
        if health_state.draining:
            return web.json_response(
                {"status": "draining", "worker": worker_id},
                status=503)
        return web.json_response({
            "status": "ok", "worker": worker_id,
            "uptime_s": round(time.monotonic() - health_state.started,
                              3)})

    async def handle_debug_traces(request: web.Request) -> web.Response:
        """The slowest-N retained traces (per worker — a trace is one
        worker's story), slowest first, with per-plane time so "which
        plane ate the p999" reads straight off the payload."""
        request["cb_source"] = "meta"
        return web.json_response({
            "enabled": trace_slow_s > 0,
            "trace_slow_ms": trace_slow_s * 1000.0,
            "worker": worker_id,
            "traces": obs_tracing.buffer().snapshot(),
        })

    # always-on event-loop lag sampler + (multi-worker) the periodic
    # snapshot publication the fleet /metrics merge reads; both bound
    # to the app's lifecycle so tests and restarts leak nothing
    lag_monitor = obs_metrics.LoopLagMonitor(registry)
    spool_task: dict = {"task": None}
    slo_task: dict = {"task": None}

    async def _slo_ticker() -> None:
        """The engine's evaluation cadence: one registry snapshot per
        `slo_eval_s` into the ring.  Under a supervisor the engine
        evaluates the WORKER-LABELED fleet view (this worker live +
        siblings off the spool, every sample tagged `worker=` — NOT
        the summed /metrics merge, whose per-series reset clamp would
        misread one sibling's restart as a fleet-lifetime delta), so
        fleet-level rules (worker_down, summed burn rates) see the
        whole gateway, a restarted sibling clamps to its own small
        post-reset values, and a reaped sibling contributes nothing."""
        from chunky_bits_tpu.obs import slo as obs_slo

        while True:
            try:
                own = registry.snapshot()
                if metrics_spool is not None:
                    entries = await asyncio.to_thread(
                        obs_metrics.load_spool, metrics_spool)
                    entries = [(wid, s) for wid, s in entries
                               if wid != worker_id]
                    entries.append((worker_id, own))
                    snap = obs_slo.worker_labeled_snapshot(entries)
                else:
                    snap = own
                slo_engine.observe(snap)
            # one bad beat (torn spool file mid-teardown, a foreign
            # snapshot shape from a mixed-version sibling) must not
            # silently kill alerting for the process lifetime — same
            # guard discipline as _spool_writer: log, retry next tick
            # lint: broad-except-ok degrade-never-die heartbeat; the
            # failure is logged and the next tick retries
            except Exception as err:
                log.warning("slo evaluation tick failed: %s", err)
            await asyncio.sleep(slo_eval)

    async def _spool_writer() -> None:
        path = os.path.join(metrics_spool, f"worker-{worker_id}.json")
        while True:
            snap = registry.snapshot()
            try:
                await asyncio.to_thread(
                    obs_metrics.write_snapshot_file, path, snap)
            except OSError as err:
                # a failed heartbeat (ENOSPC, spool dir racing the
                # supervisor's teardown) must not kill the writer: the
                # next beat retries, and the loss is logged so a worker
                # going stale in the fleet view is diagnosable
                log.warning("metrics spool write failed: %s", err)
            await asyncio.sleep(_SPOOL_INTERVAL)

    async def _on_startup(app: web.Application) -> None:
        lag_monitor.start(asyncio.get_running_loop())
        if metrics_spool is not None:
            spool_task["task"] = asyncio.ensure_future(_spool_writer())
        if slo_engine is not None:
            slo_task["task"] = asyncio.ensure_future(_slo_ticker())

    async def _on_cleanup(app: web.Application) -> None:
        lag_monitor.stop()
        for holder in (spool_task, slo_task):
            task = holder["task"]
            if task is not None:
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)
                holder["task"] = None

    app = web.Application(middlewares=[access_log])
    app[PROFILER_KEY] = profiler
    app[HEALTH_KEY] = health_state
    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)
    # registered before the catch-all: these endpoints shadow objects
    # literally named "scrub/status", "metrics", "stats", "healthz",
    # "alerts", "debug/traces" (documented deviation — the reference's
    # gateway has no non-object routes at all)
    app.router.add_get("/scrub/status", handle_scrub_status)
    app.router.add_get("/alerts", handle_alerts)
    app.router.add_get("/metrics", handle_metrics)
    app.router.add_get("/stats", handle_stats)
    app.router.add_get("/healthz", handle_healthz)
    app.router.add_get("/debug/traces", handle_debug_traces)
    app.router.add_get("/{path:.*}", handle_get)  # also serves HEAD
    app.router.add_put("/{path:.*}", handle_put)
    return app


async def serve(cluster: Cluster, host: str = "127.0.0.1",
                port: int = 8000,
                max_put_bytes: Optional[int] = None,
                max_concurrent_puts: int = DEFAULT_MAX_CONCURRENT_PUTS,
                min_put_rate: int = DEFAULT_MIN_PUT_RATE,
                max_concurrent_gets: int = DEFAULT_MAX_CONCURRENT_GETS,
                workers: Optional[int] = None,
                reuse_port: bool = False,
                on_ready: Optional[Callable[[int], None]] = None,
                metrics_spool: Optional[str] = None,
                health_state: Optional[HealthState] = None
                ) -> None:
    """Bind and serve until cancelled (ctrl-c graceful shutdown,
    main.rs:474-485).

    ``workers`` (None = the ``tunables.gateway_workers`` env default,
    normally 1) > 1 delegates to gateway/workers.py: N pre-forked
    SO_REUSEPORT processes, each running this single-process serve with
    ``reuse_port=True``.  ``on_ready`` fires with the bound port once
    the listener accepts connections (the worker readiness handshake;
    also handy for tests)."""
    from chunky_bits_tpu.cluster.tunables import (gateway_workers,
                                                  sanitize_enabled)

    if workers is None:
        workers = gateway_workers()
    if workers > 1:
        from chunky_bits_tpu.gateway.workers import serve_workers

        await serve_workers(
            cluster, host=host, port=port, workers=workers,
            max_put_bytes=max_put_bytes,
            max_concurrent_puts=max_concurrent_puts,
            min_put_rate=min_put_rate,
            max_concurrent_gets=max_concurrent_gets,
            on_ready=on_ready)
        return

    if sanitize_enabled():
        # opt-in runtime concurrency sanitizer: instrument the serving
        # loop (stall watchdog + task registry) — read here, at the one
        # moment the gateway's loop is known, like every other
        # first-use tunable
        from chunky_bits_tpu.analysis.sanitizer import get_monitor

        get_monitor().instrument_loop(asyncio.get_running_loop())
    # continuous scrub rides the serving loop when the cluster's
    # `scrub_bytes_per_sec` tunable asks for it (cluster/scrub.py;
    # off = no daemon object at all).  Single-process serve only: a
    # pre-forked fleet would otherwise run N identical namespace walks
    # — multi-worker deployments run `chunky-bits scrub` as its own
    # job, and every worker's /scrub/status says so (enabled: false).
    scrub = None
    if not reuse_port:
        from chunky_bits_tpu.cluster.scrub import maybe_build

        scrub = maybe_build(cluster)
    runner = web.AppRunner(
        make_app(cluster, max_put_bytes=max_put_bytes,
                 max_concurrent_puts=max_concurrent_puts,
                 min_put_rate=min_put_rate,
                 max_concurrent_gets=max_concurrent_gets,
                 scrub=scrub, metrics_spool=metrics_spool,
                 health_state=health_state))
    await runner.setup()
    site = web.TCPSite(runner, host, port, reuse_port=reuse_port)
    await site.start()
    if scrub is not None:
        scrub.start()
    bound_port = port
    server = getattr(site, "_server", None)
    if server is not None and server.sockets:
        bound_port = server.sockets[0].getsockname()[1]
    print(f"listening on http://{host}:{bound_port}", flush=True)
    if on_ready is not None:
        on_ready(bound_port)
    try:
        while True:
            await asyncio.sleep(3600)
    # lint: cancel-safety-ok ctrl-c/cancel IS the shutdown signal for
    # the serve park; swallowing it hands control to the finally's
    # graceful teardown (scrub stop + runner cleanup) before exit
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if scrub is not None:
            await scrub.stop()
        await runner.cleanup()
