// Native GF(2^8) erasure codec — the CPU oracle backend.
//
// Same role as the reference's `reed-solomon-erasure` crate (CPU SIMD GF(2^8)
// tables; reference: Cargo.toml:21, used at src/file/file_part.rs:161,302):
// applies a GF(2^8) matrix to a batch of stacked shards.  Field is 0x11d with
// generator 2, identical to chunky_bits_tpu/ops/gf256.py — the Python side
// cross-checks the tables at load time.
//
// The inner loop uses the classic nibble-table pshufb trick under AVX2
// (c*x = T_c[x>>4 << 4] ^ T_c[x&15]) and falls back to full-table scalar
// lookups elsewhere.  Batch items are fanned across std::threads.

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#ifdef __AVX2__
#include <immintrin.h>
#endif

namespace {

uint8_t MUL[256][256];

bool init_tables() {
    uint8_t exp_t[512];
    int log_t[256] = {0};
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp_t[i] = static_cast<uint8_t>(x);
        log_t[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
    for (int a = 0; a < 256; a++) {
        for (int b = 0; b < 256; b++) {
            MUL[a][b] = (a && b)
                ? exp_t[(log_t[a] + log_t[b]) % 255]
                : 0;
        }
    }
    return true;
}

const bool kInited = init_tables();

void xor_row(const uint8_t* src, uint8_t* dst, size_t n) {
    size_t i = 0;
#ifdef __AVX2__
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + i));
        __m256i d = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(dst + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                            _mm256_xor_si256(d, v));
    }
#endif
    for (; i < n; i++) dst[i] ^= src[i];
}

void mul_row_xor(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n) {
    const uint8_t* table = MUL[c];
    size_t i = 0;
#ifdef __AVX2__
    alignas(16) uint8_t lo[16], hi[16];
    for (int v = 0; v < 16; v++) {
        lo[v] = MUL[c][v];
        hi[v] = MUL[c][v << 4];
    }
    __m256i vlo = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(lo)));
    __m256i vhi = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(hi)));
    __m256i mask = _mm256_set1_epi8(0x0f);
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + i));
        __m256i l = _mm256_and_si256(v, mask);
        __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, l),
                                     _mm256_shuffle_epi8(vhi, h));
        __m256i d = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(dst + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                            _mm256_xor_si256(d, r));
    }
#endif
    for (; i < n; i++) dst[i] ^= table[src[i]];
}

// One batch item: out[r, s] = mat[r, k] (x) shards[k, s] over GF(2^8).
void apply_one(const uint8_t* mat, size_t r, size_t k,
               const uint8_t* shards, size_t s, uint8_t* out) {
    std::memset(out, 0, r * s);
    for (size_t i = 0; i < r; i++) {
        uint8_t* dst = out + i * s;
        for (size_t j = 0; j < k; j++) {
            uint8_t c = mat[i * k + j];
            if (c == 0) continue;
            const uint8_t* src = shards + j * s;
            if (c == 1) {
                xor_row(src, dst, s);
            } else {
                mul_row_xor(c, src, dst, s);
            }
        }
    }
}

}  // namespace

extern "C" {

// out[b, r, s] = mat[r, k] (x) shards[b, k, s]; nthreads <= 0 => hardware.
void cb_apply_matrix(const uint8_t* mat, size_t r, size_t k,
                     const uint8_t* shards, size_t b, size_t s,
                     uint8_t* out, int nthreads) {
    if (!kInited || r == 0 || b == 0 || s == 0) return;
    size_t want = nthreads > 0
        ? static_cast<size_t>(nthreads)
        : static_cast<size_t>(std::thread::hardware_concurrency());
    if (want == 0) want = 1;
    size_t threads = want < b ? want : b;
    if (threads <= 1) {
        for (size_t i = 0; i < b; i++) {
            apply_one(mat, r, k, shards + i * k * s, s, out + i * r * s);
        }
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (size_t t = 0; t < threads; t++) {
        pool.emplace_back([=]() {
            for (size_t i = t; i < b; i += threads) {
                apply_one(mat, r, k, shards + i * k * s, s, out + i * r * s);
            }
        });
    }
    for (auto& th : pool) th.join();
}

// Table self-check hook: lets Python assert C++ and numpy agree on the field.
uint8_t cb_gf_mul(uint8_t a, uint8_t b) { return MUL[a][b]; }

}  // extern "C"
