// Native GF(2^8) erasure codec + SHA-256 hashing engine — the CPU oracle
// backend and the ingest hot path's native runtime.
//
// Same role as the reference's `reed-solomon-erasure` crate plus its `sha2`
// dependency (CPU SIMD GF(2^8) tables: Cargo.toml:21, used at
// src/file/file_part.rs:161,302; per-shard SHA-256: file_part.rs:185):
// applies a GF(2^8) matrix to a batch of stacked shards, and content-hashes
// shards.  Field is 0x11d with generator 2, identical to
// chunky_bits_tpu/ops/gf256.py — the Python side cross-checks the tables at
// load time, and tests cross-check SHA-256 against hashlib.
//
// The GF inner loop dispatches at runtime: on GFNI+AVX-512 hosts a single
// gf2p8affineqb applies the 8x8 bit-matrix of "multiply by c" to 64 bytes
// per instruction (the constant-multiplier map is GF(2)-linear, so it works
// for the 0x11d field even though the ISA's gf2p8mulb is hardwired to the
// AES polynomial); otherwise the classic nibble-table pshufb trick under
// AVX2 (c*x = T_c[x>>4 << 4] ^ T_c[x&15]); full-table scalar elsewhere.
// The GFNI path self-verifies against the scalar tables at startup and
// disables itself on any mismatch.  SHA-256 uses the SHA-NI extension when
// the CPU has it (runtime dispatch) and a portable scalar path otherwise.
// `cb_encode_hash` fuses parity + per-shard hashing in one pass per batch
// item while the shard bytes are cache-hot.  Batch items are fanned across
// std::threads.

#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__AVX2__) || defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

uint8_t MUL[256][256];

bool init_tables() {
    uint8_t exp_t[512];
    int log_t[256] = {0};
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp_t[i] = static_cast<uint8_t>(x);
        log_t[x] = i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 512; i++) exp_t[i] = exp_t[i - 255];
    for (int a = 0; a < 256; a++) {
        for (int b = 0; b < 256; b++) {
            MUL[a][b] = (a && b)
                ? exp_t[(log_t[a] + log_t[b]) % 255]
                : 0;
        }
    }
    return true;
}

const bool kInited = init_tables();

// ---- GFNI path: multiply-by-c as an 8x8 GF(2) affine transform ----
//
// gf2p8affineqb computes out_bit[i] = parity(A.byte[7-i] & x) per data
// byte (empirically probed + verified on this convention), so the matrix
// qword for constant c packs bit (7-k) of c*2^j at byte k, bit j.

// Compiler gate, not just arch: the GFNI intrinsics + target attribute
// need GCC 10 / clang 10 here — on older toolchains the whole GFNI
// block must vanish or the native build (and with it the default
// backend) silently degrades to numpy.  Runtime detection of the GFNI
// *feature* goes through raw CPUID below, because
// __builtin_cpu_supports("gfni") itself only parses from GCC 11.
#if defined(__x86_64__) && \
    ((defined(__clang__) && __clang_major__ >= 10) || \
     (!defined(__clang__) && defined(__GNUC__) && __GNUC__ >= 10))
#define CB_HAVE_GFNI 1
#endif

#ifdef CB_HAVE_GFNI
#include <cpuid.h>
bool cpu_has_gfni() {
    unsigned int eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
    return (ecx >> 8) & 1u;  // CPUID.(7,0):ECX.GFNI[bit 8]
}

uint64_t GFNI_MAT[256];

uint64_t gfni_matrix(uint8_t c) {
    uint8_t col[8];
    for (int j = 0; j < 8; j++) col[j] = MUL[c][1 << j];
    uint64_t a = 0;
    for (int k = 0; k < 8; k++) {
        uint8_t row = 0;
        for (int j = 0; j < 8; j++)
            row |= static_cast<uint8_t>(((col[j] >> (7 - k)) & 1) << j);
        a |= static_cast<uint64_t>(row) << (8 * k);
    }
    return a;
}

__attribute__((target("avx512f,avx512bw,avx512vl,gfni")))
bool gfni_self_test() {
    // spot-verify the instruction semantics against the scalar tables;
    // a convention mismatch (or emulator quirk) disables the path
    const uint8_t cs[] = {1, 2, 3, 0x1d, 0x53, 0x8e, 0xff};
    for (uint8_t c : cs) {
        __m128i a = _mm_set1_epi64x(
            static_cast<long long>(GFNI_MAT[c]));
        for (int x = 0; x < 256; x += 17) {
            __m128i v = _mm_set1_epi8(static_cast<char>(x));
            __m128i r = _mm_gf2p8affine_epi64_epi8(v, a, 0);
            if (static_cast<uint8_t>(_mm_extract_epi8(r, 0))
                    != MUL[c][x])
                return false;
        }
    }
    return true;
}

bool init_gfni() {
    // avx512* go through the builtin (it checks OS XSAVE state too, and
    // those names parse on every toolchain that passed the gate above);
    // only "gfni" needs the raw-CPUID fallback.
    if (!(__builtin_cpu_supports("avx512f")
          && __builtin_cpu_supports("avx512bw")
          && __builtin_cpu_supports("avx512vl")
          && cpu_has_gfni()))
        return false;
    for (int c = 0; c < 256; c++)
        GFNI_MAT[c] = gfni_matrix(static_cast<uint8_t>(c));
    return gfni_self_test();
}

const bool kGfni = init_gfni();

__attribute__((target("avx512f,avx512bw,avx512vl,gfni")))
size_t mul_row_xor_gfni(uint8_t c, const uint8_t* src, uint8_t* dst,
                        size_t n) {
    __m512i a = _mm512_set1_epi64(static_cast<long long>(GFNI_MAT[c]));
    size_t i = 0;
    for (; i + 64 <= n; i += 64) {
        __m512i v = _mm512_loadu_si512(src + i);
        __m512i r = _mm512_gf2p8affine_epi64_epi8(v, a, 0);
        __m512i d = _mm512_loadu_si512(dst + i);
        _mm512_storeu_si512(dst + i, _mm512_xor_si512(d, r));
    }
    return i;  // caller finishes the tail with the scalar table
}
#else
const bool kGfni = false;
size_t mul_row_xor_gfni(uint8_t, const uint8_t*, uint8_t*, size_t) {
    return 0;
}
#endif

// ---- runtime GF table-tier selection ----
//
// The byte-level table kernels come in three tiers: GFNI (2), AVX2
// nibble-pshufb (1, only when the build compiled AVX2 in), scalar
// full-table (0).  The active tier is runtime-selectable so bench
// --config 12 can A/B the scheduled-XOR engine against every tier a
// deployment might actually run (a generic -O3 fallback build has no
// pshufb path at all), and tests can pin the scalar path.

#ifdef __AVX2__
constexpr int kGfCompiledSimd = 1;
#else
constexpr int kGfCompiledSimd = 0;
#endif

int g_gf_best = kGfni ? 2 : kGfCompiledSimd;
int g_gf_level = g_gf_best;

void xor_row(const uint8_t* src, uint8_t* dst, size_t n) {
    size_t i = 0;
#ifdef __AVX2__
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + i));
        __m256i d = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(dst + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                            _mm256_xor_si256(d, v));
    }
#endif
    for (; i < n; i++) dst[i] ^= src[i];
}

void mul_row_xor(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n) {
    const uint8_t* table = MUL[c];
    size_t i = 0;
    if (g_gf_level >= 2) {
        i = mul_row_xor_gfni(c, src, dst, n);
        for (; i < n; i++) dst[i] ^= table[src[i]];
        return;
    }
#ifdef __AVX2__
    if (g_gf_level < 1) {
        for (; i < n; i++) dst[i] ^= table[src[i]];
        return;
    }
    alignas(16) uint8_t lo[16], hi[16];
    for (int v = 0; v < 16; v++) {
        lo[v] = MUL[c][v];
        hi[v] = MUL[c][v << 4];
    }
    __m256i vlo = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(lo)));
    __m256i vhi = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(hi)));
    __m256i mask = _mm256_set1_epi8(0x0f);
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + i));
        __m256i l = _mm256_and_si256(v, mask);
        __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(vlo, l),
                                     _mm256_shuffle_epi8(vhi, h));
        __m256i d = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(dst + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                            _mm256_xor_si256(d, r));
    }
#endif
    for (; i < n; i++) dst[i] ^= table[src[i]];
}

// One batch item: out[r, s] = mat[r, k] (x) shards[k, s] over GF(2^8).
//
// Blocked over the byte axis so each (r + k)-row working chunk stays in
// L2 across the whole coefficient grid: without blocking, every output
// row streams all k megabyte-scale input rows from DRAM again — r*k*3
// row-passes of memory traffic vs (k reads + r writes) with blocking
// (~9x less at d=10 p=4), which is what the byte-level kernels (GFNI /
// pshufb) are fast enough to expose.
//: byte-axis block size: (k + r) * BLK ~ 0.5-1 MiB << L2+L3, and a
//: multiple of 64 so SHA block boundaries align (encode_hash_one)
constexpr size_t kApplyBlk = 32768;

// One byte-range [off, off+len) of the coefficient grid.
void apply_block(const uint8_t* mat, size_t r, size_t k,
                 const uint8_t* shards, size_t s, uint8_t* out,
                 size_t off, size_t len) {
    for (size_t i = 0; i < r; i++) {
        uint8_t* dst = out + i * s + off;
        // zero here, not up front: a whole-buffer memset would
        // stream r*s bytes through cache before any accumulation,
        // evicting the very chunks the blocking keeps hot
        std::memset(dst, 0, len);
        for (size_t j = 0; j < k; j++) {
            uint8_t c = mat[i * k + j];
            if (c == 0) continue;
            const uint8_t* src = shards + j * s + off;
            if (c == 1) {
                xor_row(src, dst, len);
            } else {
                mul_row_xor(c, src, dst, len);
            }
        }
    }
}

void apply_one(const uint8_t* mat, size_t r, size_t k,
               const uint8_t* shards, size_t s, uint8_t* out) {
    for (size_t off = 0; off < s; off += kApplyBlk) {
        size_t len = s - off < kApplyBlk ? s - off : kApplyBlk;
        apply_block(mat, r, k, shards, s, out, off, len);
    }
}

// ---- SHA-256 ----

namespace sha256 {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

constexpr uint32_t H0[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
};

inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

void transform_scalar(uint32_t* st, const uint8_t* p, size_t blocks) {
    uint32_t w[64];
    for (; blocks; blocks--, p += 64) {
        for (int i = 0; i < 16; i++) {
            w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16)
                 | (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
        }
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18)
                        ^ (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19)
                        ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
        uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = h + S1 + ch + K[i] + w[i];
            uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + maj;
            h = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        st[0] += a; st[1] += b; st[2] += c; st[3] += d;
        st[4] += e; st[5] += f; st[6] += g; st[7] += h;
    }
}

#if defined(__x86_64__) && defined(__GNUC__)
#define CB_HAVE_SHANI 1
#include <cpuid.h>
// Runtime SHA-NI detection via raw CPUID (leaf 7, EBX bit 29).  The
// obvious __builtin_cpu_supports("sha") only parses from GCC 11 — on
// GCC 10 that builtin is a hard compile error that takes the whole
// native build (and the default backend) down with it, hence this
// hand-rolled check.
bool cpu_has_shani() {
    unsigned int eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
    return (ebx >> 29) & 1u;
}
// Intel SHA extensions path; layout (ABEF/CDGH packing, per-4-round
// message recurrence) follows the standard published pattern.
__attribute__((target("sha,sse4.1,ssse3")))
void transform_shani(uint32_t* st, const uint8_t* p, size_t blocks) {
    const __m128i mask =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
    __m128i tmp = _mm_loadu_si128(reinterpret_cast<const __m128i*>(st));
    __m128i st1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(st + 4));
    tmp = _mm_shuffle_epi32(tmp, 0xB1);        // CDAB
    st1 = _mm_shuffle_epi32(st1, 0x1B);        // EFGH
    __m128i st0 = _mm_alignr_epi8(tmp, st1, 8);   // ABEF
    st1 = _mm_blend_epi16(st1, tmp, 0xF0);        // CDGH

    for (; blocks; blocks--, p += 64) {
        __m128i save0 = st0, save1 = st1;
        __m128i msgs[4];
        for (int i = 0; i < 4; i++) {
            msgs[i] = _mm_shuffle_epi8(
                _mm_loadu_si128(
                    reinterpret_cast<const __m128i*>(p + 16 * i)),
                mask);
            __m128i m = _mm_add_epi32(
                msgs[i],
                _mm_loadu_si128(
                    reinterpret_cast<const __m128i*>(K + 4 * i)));
            st1 = _mm_sha256rnds2_epu32(st1, st0, m);
            m = _mm_shuffle_epi32(m, 0x0E);
            st0 = _mm_sha256rnds2_epu32(st0, st1, m);
        }
        for (int i = 4; i < 16; i++) {
            __m128i w = _mm_sha256msg1_epu32(msgs[(i - 4) & 3],
                                             msgs[(i - 3) & 3]);
            w = _mm_add_epi32(
                w, _mm_alignr_epi8(msgs[(i - 1) & 3], msgs[(i - 2) & 3], 4));
            w = _mm_sha256msg2_epu32(w, msgs[(i - 1) & 3]);
            msgs[i & 3] = w;
            __m128i m = _mm_add_epi32(
                w, _mm_loadu_si128(
                       reinterpret_cast<const __m128i*>(K + 4 * i)));
            st1 = _mm_sha256rnds2_epu32(st1, st0, m);
            m = _mm_shuffle_epi32(m, 0x0E);
            st0 = _mm_sha256rnds2_epu32(st0, st1, m);
        }
        st0 = _mm_add_epi32(st0, save0);
        st1 = _mm_add_epi32(st1, save1);
    }

    tmp = _mm_shuffle_epi32(st0, 0x1B);        // FEBA
    st1 = _mm_shuffle_epi32(st1, 0xB1);        // DCHG
    st0 = _mm_blend_epi16(tmp, st1, 0xF0);     // DCBA
    st1 = _mm_alignr_epi8(st1, tmp, 8);        // HGFE
    _mm_storeu_si128(reinterpret_cast<__m128i*>(st), st0);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(st + 4), st1);
}
// Two independent streams interleaved through one instruction stream:
// sha256rnds2/msg1/msg2 have multi-cycle latency but single-cycle
// throughput, so a second chain hides the first one's latency (~1.6-1.8x
// one core).  Shards are independent, so pairs are free to come by.
__attribute__((target("sha,sse4.1,ssse3")))
void transform_shani_x2(uint32_t* stA_, const uint8_t* pA,
                        uint32_t* stB_, const uint8_t* pB, size_t blocks) {
    const __m128i mask =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
    __m128i tmpA = _mm_loadu_si128(reinterpret_cast<const __m128i*>(stA_));
    __m128i a1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(stA_ + 4));
    tmpA = _mm_shuffle_epi32(tmpA, 0xB1);
    a1 = _mm_shuffle_epi32(a1, 0x1B);
    __m128i a0 = _mm_alignr_epi8(tmpA, a1, 8);
    a1 = _mm_blend_epi16(a1, tmpA, 0xF0);
    __m128i tmpB = _mm_loadu_si128(reinterpret_cast<const __m128i*>(stB_));
    __m128i b1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(stB_ + 4));
    tmpB = _mm_shuffle_epi32(tmpB, 0xB1);
    b1 = _mm_shuffle_epi32(b1, 0x1B);
    __m128i b0 = _mm_alignr_epi8(tmpB, b1, 8);
    b1 = _mm_blend_epi16(b1, tmpB, 0xF0);

    for (; blocks; blocks--, pA += 64, pB += 64) {
        __m128i saveA0 = a0, saveA1 = a1, saveB0 = b0, saveB1 = b1;
        __m128i msgsA[4], msgsB[4];
        for (int i = 0; i < 4; i++) {
            msgsA[i] = _mm_shuffle_epi8(
                _mm_loadu_si128(
                    reinterpret_cast<const __m128i*>(pA + 16 * i)),
                mask);
            msgsB[i] = _mm_shuffle_epi8(
                _mm_loadu_si128(
                    reinterpret_cast<const __m128i*>(pB + 16 * i)),
                mask);
            __m128i kv = _mm_loadu_si128(
                reinterpret_cast<const __m128i*>(K + 4 * i));
            __m128i mA = _mm_add_epi32(msgsA[i], kv);
            __m128i mB = _mm_add_epi32(msgsB[i], kv);
            a1 = _mm_sha256rnds2_epu32(a1, a0, mA);
            b1 = _mm_sha256rnds2_epu32(b1, b0, mB);
            mA = _mm_shuffle_epi32(mA, 0x0E);
            mB = _mm_shuffle_epi32(mB, 0x0E);
            a0 = _mm_sha256rnds2_epu32(a0, a1, mA);
            b0 = _mm_sha256rnds2_epu32(b0, b1, mB);
        }
        for (int i = 4; i < 16; i++) {
            __m128i wA = _mm_sha256msg1_epu32(msgsA[(i - 4) & 3],
                                              msgsA[(i - 3) & 3]);
            __m128i wB = _mm_sha256msg1_epu32(msgsB[(i - 4) & 3],
                                              msgsB[(i - 3) & 3]);
            wA = _mm_add_epi32(
                wA,
                _mm_alignr_epi8(msgsA[(i - 1) & 3], msgsA[(i - 2) & 3], 4));
            wB = _mm_add_epi32(
                wB,
                _mm_alignr_epi8(msgsB[(i - 1) & 3], msgsB[(i - 2) & 3], 4));
            wA = _mm_sha256msg2_epu32(wA, msgsA[(i - 1) & 3]);
            wB = _mm_sha256msg2_epu32(wB, msgsB[(i - 1) & 3]);
            msgsA[i & 3] = wA;
            msgsB[i & 3] = wB;
            __m128i kv = _mm_loadu_si128(
                reinterpret_cast<const __m128i*>(K + 4 * i));
            __m128i mA = _mm_add_epi32(wA, kv);
            __m128i mB = _mm_add_epi32(wB, kv);
            a1 = _mm_sha256rnds2_epu32(a1, a0, mA);
            b1 = _mm_sha256rnds2_epu32(b1, b0, mB);
            mA = _mm_shuffle_epi32(mA, 0x0E);
            mB = _mm_shuffle_epi32(mB, 0x0E);
            a0 = _mm_sha256rnds2_epu32(a0, a1, mA);
            b0 = _mm_sha256rnds2_epu32(b0, b1, mB);
        }
        a0 = _mm_add_epi32(a0, saveA0);
        a1 = _mm_add_epi32(a1, saveA1);
        b0 = _mm_add_epi32(b0, saveB0);
        b1 = _mm_add_epi32(b1, saveB1);
    }

    tmpA = _mm_shuffle_epi32(a0, 0x1B);
    a1 = _mm_shuffle_epi32(a1, 0xB1);
    a0 = _mm_blend_epi16(tmpA, a1, 0xF0);
    a1 = _mm_alignr_epi8(a1, tmpA, 8);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(stA_), a0);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(stA_ + 4), a1);
    tmpB = _mm_shuffle_epi32(b0, 0x1B);
    b1 = _mm_shuffle_epi32(b1, 0xB1);
    b0 = _mm_blend_epi16(tmpB, b1, 0xF0);
    b1 = _mm_alignr_epi8(b1, tmpB, 8);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(stB_), b0);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(stB_ + 4), b1);
}
#endif

using TransformFn = void (*)(uint32_t*, const uint8_t*, size_t);

TransformFn pick_transform() {
#ifdef CB_HAVE_SHANI
    if (cpu_has_shani()) return transform_shani;
#endif
    return transform_scalar;
}

const TransformFn kTransform = pick_transform();

using Transform2Fn = void (*)(uint32_t*, const uint8_t*,
                              uint32_t*, const uint8_t*, size_t);

Transform2Fn pick_transform2() {
#ifdef CB_HAVE_SHANI
    if (cpu_has_shani()) return transform_shani_x2;
#endif
    return nullptr;
}

const Transform2Fn kTransform2 = pick_transform2();

// Pad/finalize: absorb the trailing `rem` bytes (rem < 64) plus the
// 0x80 pad and 64-bit big-endian bit length, then emit the digest.
void finalize(uint32_t st[8], const uint8_t* partial, size_t rem,
              uint64_t total_len, uint8_t out[32]) {
    uint8_t tail[128];
    std::memcpy(tail, partial, rem);
    tail[rem] = 0x80;
    size_t tail_len = rem + 1 <= 56 ? 64 : 128;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1 - 8);
    uint64_t bits = total_len * 8;
    for (int i = 0; i < 8; i++) {
        tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
    }
    kTransform(st, tail, tail_len / 64);
    for (int i = 0; i < 8; i++) {
        out[4 * i + 0] = uint8_t(st[i] >> 24);
        out[4 * i + 1] = uint8_t(st[i] >> 16);
        out[4 * i + 2] = uint8_t(st[i] >> 8);
        out[4 * i + 3] = uint8_t(st[i]);
    }
}

void digest(const uint8_t* data, size_t len, uint8_t out[32]) {
    uint32_t st[8];
    std::memcpy(st, H0, sizeof(st));
    size_t blocks = len / 64;
    kTransform(st, data, blocks);
    finalize(st, data + blocks * 64, len - blocks * 64, uint64_t(len), out);
}

// Hash two equal-length buffers through interleaved SHA-NI streams
// (falls back to two sequential digests without the extension).
void digest_pair(const uint8_t* a, const uint8_t* b, size_t len,
                 uint8_t outA[32], uint8_t outB[32]) {
    if (kTransform2 == nullptr) {
        digest(a, len, outA);
        digest(b, len, outB);
        return;
    }
    uint32_t stA[8], stB[8];
    std::memcpy(stA, H0, sizeof(stA));
    std::memcpy(stB, H0, sizeof(stB));
    size_t blocks = len / 64;
    kTransform2(stA, a, stB, b, blocks);
    finalize(stA, a + blocks * 64, len - blocks * 64, uint64_t(len), outA);
    finalize(stB, b + blocks * 64, len - blocks * 64, uint64_t(len), outB);
}

// Streaming SHA-256 over a file byte range without surfacing the bytes
// to the caller — the read+hash fusion for local chunk verification
// (verify reads every location of every chunk, reference
// src/file/file_part.rs:228-251).  `want` = UINT64_MAX hashes to EOF.
// Returns 0 ok, -1 open/read error, -2 file shorter than start+want.
int digest_file(const char* path, uint64_t start, uint64_t want,
                uint8_t out[32]) {
    std::FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    if (start != 0 && fseeko(f, static_cast<off_t>(start),
                             SEEK_SET) != 0) {
        std::fclose(f);
        return -1;
    }
    uint32_t st[8];
    std::memcpy(st, H0, sizeof(st));
    std::vector<uint8_t> buf(1 << 20);
    size_t rem = 0;  // partial block carried at buf[0..rem)
    uint64_t total = 0;
    const bool to_eof = want == UINT64_MAX;
    while (true) {
        size_t cap = buf.size() - rem;
        if (!to_eof) {
            uint64_t left = want - total;
            if (left < cap) cap = static_cast<size_t>(left);
        }
        if (cap == 0) break;
        size_t n = std::fread(buf.data() + rem, 1, cap, f);
        if (n == 0) {
            if (std::ferror(f)) {
                std::fclose(f);
                return -1;
            }
            break;  // EOF
        }
        total += n;
        size_t have = rem + n;
        size_t blocks = have / 64;
        kTransform(st, buf.data(), blocks);
        rem = have - blocks * 64;
        std::memmove(buf.data(), buf.data() + blocks * 64, rem);
    }
    std::fclose(f);
    if (!to_eof && total != want) return -2;
    finalize(st, buf.data(), rem, total, out);
    return 0;
}

}  // namespace sha256

// Fused encode+hash for one batch item, block-interleaved: each 32 KiB
// byte range runs the GF coefficient grid and then immediately feeds the
// (still L2-hot) data and fresh parity chunks into streaming SHA states
// — every byte crosses DRAM once for both jobs, where the sequential
// encode-then-hash shape re-reads all k+r rows for the hash pass.
void encode_hash_one(const uint8_t* mat, size_t r, size_t k,
                     const uint8_t* item, size_t s,
                     uint8_t* parity, uint8_t* hashes) {
    const size_t total = k + r;
    std::vector<uint32_t> st(total * 8);
    for (size_t j = 0; j < total; j++)
        std::memcpy(&st[j * 8], sha256::H0, 32);
    auto row = [&](size_t j) {
        return j < k ? item + j * s : parity + (j - k) * s;
    };
    size_t hashed = 0;  // bytes per row consumed by whole SHA blocks
    for (size_t off = 0; off < s; off += kApplyBlk) {
        size_t len = s - off < kApplyBlk ? s - off : kApplyBlk;
        if (r > 0) apply_block(mat, r, k, item, s, parity, off, len);
        size_t blocks = len / 64;  // short only on the final range
        if (blocks) {
            size_t j = 0;
            if (sha256::kTransform2 != nullptr) {
                for (; j + 1 < total; j += 2)
                    sha256::kTransform2(&st[j * 8], row(j) + off,
                                        &st[(j + 1) * 8],
                                        row(j + 1) + off, blocks);
            }
            for (; j < total; j++)
                sha256::kTransform(&st[j * 8], row(j) + off, blocks);
            hashed = off + blocks * 64;
        }
    }
    for (size_t j = 0; j < total; j++)
        sha256::finalize(&st[j * 8], row(j) + hashed, s - hashed,
                         static_cast<uint64_t>(s), hashes + j * 32);
}

// Run `fn(i)` for i in [0, n) across up to `nthreads` std::threads
// (<=0 => hardware concurrency).
template <typename Fn>
void parallel_for(size_t n, int nthreads, Fn fn) {
    size_t want = nthreads > 0
        ? static_cast<size_t>(nthreads)
        : static_cast<size_t>(std::thread::hardware_concurrency());
    if (want == 0) want = 1;
    size_t threads = want < n ? want : n;
    if (threads <= 1) {
        for (size_t i = 0; i < n; i++) fn(i);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (size_t t = 0; t < threads; t++) {
        pool.emplace_back([=]() {
            for (size_t i = t; i < n; i += threads) fn(i);
        });
    }
    for (auto& th : pool) th.join();
}

// ---- scheduled-XOR engine (ops/xor_schedule.py) ----
//
// Executes a pre-compiled XOR program over bit-planes: shards are
// transposed into 8 planes each (plane v, byte t8, bit b = bit v of
// shard byte 8*t8+b), the flat (dst, src, kind) op list runs as
// plane-wide XOR/copy/zero over an arena tiled to stay L1/L2-resident
// (arXiv:2108.02692's cache-tiling), and output planes transpose back
// to parity bytes — so every emitted byte is identical to the table
// codecs (the content-address invariant), while the per-byte k*r
// table work becomes wide XORs.
//
// Runtime dispatch discipline matches the SHA-NI/GFNI fixes above: no
// reliance on -march (the generic fallback build must still get SIMD
// here), raw-CPUID feature detection, AVX2 bodies behind a target
// attribute, SSE2 as the x86_64 baseline, portable scalar elsewhere —
// and the active level is forcible (cb_xor_set_impl) so the scalar
// fallback is pinned by a test, not trusted.
namespace xorsched {

// 8x8 bit-matrix transpose of a uint64 (byte i = row i, bit j = col
// j): the standard three delta-swaps; an involution, so it serves
// both directions (bytes -> planes and planes -> bytes).
inline uint64_t transpose8(uint64_t x) {
    uint64_t t;
    t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAULL;
    x = x ^ t ^ (t << 7);
    t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCULL;
    x = x ^ t ^ (t << 14);
    t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ULL;
    x = x ^ t ^ (t << 28);
    return x;
}

#if defined(__x86_64__) && defined(__GNUC__)
#define CB_XOR_X86 1
#include <cpuid.h>

// Raw-CPUID AVX2 detection (leaf 7 EBX bit 5) plus the OS half the
// feature bit alone doesn't prove: OSXSAVE (leaf 1 ECX bit 27) and
// XCR0 xmm+ymm state via xgetbv — an AVX2 CPU under an OS that never
// enables ymm state would fault on the first vector op.
bool cpu_has_avx2() {
    unsigned int eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
    if (!((ebx >> 5) & 1u)) return false;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
    if (!((ecx >> 27) & 1u)) return false;  // OSXSAVE
    unsigned int lo = 0, hi = 0;
    __asm__ volatile("xgetbv" : "=a"(lo), "=d"(hi) : "c"(0));
    return (lo & 0x6) == 0x6;  // XMM + YMM state enabled
}

// two-lane transpose8 (SSE2 shifts are per-64-bit-lane already)
inline __m128i transpose8_x2(__m128i x) {
    const __m128i mA = _mm_set1_epi64x(0x00AA00AA00AA00AALL);
    const __m128i mC = _mm_set1_epi64x(0x0000CCCC0000CCCCLL);
    const __m128i mF = _mm_set1_epi64x(0x00000000F0F0F0F0LL);
    __m128i t;
    t = _mm_and_si128(_mm_xor_si128(x, _mm_srli_epi64(x, 7)), mA);
    x = _mm_xor_si128(x, _mm_xor_si128(t, _mm_slli_epi64(t, 7)));
    t = _mm_and_si128(_mm_xor_si128(x, _mm_srli_epi64(x, 14)), mC);
    x = _mm_xor_si128(x, _mm_xor_si128(t, _mm_slli_epi64(t, 14)));
    t = _mm_and_si128(_mm_xor_si128(x, _mm_srli_epi64(x, 28)), mF);
    x = _mm_xor_si128(x, _mm_xor_si128(t, _mm_slli_epi64(t, 28)));
    return x;
}
#endif

int detect_best() {
#ifdef CB_XOR_X86
    return cpu_has_avx2() ? 2 : 1;  // SSE2 is the x86_64 baseline
#else
    return 0;
#endif
}

const int kXorBest = detect_best();
int g_xor_level = kXorBest;  // 0 scalar / 1 sse2 / 2 avx2

// -- split: 8*tl shard bytes -> 8 planes of tl bytes (p0 + v*stride) --

void split_scalar(const uint8_t* src, size_t tl, uint8_t* p0,
                  size_t stride) {
    for (size_t t = 0; t < tl; t++) {
        uint64_t w;
        std::memcpy(&w, src + 8 * t, 8);
        w = transpose8(w);
        for (int v = 0; v < 8; v++)
            p0[v * stride + t] = static_cast<uint8_t>(w >> (8 * v));
    }
}

#ifdef CB_XOR_X86
// movemask reads bit 7 of each byte; add_epi8(x, x) shifts each byte
// left one bit with no cross-byte traffic, so eight mask+shift rounds
// peel plane 7 down to plane 0 — 2 plane bytes per 16 source bytes.
void split_sse2(const uint8_t* src, size_t tl, uint8_t* p0,
                size_t stride) {
    size_t t = 0;
    for (; t + 2 <= tl; t += 2) {
        __m128i x = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(src + 8 * t));
        for (int v = 7; v >= 0; v--) {
            uint16_t m = static_cast<uint16_t>(_mm_movemask_epi8(x));
            std::memcpy(p0 + v * stride + t, &m, 2);
            x = _mm_add_epi8(x, x);
        }
    }
    if (t < tl) split_scalar(src + 8 * t, tl - t, p0 + t, stride);
}

__attribute__((target("avx2")))
void split_avx2(const uint8_t* src, size_t tl, uint8_t* p0,
                size_t stride) {
    size_t t = 0;
    for (; t + 4 <= tl; t += 4) {
        __m256i x = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + 8 * t));
        for (int v = 7; v >= 0; v--) {
            uint32_t m = static_cast<uint32_t>(_mm256_movemask_epi8(x));
            std::memcpy(p0 + v * stride + t, &m, 4);
            x = _mm256_add_epi8(x, x);
        }
    }
    if (t < tl) split_sse2(src + 8 * t, tl - t, p0 + t, stride);
}
#endif

// -- pack: 8 planes of tl bytes -> 8*tl output bytes --

void pack_scalar(const uint8_t* p0, size_t stride, size_t tl,
                 uint8_t* dst) {
    for (size_t t = 0; t < tl; t++) {
        uint64_t w = 0;
        for (int v = 0; v < 8; v++)
            w |= static_cast<uint64_t>(p0[v * stride + t]) << (8 * v);
        w = transpose8(w);
        std::memcpy(dst + 8 * t, &w, 8);
    }
}

#ifdef CB_XOR_X86
// 16 plane-byte columns at a time: a 3-level punpck tower turns the 8
// plane rows into 16 byte-groups [p0[u]..p7[u]], each transposed as a
// 64-bit lane pair — SSE2-baseline, so even the no-AVX2 build packs
// at vector speed.
void pack_sse2(const uint8_t* p0, size_t stride, size_t tl,
               uint8_t* dst) {
    size_t t = 0;
    for (; t + 16 <= tl; t += 16) {
        __m128i x0 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 0 * stride + t));
        __m128i x1 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 1 * stride + t));
        __m128i x2 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 2 * stride + t));
        __m128i x3 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 3 * stride + t));
        __m128i x4 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 4 * stride + t));
        __m128i x5 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 5 * stride + t));
        __m128i x6 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 6 * stride + t));
        __m128i x7 = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(p0 + 7 * stride + t));
        __m128i a0 = _mm_unpacklo_epi8(x0, x1);
        __m128i a1 = _mm_unpackhi_epi8(x0, x1);
        __m128i b0 = _mm_unpacklo_epi8(x2, x3);
        __m128i b1 = _mm_unpackhi_epi8(x2, x3);
        __m128i c0 = _mm_unpacklo_epi8(x4, x5);
        __m128i c1 = _mm_unpackhi_epi8(x4, x5);
        __m128i d0 = _mm_unpacklo_epi8(x6, x7);
        __m128i d1 = _mm_unpackhi_epi8(x6, x7);
        __m128i e0 = _mm_unpacklo_epi16(a0, b0);
        __m128i e1 = _mm_unpackhi_epi16(a0, b0);
        __m128i e2 = _mm_unpacklo_epi16(a1, b1);
        __m128i e3 = _mm_unpackhi_epi16(a1, b1);
        __m128i f0 = _mm_unpacklo_epi16(c0, d0);
        __m128i f1 = _mm_unpackhi_epi16(c0, d0);
        __m128i f2 = _mm_unpacklo_epi16(c1, d1);
        __m128i f3 = _mm_unpackhi_epi16(c1, d1);
        uint8_t* o = dst + 8 * t;
        __m128i g;
        g = transpose8_x2(_mm_unpacklo_epi32(e0, f0));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 0), g);
        g = transpose8_x2(_mm_unpackhi_epi32(e0, f0));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 16), g);
        g = transpose8_x2(_mm_unpacklo_epi32(e1, f1));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 32), g);
        g = transpose8_x2(_mm_unpackhi_epi32(e1, f1));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 48), g);
        g = transpose8_x2(_mm_unpacklo_epi32(e2, f2));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 64), g);
        g = transpose8_x2(_mm_unpackhi_epi32(e2, f2));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 80), g);
        g = transpose8_x2(_mm_unpacklo_epi32(e3, f3));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 96), g);
        g = transpose8_x2(_mm_unpackhi_epi32(e3, f3));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(o + 112), g);
    }
    if (t < tl) pack_scalar(p0 + t, stride, tl - t, dst + 8 * t);
}
#endif

// -- the wide-XOR inner loop (the op list's hot kernel) --

void xor_planes_scalar(uint8_t* dst, const uint8_t* src, size_t n) {
    size_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t a, b;
        std::memcpy(&a, dst + i, 8);
        std::memcpy(&b, src + i, 8);
        a ^= b;
        std::memcpy(dst + i, &a, 8);
    }
    for (; i < n; i++) dst[i] ^= src[i];
}

#ifdef CB_XOR_X86
void xor_planes_sse2(uint8_t* dst, const uint8_t* src, size_t n) {
    size_t i = 0;
    for (; i + 16 <= n; i += 16) {
        __m128i a = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(dst + i));
        __m128i b = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(src + i));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                         _mm_xor_si128(a, b));
    }
    for (; i < n; i++) dst[i] ^= src[i];
}

__attribute__((target("avx2")))
void xor_planes_avx2(uint8_t* dst, const uint8_t* src, size_t n) {
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i a = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(dst + i));
        __m256i b = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                            _mm256_xor_si256(a, b));
    }
    for (; i < n; i++) dst[i] ^= src[i];
}
#endif

void split(const uint8_t* src, size_t tl, uint8_t* p0, size_t stride) {
#ifdef CB_XOR_X86
    if (g_xor_level >= 2) return split_avx2(src, tl, p0, stride);
    if (g_xor_level >= 1) return split_sse2(src, tl, p0, stride);
#endif
    split_scalar(src, tl, p0, stride);
}

void pack(const uint8_t* p0, size_t stride, size_t tl, uint8_t* dst) {
#ifdef CB_XOR_X86
    if (g_xor_level >= 1) return pack_sse2(p0, stride, tl, dst);
#endif
    pack_scalar(p0, stride, tl, dst);
}

void xor_planes(uint8_t* dst, const uint8_t* src, size_t n) {
#ifdef CB_XOR_X86
    if (g_xor_level >= 2) return xor_planes_avx2(dst, src, n);
    if (g_xor_level >= 1) return xor_planes_sse2(dst, src, n);
#endif
    xor_planes_scalar(dst, src, n);
}

//: arena budget: n_planes * tile bytes; 256 KiB keeps the whole
//: working set L2-resident on everything this targets while leaving
//: room for the source/dest streams
constexpr size_t kXorArenaBytes = 256u << 10;

// One batch item: run the whole op list per tile so every plane's
// tile stays cache-hot across the program (the paper's L1-residency
// reordering, realized as an outer tile loop).
void xor_exec_one(const int32_t* ops, size_t n_ops, size_t n_planes,
                  size_t k, size_t r, const uint8_t* item, size_t s,
                  uint8_t* out) {
    const size_t P = s / 8;
    size_t tile = P;
    if (n_planes * tile > kXorArenaBytes) {
        tile = kXorArenaBytes / n_planes;
        tile &= ~static_cast<size_t>(15);
        if (tile == 0) tile = 16;
    }
    std::vector<uint8_t> arena(n_planes * tile);
    uint8_t* A = arena.data();
    const size_t out_base = n_planes - 8 * r;
    for (size_t lo = 0; lo < P; lo += tile) {
        const size_t tl = P - lo < tile ? P - lo : tile;
        for (size_t j = 0; j < k; j++)
            split(item + j * s + 8 * lo, tl, A + (8 * j) * tile, tile);
        for (size_t o = 0; o < n_ops; o++) {
            const int32_t dst = ops[3 * o];
            const int32_t src = ops[3 * o + 1];
            const int32_t kind = ops[3 * o + 2];
            uint8_t* d = A + static_cast<size_t>(dst) * tile;
            if (kind == 1) {
                xor_planes(d, A + static_cast<size_t>(src) * tile, tl);
            } else if (kind == 0) {
                // slot recycling may hand a copy's dst the arena slot
                // its src freed on this very op — already in place
                const uint8_t* sp = A + static_cast<size_t>(src) * tile;
                if (d != sp) std::memcpy(d, sp, tl);
            } else {
                std::memset(d, 0, tl);
            }
        }
        for (size_t i = 0; i < r; i++)
            pack(A + (out_base + 8 * i) * tile, tile, tl,
                 out + i * s + 8 * lo);
    }
}

}  // namespace xorsched

}  // namespace

extern "C" {

// out[b, r, s] = mat[r, k] (x) shards[b, k, s]; nthreads <= 0 => hardware.
void cb_apply_matrix(const uint8_t* mat, size_t r, size_t k,
                     const uint8_t* shards, size_t b, size_t s,
                     uint8_t* out, int nthreads) {
    if (!kInited || r == 0 || b == 0 || s == 0) return;
    parallel_for(b, nthreads, [=](size_t i) {
        apply_one(mat, r, k, shards + i * k * s, s, out + i * r * s);
    });
}

// Table self-check hook: lets Python assert C++ and numpy agree on the field.
uint8_t cb_gf_mul(uint8_t a, uint8_t b) { return MUL[a][b]; }

// Force the byte-table kernel tier (0 scalar table / 1 AVX2 pshufb /
// 2 GFNI); clamped to what this build+CPU actually has.  Returns the
// effective tier.  Bench --config 12 uses this to A/B the XOR engine
// against every tier a deployment might run; output bytes are
// identical at every tier (the tiers are the same math).
int cb_gf_set_level(int level) {
    if (level > g_gf_best) level = g_gf_best;
    if (level == 1 && !kGfCompiledSimd) level = 0;
    if (level < 0) level = 0;
    g_gf_level = level;
    return level;
}

int cb_gf_get_level(void) { return g_gf_level; }

// Scheduled-XOR executor (ops/xor_schedule.py): run the compiled
// (dst, src, kind) op list over bit-planes of every batch item.
//   ops[n_ops, 3] int32 over arena ids [inputs 8k | temps | outputs 8r]
//   out[b, r, s] = the schedule's matrix (x) shards[b, k, s]
// s must be a multiple of 8 (the Python gate guarantees it); batch
// items fan across std::threads like cb_apply_matrix, so a HostPipeline
// slice calling with nthreads=1 keeps total host parallelism at the
// scheduler's worker count.
void cb_xor_exec(const int32_t* ops, size_t n_ops, size_t n_planes,
                 size_t k, size_t r, const uint8_t* shards, size_t b,
                 size_t s, uint8_t* out, int nthreads) {
    if (!kInited || b == 0 || r == 0 || s == 0 || (s % 8) != 0) return;
    parallel_for(b, nthreads, [=](size_t i) {
        xorsched::xor_exec_one(ops, n_ops, n_planes, k, r,
                               shards + i * k * s, s, out + i * r * s);
    });
}

// Force the XOR engine's kernel tier (0 scalar / 1 SSE2 / 2 AVX2);
// clamped to the detected ceiling.  Returns the effective tier — the
// forced-scalar identity test pins the fallback path with this.
int cb_xor_set_impl(int level) {
    if (level > xorsched::kXorBest) level = xorsched::kXorBest;
    if (level < 0) level = 0;
    xorsched::g_xor_level = level;
    return level;
}

int cb_xor_get_impl(void) { return xorsched::g_xor_level; }

// SHA-256 of one buffer (SHA-NI when available).
void cb_sha256(const uint8_t* data, size_t len, uint8_t* out) {
    sha256::digest(data, len, out);
}

// SHA-256 of a file byte range; len = UINT64_MAX hashes start..EOF.
// 0 ok, -1 I/O error, -2 short file.
int cb_sha256_file(const char* path, uint64_t start, uint64_t len,
                   uint8_t* out) {
    return sha256::digest_file(path, start, len, out);
}

// 1 when the SHA-NI fast path is active (introspection for tests/bench).
int cb_sha256_is_accelerated(void) {
#ifdef CB_HAVE_SHANI
    return sha256::kTransform == sha256::transform_shani ? 1 : 0;
#else
    return 0;
#endif
}

// Hash n contiguous rows of length s: out[i*32..] = sha256(rows[i*s..]).
void cb_sha256_rows(const uint8_t* rows, size_t n, size_t s,
                    uint8_t* out, int nthreads) {
    // Pairs of rows share one interleaved SHA-NI instruction stream.
    parallel_for((n + 1) / 2, nthreads, [=](size_t pi) {
        size_t i = 2 * pi;
        if (i + 1 < n) {
            sha256::digest_pair(rows + i * s, rows + (i + 1) * s, s,
                                out + i * 32, out + (i + 1) * 32);
        } else {
            sha256::digest(rows + i * s, s, out + i * 32);
        }
    });
}

// Fused ingest step: parity + per-shard content hashes in one pass per
// batch item, while the item's shards are cache-hot.
//   out_parity[b, r, s]       = mat[r, k] (x) shards[b, k, s]
//   out_hashes[b, k + r, 32]  = sha256 of each data then parity shard
void cb_encode_hash(const uint8_t* mat, size_t r, size_t k,
                    const uint8_t* shards, size_t b, size_t s,
                    uint8_t* out_parity, uint8_t* out_hashes, int nthreads) {
    if (!kInited || b == 0 || s == 0) return;
    parallel_for(b, nthreads, [=](size_t i) {
        encode_hash_one(mat, r, k, shards + i * k * s, s,
                        out_parity + i * r * s,
                        out_hashes + i * (k + r) * 32);
    });
}

}  // extern "C"
