"""Placement abstraction: destinations hand out per-shard writers.

Mirrors the reference's ``CollectionDestination`` / ``ShardWriter`` traits
(src/file/collection_destination.rs): ``get_writers(count)`` for fresh
writes, ``get_used_writers(existing)`` for resilver (writers only for the
missing slots), and ``write_shard(hash, bytes) -> [Location]``.

Implementations here: weighted location lists (random weighted sample
without replacement), plain location lists (first-N), and the void
destination (discard — used to hash/measure without storing,
collection_destination.rs:113-132).  The cluster-aware destination with
zones/failover lives in chunky_bits_tpu/cluster/destination.py.
"""

from __future__ import annotations

import random
from typing import Optional, Protocol, Sequence, runtime_checkable

from chunky_bits_tpu.errors import NotEnoughWriters
from chunky_bits_tpu.file.hashing import AnyHash
from chunky_bits_tpu.file.location import Location, LocationContext
from chunky_bits_tpu.file.weighted_location import WeightedLocation


@runtime_checkable
class ShardWriter(Protocol):
    async def write_shard(self, hash_: AnyHash, data: bytes
                          ) -> list[Location]:  # pragma: no cover
        ...


class CollectionDestination(Protocol):
    def get_writers(self, count: int) -> list[ShardWriter]:  # pragma: no cover
        ...

    def get_used_writers(
        self, locations: Sequence[Optional[Location]]
    ) -> list[ShardWriter]:
        ...

    def get_context(self) -> LocationContext:
        ...


class _LocationWriter:
    """Binds a Location and a context into a ShardWriter."""

    def __init__(self, location: Location, cx: Optional[LocationContext]):
        self.location = location
        self.cx = cx

    async def write_shard(self, hash_: AnyHash, data: bytes) -> list[Location]:
        loc = await self.location.write_subfile(str(hash_), data, self.cx)
        return [loc]


class _BaseDestination:
    """Shared default implementations (collection_destination.rs:27-36)."""

    def get_used_writers(
        self, locations: Sequence[Optional[Location]]
    ) -> list[ShardWriter]:
        # Writers are needed for the *missing* (None) slots.  The reference's
        # default trait impl counts the present slots instead
        # (collection_destination.rs:30-35) — an inversion its own cluster
        # Destination does not share (destination.rs:62); the sane count is
        # used here.
        needed = sum(1 for loc in locations if loc is None)
        return self.get_writers(needed)

    def get_context(self) -> LocationContext:
        return LocationContext()


class WeightedLocationsDestination(_BaseDestination):
    """Weighted random sample without replacement
    (collection_destination.rs:56-73)."""

    def __init__(self, locations: Sequence[WeightedLocation],
                 cx: Optional[LocationContext] = None):
        self.locations = list(locations)
        self.cx = cx

    def get_writers(self, count: int) -> list[ShardWriter]:
        if len(self.locations) < count:
            raise NotEnoughWriters(
                f"need {count} writers, have {len(self.locations)}"
            )
        pool = list(self.locations)
        rng = random.Random()
        picked: list[ShardWriter] = []
        for _ in range(count):
            weights = [max(wl.weight, 0) for wl in pool]
            total = sum(weights)
            if total <= 0:
                # all-zero weights: fall back to uniform
                idx = rng.randrange(len(pool))
            else:
                idx = rng.choices(range(len(pool)), weights=weights, k=1)[0]
            picked.append(_LocationWriter(pool.pop(idx).location, self.cx))
        return picked


class LocationsDestination(_BaseDestination):
    """First-N placement over a plain location list
    (collection_destination.rs:75-84)."""

    def __init__(self, locations: Sequence[Location],
                 cx: Optional[LocationContext] = None):
        self.locations = [loc if isinstance(loc, Location)
                          else Location.parse(str(loc)) for loc in locations]
        self.cx = cx

    def get_writers(self, count: int) -> list[ShardWriter]:
        if len(self.locations) < count:
            raise NotEnoughWriters(
                f"need {count} writers, have {len(self.locations)}"
            )
        return [_LocationWriter(loc, self.cx)
                for loc in self.locations[:count]]


def as_destination(obj) -> "CollectionDestination":
    """Coerce the shapes the reference accepts as destinations: a
    CollectionDestination passes through; a list of WeightedLocations
    becomes weighted sampling (collection_destination.rs:56-73); a list
    of Locations (or location strings) becomes first-N placement
    (collection_destination.rs:75-84); None becomes the void (the
    builder's default, like the reference's ``()`` unit destination).

    An *empty* collection is a LocationsDestination that raises
    NotEnoughWriters on use — never a silent discard; mixing weighted
    and unweighted entries is a type error rather than a repr-parse."""
    if obj is None:
        return VoidDestination()
    if isinstance(obj, (list, tuple)):
        n_weighted = sum(isinstance(x, WeightedLocation) for x in obj)
        if n_weighted and n_weighted != len(obj):
            raise TypeError(
                "destination list mixes WeightedLocation with plain "
                "locations; use one or the other")
        if obj and n_weighted == len(obj):
            return WeightedLocationsDestination(list(obj))
        return LocationsDestination(list(obj))
    return obj


class _VoidWriter:
    async def write_shard(self, hash_: AnyHash, data: bytes) -> list[Location]:
        return []


class VoidDestination(_BaseDestination):
    """Sends shards to the void; used to test/measure the codec without
    storage (collection_destination.rs:113-132)."""

    def get_writers(self, count: int) -> list[ShardWriter]:
        return [_VoidWriter() for _ in range(count)]
