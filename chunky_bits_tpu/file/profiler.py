"""Per-operation I/O telemetry.

Mirrors the reference profiler (src/file/profiler.rs): reads and writes are
logged with result, location, byte length and start/end times; a reporter
drains the log into a ``ProfileReport`` exposing average read/write durations
and wall/byte totals (profiler.rs:240-329).  A thread-safe in-memory log
replaces the reference's unbounded-channel collector task — same observable
API, no background task to leak.

Two extensions beyond the reference:

* **Bounded rings.**  The reference's collector is unbounded (an
  unread channel grows forever, profiler.rs:33-65) and so were the
  in-memory logs here: in a long-running gateway with no reporter
  draining them, ``_requests``/``_entries``/``_location_failures`` were
  a slow leak.  Each is now a count-bounded drop-oldest ring
  (``MAX_REQUESTS``/``MAX_ENTRIES``/``MAX_LOCATION_FAILURES``) with the
  drops COUNTED — surfaced in the report (``Dropped<...>``) and the
  metrics registry (``cb_profiler_dropped_total``) so a saturated ring
  is an observable fact, not silent data loss.
* **Registry feed.**  Every ``log_request``/``log_read``/``log_write``
  also records into the process metrics registry
  (``obs/metrics.py``: latency histograms + byte counters) and, when a
  trace is active, a span onto the current request's trace — the
  Profiler stays the one choke point all three telemetry surfaces
  (stanza strings, /metrics series, /debug/traces spans) derive from,
  so they can never disagree.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Optional

from chunky_bits_tpu.obs import metrics as obs_metrics
from chunky_bits_tpu.obs import tracing as obs_tracing

#: the clock seam (canonical surface cluster/clock.py; utils-side
#: import for cycle hygiene): the ``start_time`` values callers pass
#: to log_read/log_write come off this clock, so the matching ``end``
#: read must too — mixing timebases would corrupt every duration the
#: moment the simulator installs a virtual clock
from chunky_bits_tpu.utils import clock as _clock


def percentile(sorted_values: list, q: float) -> float:
    """Linear-interpolated percentile of an ascending-sorted list (the
    numpy 'linear' method, stdlib-only so this module stays
    dependency-free).  THE percentile implementation for the serving
    plane: the gateway access log's stats and bench --config 9's
    client-side latency report both call it, so production p99s and
    bench p99s are computed by the same code."""
    if not sorted_values:
        return 0.0
    if len(sorted_values) == 1:
        return float(sorted_values[0])
    pos = (len(sorted_values) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return float(sorted_values[lo]) * (1 - frac) \
        + float(sorted_values[hi]) * frac


@dataclass
class RequestLog:
    """One gateway request (the access-log record): what was asked,
    what was answered, how long it took and where the bytes came from.
    ``source`` is the serving path: "cache" (all chunks pre-verified in
    the read cache), "sendfile" (zero-copy local whole-chunk stream),
    "cond" (304, zero body bytes), "meta" (HEAD — headers only),
    "store" (fetch+verify+reassemble), or "-" (errors / PUTs)."""

    method: str
    path: str
    status: int
    nbytes: int
    duration: float  # seconds of wall time
    source: str
    #: resolved QoS tenant (cluster/qos.py closed table) or "-" when
    #: the scheduler is off — lets one access log answer per-tenant
    #: p99 questions (tenant_request_stats) without a second log
    tenant: str = "-"


@dataclass
class RequestStats:
    """Aggregate of the drained access log (percentiles via
    :func:`percentile`, shared with bench --config 9)."""

    count: int
    errors: int  # status >= 500
    total_bytes: int
    p50_ms: float
    p99_ms: float
    p999_ms: float

    def to_obj(self) -> dict:
        """Plain-dict form — the gateway's ``/stats`` payload and the
        ``chunky-bits stats`` renderer both read this, so serving
        percentiles stay one implementation away from the source."""
        return {
            "count": self.count,
            "errors": self.errors,
            "total_bytes": self.total_bytes,
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "p999_ms": round(self.p999_ms, 3),
        }

    def __str__(self) -> str:
        return (f"Requests<n={self.count} errors={self.errors} "
                f"bytes={self.total_bytes} p50={self.p50_ms:.2f}ms "
                f"p99={self.p99_ms:.2f}ms p999={self.p999_ms:.2f}ms>")


def request_stats(entries: list) -> RequestStats:
    """Roll a list of :class:`RequestLog` into :class:`RequestStats`."""
    lat = sorted(e.duration for e in entries)
    return RequestStats(
        count=len(entries),
        errors=sum(1 for e in entries if e.status >= 500),
        total_bytes=sum(e.nbytes for e in entries),
        p50_ms=percentile(lat, 50) * 1000.0,
        p99_ms=percentile(lat, 99) * 1000.0,
        p999_ms=percentile(lat, 99.9) * 1000.0,
    )


def tenant_request_stats(entries: list) -> dict:
    """Per-tenant :class:`RequestStats` split of the access log —
    the serving-plane isolation question ("whose p99 moved?") answered
    from the SAME records and the SAME :func:`percentile` code as the
    aggregate.  Key count is bounded by the closed tenant table
    (cluster/qos.py) plus "-" for scheduler-off records."""
    by_tenant: dict = {}
    for e in entries:
        by_tenant.setdefault(getattr(e, "tenant", "-"), []).append(e)
    return {tenant: request_stats(rows)
            for tenant, rows in sorted(by_tenant.items())}


@dataclass
class ResultLog:
    kind: str  # "read" | "write"
    ok: bool
    error: Optional[str]
    location: object
    length: int  # bytes moved (read: bytes returned; write: bytes sent)
    start_time: float
    end_time: float

    @property
    def duration(self) -> float:
        return self.end_time - self.start_time


class Profiler:
    """Handed to a LocationContext; log_* is called at the two I/O hooks
    (reference: src/file/location.rs:109-112,240-242).  All in-memory
    logs are drop-oldest rings (see the module docstring) — the recent
    window a reporter actually reads survives, the unbounded tail a
    reporterless gateway would accumulate does not."""

    #: ring bounds: generous next to any reporter's drain cadence (a
    #: bench config-9 run logs a few thousand requests), tiny next to a
    #: week of undrained gateway traffic
    MAX_REQUESTS = 65536
    MAX_ENTRIES = 65536
    MAX_LOCATION_FAILURES = 1024

    def __init__(self, max_requests: Optional[int] = None,
                 max_entries: Optional[int] = None,
                 max_location_failures: Optional[int] = None) -> None:
        self._entries: deque[ResultLog] = deque(
            maxlen=max_entries or self.MAX_ENTRIES)
        self._lock = threading.Lock()
        self._caches: list = []  # read caches whose counters we surface
        self._pipelines: list = []  # host pipelines ditto
        self._healths: list = []  # location-health scoreboards ditto
        self._scrubs: list = []  # scrub daemons ditto
        self._slos: list = []  # SLO engines (obs/slo.py) ditto
        self._qos: list = []  # QoS schedulers (cluster/qos.py) ditto
        # per-location failure notes from the read fall-through
        # (fetch_chunk): which location failed / was corrupt and why —
        # the diagnosable trail the anonymous `except LocationError:
        # continue` used to swallow
        self._location_failures: deque[tuple[object, str]] = deque(
            maxlen=max_location_failures or self.MAX_LOCATION_FAILURES)
        # gateway access-log records (one per HTTP request) — the
        # serving-plane analogue of the per-I/O entries above
        self._requests: deque[RequestLog] = deque(
            maxlen=max_requests or self.MAX_REQUESTS)
        # drop-oldest accounting per ring (also counted into the
        # metrics registry as cb_profiler_dropped_total{kind})
        self._dropped = {"requests": 0, "entries": 0,
                         "location_failures": 0}

    def _append(self, ring: deque, kind: str, item: object) -> bool:
        """Ring append with drop accounting; caller holds the lock and
        reports a True return to the registry AFTER releasing it (no
        foreign lock is ever taken under ``self._lock``)."""
        dropped = ring.maxlen is not None and len(ring) == ring.maxlen
        if dropped:
            self._dropped[kind] += 1
        ring.append(item)
        return dropped

    def drop_counts(self) -> dict:
        """Per-ring drop-oldest counts since construction."""
        with self._lock:
            return dict(self._dropped)

    def attach_cache(self, cache) -> None:
        """Register a chunk cache so its hit/miss/eviction/singleflight
        counters ride along in the report — cache hits never reach the
        read hooks, so without this a fully hot read profiles as zero
        I/O and zero everything else."""
        with self._lock:
            if all(c is not cache for c in self._caches):
                self._caches.append(cache)

    def cache_stats(self) -> list:
        """Snapshot of each attached cache's counters (CacheStats)."""
        with self._lock:
            return [c.stats() for c in self._caches]

    def attach_pipeline(self, pipeline) -> None:
        """Register a host pipeline (parallel/host_pipeline.py) so its
        per-stage busy/idle/bytes counters ride along in the report —
        hashing and encode run on its workers, not at the I/O hooks, so
        saturation would otherwise be invisible here."""
        with self._lock:
            if all(p is not pipeline for p in self._pipelines):
                self._pipelines.append(pipeline)

    def pipeline_stats(self) -> list:
        """Snapshot of each attached pipeline's counters
        (PipelineStats)."""
        with self._lock:
            return [p.stats() for p in self._pipelines]

    def attach_health(self, health) -> None:
        """Register a location-health scoreboard
        (cluster/health.py) so its per-location table — EWMA latency,
        error rate, breaker state, hedges fired/won/cancelled — rides
        along in read/write reports."""
        with self._lock:
            if all(h is not health for h in self._healths):
                self._healths.append(health)

    def health_stats(self) -> list:
        """Snapshot of each attached scoreboard (HealthStats)."""
        with self._lock:
            return [h.stats() for h in self._healths]

    def attach_scrub(self, scrub) -> None:
        """Register a scrub daemon (cluster/scrub.py) so its
        scanned/verified/corrupt/repaired counters and byte-rate ride
        along in the report — scrub I/O happens outside any one
        operation's hooks, so without this a scrubbed cluster's reports
        would not show the background verification at all."""
        with self._lock:
            if all(s is not scrub for s in self._scrubs):
                self._scrubs.append(scrub)

    def scrub_stats(self) -> list:
        """Snapshot of each attached scrub daemon (ScrubStats)."""
        with self._lock:
            return [s.stats() for s in self._scrubs]

    def attach_slo(self, engine) -> None:
        """Register an SLO engine (obs/slo.py) so firing/pending alert
        counts ride along in the report's ``Slo<...>`` stanza — the
        report and ``GET /alerts`` must tell one story (the PR-8
        one-set-of-numbers discipline)."""
        with self._lock:
            if all(e is not engine for e in self._slos):
                self._slos.append(engine)

    def slo_stats(self) -> list:
        """Snapshot of each attached SLO engine (SloStats)."""
        with self._lock:
            return [e.stats() for e in self._slos]

    def attach_qos(self, scheduler) -> None:
        """Register a QoS scheduler (cluster/qos.py) so per-tenant
        admission/shed/queue counters ride along in the report's
        ``Qos<...>`` stanza — the same snapshot ``/stats`` and the
        ``cb_qos_*`` families read (one set of numbers)."""
        with self._lock:
            if all(q is not scheduler for q in self._qos):
                self._qos.append(scheduler)

    def qos_stats(self) -> list:
        """Snapshot of each attached QoS scheduler (QosStats)."""
        with self._lock:
            return [q.stats() for q in self._qos]

    def log_location_failure(self, location, error: str) -> None:
        """A per-location read failure (unreadable or hash-mismatched)
        recorded by the chunk fall-through — the read completed via
        another location or reconstruction, but a degraded cluster must
        stay diagnosable."""
        with self._lock:
            dropped = self._append(self._location_failures,
                                   "location_failures",
                                   (location, error))
        if dropped:
            obs_metrics.record_dropped("location_failures")

    def drain_location_failures(self) -> list[tuple[object, str]]:
        with self._lock:
            out = list(self._location_failures)
            self._location_failures.clear()
        return out

    def log_request(self, method: str, path: str, status: int,
                    nbytes: int, duration: float, source: str,
                    tenant: str = "-") -> None:
        """One gateway request completed (gateway/http.py's access-log
        middleware): the same counters production logs print feed the
        report's :class:`RequestStats`, so serving percentiles come
        from one code path whether read off a log line or a bench
        run.  ``tenant`` is the resolved QoS tenant ("-" = scheduler
        off) — it stays OUT of the registry's request families (the
        per-tenant series are the scheduler's own ``cb_qos_*``) and
        IN the access log for :func:`tenant_request_stats`."""
        entry = RequestLog(method, path, status, nbytes, duration,
                           source, tenant)
        with self._lock:
            dropped = self._append(self._requests, "requests", entry)
        if dropped:
            obs_metrics.record_dropped("requests")
        obs_metrics.record_request(method, status, nbytes, duration,
                                   source)

    def drain_requests(self) -> list[RequestLog]:
        with self._lock:
            out = list(self._requests)
            self._requests.clear()
        return out

    def peek_requests(self) -> list[RequestLog]:
        """Non-draining snapshot of the request ring — the gateway's
        ``/stats`` summary must not steal entries from a reporter."""
        with self._lock:
            return list(self._requests)

    def log_read(self, ok: bool, error: Optional[str], location,
                 length: int, start_time: float) -> None:
        end = _clock.monotonic()
        entry = ResultLog("read", ok, error, location, length,
                          start_time, end)
        with self._lock:
            dropped = self._append(self._entries, "entries", entry)
        if dropped:
            obs_metrics.record_dropped("entries")
        obs_metrics.record_io("read", ok, length, end - start_time)
        # no io.read span: the read path's network time is already
        # attributed by the enclosing chunk_fetch span
        # (file/file_part.py) — a second span here would double-count
        # plane_ms["network"] in /debug/traces

    def log_write(self, ok: bool, error: Optional[str], location,
                  length: int, start_time: float) -> None:
        end = _clock.monotonic()
        entry = ResultLog("write", ok, error, location, length,
                          start_time, end)
        with self._lock:
            dropped = self._append(self._entries, "entries", entry)
        if dropped:
            obs_metrics.record_dropped("entries")
        obs_metrics.record_io("write", ok, length, end - start_time)
        obs_tracing.record_span("io.write", "network", start_time,
                                end - start_time,
                                "ok" if ok else "error")

    def drain(self) -> list[ResultLog]:
        with self._lock:
            out = list(self._entries)
            self._entries.clear()
        return out


class ProfileReport:
    def __init__(self, entries: list[ResultLog], cache_stats: list = (),
                 pipeline_stats: list = (), health_stats: list = (),
                 location_failures: list = (), requests: list = (),
                 scrub_stats: list = (), slo_stats: list = (),
                 dropped: Optional[dict] = None,
                 qos_stats: list = ()):
        self.entries = entries
        self.cache_stats = list(cache_stats)
        self.pipeline_stats = list(pipeline_stats)
        self.health_stats = list(health_stats)
        self.location_failures = list(location_failures)
        self.requests = list(requests)
        self.scrub_stats = list(scrub_stats)
        self.slo_stats = list(slo_stats)
        self.qos_stats = list(qos_stats)
        self.dropped = dict(dropped or {})

    def _avg(self, kind: str) -> Optional[float]:
        durations = [e.duration for e in self.entries if e.kind == kind]
        if not durations:
            return None
        return sum(durations) / len(durations)

    def average_read_duration(self) -> Optional[float]:
        return self._avg("read")

    def average_write_duration(self) -> Optional[float]:
        return self._avg("write")

    def total_time(self) -> Optional[float]:
        if not self.entries:
            return None
        return self.entries[-1].end_time - self.entries[0].start_time

    def total_bytes(self) -> int:
        return sum(e.length for e in self.entries if e.ok)

    def __str__(self) -> str:
        def ms(v: Optional[float]) -> str:
            return "None" if v is None else str(int(v * 1000))

        base = (
            f"ReadAvg<{ms(self.average_read_duration())}ms> "
            f"WriteAvg<{ms(self.average_write_duration())}ms> "
            f"Total<{ms(self.total_time())}ms> Total<{self.total_bytes()}B>"
        )
        for stats in self.cache_stats:
            base += f" {stats}"
        for stats in self.pipeline_stats:
            base += f" {stats}"
        for stats in self.health_stats:
            base += f" {stats}"
        for stats in self.scrub_stats:
            base += f" {stats}"
        for stats in self.slo_stats:
            base += f" {stats}"
        for stats in self.qos_stats:
            base += f" {stats}"
        if self.requests:
            base += f" {request_stats(self.requests)}"
        if self.location_failures:
            shown = "; ".join(f"{loc}: {err}"
                              for loc, err in self.location_failures[:8])
            extra = len(self.location_failures) - 8
            if extra > 0:
                shown += f"; +{extra} more"
            base += f" ReadFailures<{shown}>"
        drops = {k: v for k, v in self.dropped.items() if v}
        if drops:
            inner = " ".join(f"{k}={v}" for k, v in sorted(drops.items()))
            base += f" Dropped<{inner}>"
        return base


class ProfileReporter:
    """Pairs with a Profiler (reference: new_profiler(), profiler.rs:33-65)."""

    def __init__(self, profiler: Profiler):
        self._profiler = profiler

    def profile(self) -> ProfileReport:
        return ProfileReport(self._profiler.drain(),
                             self._profiler.cache_stats(),
                             self._profiler.pipeline_stats(),
                             self._profiler.health_stats(),
                             self._profiler.drain_location_failures(),
                             self._profiler.drain_requests(),
                             self._profiler.scrub_stats(),
                             self._profiler.slo_stats(),
                             self._profiler.drop_counts(),
                             self._profiler.qos_stats())


def new_profiler() -> tuple[Profiler, ProfileReporter]:
    p = Profiler()
    return p, ProfileReporter(p)
