"""Whole-file metadata: the durable state of the system.

Wire-compatible with the reference's ``FileReference``
(src/file/file_reference.rs:38-46; schema documented in README.md:44-60):

    content_type: <optional str>
    compression:  <optional — reserved>
    length: <u64>
    parts:
      - chunksize: <usize>
        data:   [{sha256: <hex>, locations: [...]}, ...]
        parity: [{sha256: <hex>, locations: [...]}, ...]

The reference's Python read-only decoder (python/chunky-bits.py) can read
references written by this framework unchanged — that is the interop
contract.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.file_part import (
    FileIntegrity,
    FilePart,
    ResilverPartReport,
    VerifyPartReport,
)
from chunky_bits_tpu.file.location import Location, LocationContext
from chunky_bits_tpu.utils import aio

RESILVER_CONCURRENCY = 10  # parts in flight (file_reference.rs:110)


@dataclass
class FileReference:
    length: Optional[int]
    parts: list[FilePart]
    content_type: Optional[str] = None
    compression: Optional[str] = None

    def len_bytes(self) -> int:
        if self.length is not None:
            return self.length
        return sum(part.len_bytes() for part in self.parts)

    # ---- serde ----

    def to_obj(self) -> dict:
        obj: dict = {}
        if self.compression is not None:
            obj["compression"] = self.compression
        if self.content_type is not None:
            obj["content_type"] = self.content_type
        obj["length"] = self.length
        obj["parts"] = [p.to_obj() for p in self.parts]
        return obj

    @classmethod
    def from_obj(cls, obj: dict) -> "FileReference":
        if not isinstance(obj, dict) or "parts" not in obj:
            raise SerdeError("file reference must be a mapping with 'parts'")
        length = obj.get("length")
        return cls(
            length=int(length) if length is not None else None,
            parts=[FilePart.from_obj(p) for p in obj["parts"]],
            content_type=obj.get("content_type"),
            compression=obj.get("compression"),
        )

    # ---- builders ----

    def read_builder(self, cx: Optional[LocationContext] = None):
        from chunky_bits_tpu.file.reader import FileReadBuilder

        builder = FileReadBuilder(self)
        if cx is not None:
            builder = builder.location_context(cx)
        return builder

    @staticmethod
    def write_builder():
        from chunky_bits_tpu.file.writer import FileWriteBuilder

        return FileWriteBuilder()

    # ---- verify / resilver fan-out (file_reference.rs:78-113) ----

    async def verify(self, cx: Optional[LocationContext] = None,
                     pipeline=None) -> "VerifyFileReport":
        # Bounded parts-in-flight, like resilver.  The reference gathers
        # every part at once (file_reference.rs:78-87) — unbounded sockets
        # on a 10 GiB file; bounding is a deliberate improvement.
        from chunky_bits_tpu.parallel.host_pipeline import get_host_pipeline

        sem = asyncio.Semaphore(RESILVER_CONCURRENCY)
        # ONE host pipeline across the whole file: the ~10x10 in-flight
        # location reads funnel their SHA-256 re-hash through its
        # min(N, nproc) workers instead of one thread (multi-core
        # verify), and the report's profiler sees one set of counters
        pipe = pipeline if pipeline is not None else get_host_pipeline()

        async def one(part: FilePart) -> "VerifyPartReport":
            async with sem:
                return await part.verify(cx, pipeline=pipe)

        reports = await aio.gather_or_cancel(
            [one(p) for p in self.parts])
        return VerifyFileReport(list(reports))

    async def resilver(self, destination,
                       cx: Optional[LocationContext] = None,
                       backend: Optional[str] = None,
                       pipeline=None) -> "ResilverFileReport":
        from chunky_bits_tpu.ops.batching import ReconstructBatcher
        from chunky_bits_tpu.parallel.host_pipeline import get_host_pipeline

        sem = asyncio.Semaphore(RESILVER_CONCURRENCY)
        # All in-flight parts share one batcher: parts degraded by the same
        # node loss share an erasure pattern and rebuild in one dispatch.
        batcher = ReconstructBatcher(backend=backend)
        # ...and one host pipeline: shard re-hash during the re-read
        # phase runs sliced across its workers (see verify above)
        pipe = pipeline if pipeline is not None else get_host_pipeline()

        async def one(part: FilePart) -> ResilverPartReport:
            async with sem:
                return await part.resilver(destination, cx, backend=backend,
                                           batcher=batcher, pipeline=pipe)

        try:
            # on failure siblings are cancelled before the drain below, so
            # no part can submit fresh batcher work after aclose
            reports = await aio.gather_or_cancel(
                [one(p) for p in self.parts])
        finally:
            await batcher.aclose()
        return ResilverFileReport(list(reports))


class _FileReportBase:
    """Roll-ups across parts (file_reference.rs:149-239)."""

    part_reports: list

    def integrity(self) -> FileIntegrity:
        current = FileIntegrity.VALID
        for report in self.part_reports:
            part_integrity = report.integrity()
            if part_integrity > current:
                current = part_integrity
        return current

    def is_ideal(self) -> bool:
        return self.integrity().is_ideal()

    def is_available(self) -> bool:
        return self.integrity().is_available()

    def total_parts(self) -> int:
        return len(self.part_reports)

    def total_chunks(self) -> int:
        return sum(r.total_chunks() for r in self.part_reports)

    def healthy_parts(self) -> list[FilePart]:
        return [r.file_part for r in self.part_reports
                if not r.unhealthy_chunks()]

    def healthy_chunks(self):
        return [c for r in self.part_reports for c in r.healthy_chunks()]

    def unhealthy_chunks(self):
        return [c for r in self.part_reports for c in r.unhealthy_chunks()]

    def unavailable_locations(self):
        return [t for r in self.part_reports
                for t in r.unavailable_locations()]

    def invalid_locations(self) -> list[Location]:
        return [loc for r in self.part_reports
                for loc in r.invalid_locations()]

    def locations_with_integrity(self):
        for r in self.part_reports:
            yield from r.locations_with_integrity()

    def display_full_report(self) -> str:
        out = [f"file\t{self.integrity()}\n"]
        for r in self.part_reports:
            out.append(r.display_full_report())
        return "\n".join(out)


class VerifyFileReport(_FileReportBase):
    def __init__(self, part_reports: list[VerifyPartReport]):
        self.part_reports = part_reports

    def __str__(self) -> str:
        # The reference prints the *healthy* count under the "unhealthy"
        # label (file_reference.rs:243-252); corrected here.
        unhealthy = self.total_parts() - len(self.healthy_parts())
        return (
            f"{self.integrity()}: {unhealthy}/"
            f"{self.total_parts()} unhealthy parts"
        )


class ResilverFileReport(_FileReportBase):
    def __init__(self, part_reports: list[ResilverPartReport]):
        self.part_reports = part_reports

    def rebuild_errors(self) -> list[Optional[str]]:
        return [r.rebuild_error() for r in self.part_reports]

    def new_locations(self) -> list[Location]:
        return [loc for r in self.part_reports for loc in r.new_locations()]

    def successful_writes(self):
        return [w for r in self.part_reports for w in r.successful_writes()]

    def failed_writes(self) -> list[str]:
        return [e for r in self.part_reports for e in r.failed_writes()]

    def resilvered_parts(self) -> list[FilePart]:
        return [r.file_part for r in self.part_reports]

    def modified_parts(self) -> list[FilePart]:
        return [r.file_part for r in self.part_reports
                if r.successful_writes()]

    def __str__(self) -> str:
        return (
            f"{self.integrity()}: {len(self.modified_parts())}/"
            f"{self.total_parts()} parts modified"
        )
