"""The unit of placement: a content hash plus the locations holding it.

Wire format matches the reference (src/file/chunk.rs:14-18, hash flattened):

    sha256: <hex>
    locations: [<location string>, ...]

TPU-repo extension (repair-bandwidth plane, cluster/repair.py): an
OPTIONAL per-chunk block-digest tree under the ``blocks`` key —

    blocks: {size: <block bytes>, sha256: [<hex>, ...]}

— written on the encode path when the ``repair_block_bytes`` tunable is
set, letting scrub/verify localize corruption to fixed-size block
ranges instead of whole chunks (the repair planner then moves ≈damage
bytes off helpers instead of d whole chunks).  Strictly additive:
references without the key parse, verify and repair exactly as before,
and the read-only interop decoder (python/chunky-bits.py, like the
reference's) ignores it.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.hashing import AnyHash
from chunky_bits_tpu.file.location import Location


@dataclass(frozen=True)
class BlockDigests:
    """Per-chunk damage-localization tree: one sha256 per fixed-size
    block of the chunk's content (last block may run short).  A content
    property like the chunk hash — identical across replicas — so it
    lives on the chunk, not on any location."""

    size: int  # block size in bytes (> 0)
    digests: tuple[bytes, ...]  # 32-byte sha256 per block, in order

    def __post_init__(self) -> None:
        if self.size <= 0:
            raise SerdeError("block size must be > 0")
        if not self.digests:
            raise SerdeError("block digests must be non-empty")
        if any(len(d) != 32 for d in self.digests):
            raise SerdeError("block digests must be 32 bytes each")

    @classmethod
    def from_buf(cls, data, size: int) -> "BlockDigests":
        """Digest tree of ``data`` (any buffer) at block ``size``."""
        view = memoryview(data)
        digests = [
            hashlib.sha256(view[off: off + size]).digest()
            for off in range(0, max(len(view), 1), size)
        ]
        return cls(size=int(size), digests=tuple(digests))

    def covers(self, length: int) -> bool:
        """True when this tree describes a buffer of ``length`` bytes
        (block count matches — the localization precondition)."""
        blocks = max((length + self.size - 1) // self.size, 1)
        return len(self.digests) == blocks

    def damaged_ranges(self, data) -> Optional[list[tuple[int, int]]]:
        """Merged ``(start, length)`` ranges of ``data`` whose blocks
        mismatch this tree, or ``None`` when localization does not apply
        (length mismatch — e.g. a truncated replica, whose damage extent
        the tree cannot bound).  ``[]`` means every block matches."""
        view = memoryview(data)
        if not self.covers(len(view)):
            return None
        out: list[tuple[int, int]] = []
        for bi, digest in enumerate(self.digests):
            start = bi * self.size
            block = view[start: start + self.size]
            if hashlib.sha256(block).digest() == digest:
                continue
            if out and out[-1][0] + out[-1][1] == start:
                prev = out.pop()
                out.append((prev[0], prev[1] + len(block)))
            else:
                out.append((start, len(block)))
        return out

    def verify_range(self, data, start: int) -> Optional[bool]:
        """Check ``data`` (bytes read at chunk offset ``start``) against
        the tree: ``True``/``False`` when the range is block-aligned and
        block-sized (so each covered block is wholly present), ``None``
        when the tree cannot judge it (unaligned, or the range runs past
        the covered blocks without being the short tail)."""
        view = memoryview(data)
        if start % self.size != 0 or not view.nbytes:
            return None
        first = start // self.size
        blocks = (view.nbytes + self.size - 1) // self.size
        if first + blocks > len(self.digests):
            return None
        if view.nbytes % self.size and first + blocks != len(self.digests):
            return None  # short middle read: not a whole-block range
        for bi in range(blocks):
            off = bi * self.size
            block = view[off: off + self.size]
            if hashlib.sha256(block).digest() != self.digests[first + bi]:
                return False
        return True

    def to_obj(self) -> dict:
        return {"size": self.size,
                "sha256": [d.hex() for d in self.digests]}

    @classmethod
    def from_obj(cls, obj: object) -> Optional["BlockDigests"]:
        """Lenient parse: anything malformed reads as None (no tree) —
        a damaged/foreign ``blocks`` stanza must degrade the chunk to
        whole-chunk repair, never brick parsing of its reference."""
        if not isinstance(obj, dict):
            return None
        try:
            size = int(obj["size"])
            digests = tuple(bytes.fromhex(h) for h in obj["sha256"])
            return cls(size=size, digests=digests)
        except (KeyError, TypeError, ValueError, SerdeError):
            return None


@dataclass
class Chunk:
    hash: AnyHash
    locations: list[Location] = field(default_factory=list)
    #: optional block-digest tree for damage localization (see module
    #: docstring); None on references written before the tunable, or
    #: when the chunk is no longer than one block
    blocks: Optional[BlockDigests] = None

    def cache_key(self) -> "bytes | None":
        """Key for the content-addressed read cache: the raw sha256
        digest, or None for any future non-sha256 algorithm (those
        chunks simply bypass the cache rather than risk a key clash
        across hash domains)."""
        if self.hash.algorithm != "sha256":
            return None
        return self.hash.value.digest

    def to_obj(self) -> dict:
        obj = {
            self.hash.algorithm: self.hash.value.hex(),
            "locations": [str(loc) for loc in self.locations],
        }
        if self.blocks is not None:
            obj["blocks"] = self.blocks.to_obj()
        return obj

    @classmethod
    def from_obj(cls, obj: dict) -> "Chunk":
        if not isinstance(obj, dict):
            raise SerdeError(f"chunk must be a mapping, got {type(obj)}")
        hash_ = None
        for algo in ("sha256",):
            if algo in obj:
                hash_ = AnyHash.parse(f"{algo}-{obj[algo]}")
                break
        if hash_ is None:
            raise SerdeError(f"chunk has no recognized hash key: {obj}")
        locations = [Location.parse(s) for s in obj.get("locations", [])]
        blocks = (BlockDigests.from_obj(obj["blocks"])
                  if "blocks" in obj else None)
        return cls(hash=hash_, locations=locations, blocks=blocks)
