"""The unit of placement: a content hash plus the locations holding it.

Wire format matches the reference (src/file/chunk.rs:14-18, hash flattened):

    sha256: <hex>
    locations: [<location string>, ...]
"""

from __future__ import annotations

from dataclasses import dataclass, field

from chunky_bits_tpu.errors import SerdeError
from chunky_bits_tpu.file.hashing import AnyHash
from chunky_bits_tpu.file.location import Location


@dataclass
class Chunk:
    hash: AnyHash
    locations: list[Location] = field(default_factory=list)

    def cache_key(self) -> "bytes | None":
        """Key for the content-addressed read cache: the raw sha256
        digest, or None for any future non-sha256 algorithm (those
        chunks simply bypass the cache rather than risk a key clash
        across hash domains)."""
        if self.hash.algorithm != "sha256":
            return None
        return self.hash.value.digest

    def to_obj(self) -> dict:
        return {
            self.hash.algorithm: self.hash.value.hex(),
            "locations": [str(loc) for loc in self.locations],
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "Chunk":
        if not isinstance(obj, dict):
            raise SerdeError(f"chunk must be a mapping, got {type(obj)}")
        hash_ = None
        for algo in ("sha256",):
            if algo in obj:
                hash_ = AnyHash.parse(f"{algo}-{obj[algo]}")
                break
        if hash_ is None:
            raise SerdeError(f"chunk has no recognized hash key: {obj}")
        locations = [Location.parse(s) for s in obj.get("locations", [])]
        return cls(hash=hash_, locations=locations)
