"""Content addressing and integrity hashing.

Mirrors the reference's hash module (src/file/hash/): ``Sha256Hash`` with hex
serde (hash/sha256.rs:18), and the algorithm-tagged ``AnyHash`` whose display
form is ``sha256-<hex>`` (hash/any.rs:99-106) — the chunk filename on every
destination.  hashlib's SHA-256 is OpenSSL-native and releases the GIL, so
the async variants just hop to a thread (the spawn_blocking analogue,
hash/any.rs:17-52).
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass

from chunky_bits_tpu.errors import SerdeError


@dataclass(frozen=True, order=True)
class Sha256Hash:
    digest: bytes  # 32 raw bytes

    def __post_init__(self) -> None:
        if len(self.digest) != 32:
            raise SerdeError("sha256 digest must be 32 bytes")

    @classmethod
    def from_buf(cls, data: bytes) -> "Sha256Hash":
        return cls(hashlib.sha256(data).digest())

    @classmethod
    def from_reader(cls, reader, chunk: int = 1 << 20) -> "Sha256Hash":
        h = hashlib.sha256()
        while True:
            data = reader.read(chunk)
            if not data:
                break
            h.update(data)
        return cls(h.digest())

    @classmethod
    def from_hex(cls, s: str) -> "Sha256Hash":
        try:
            raw = bytes.fromhex(s)
        except ValueError as err:
            raise SerdeError(f"invalid sha256 hex: {s!r}") from err
        return cls(raw)

    def hex(self) -> str:
        return self.digest.hex()

    def verify(self, data: bytes) -> bool:
        return hashlib.sha256(data).digest() == self.digest

    def __str__(self) -> str:
        return self.hex()


@dataclass(frozen=True, order=True)
class AnyHash:
    """Algorithm-tagged hash; the extension point for future algorithms.

    String form ``sha256-<hex>``; serde form ``{"sha256": "<hex>"}`` flattened
    into the chunk mapping (reference: src/file/chunk.rs:14-18).
    """

    algorithm: str
    value: Sha256Hash

    @classmethod
    def sha256(cls, h: Sha256Hash) -> "AnyHash":
        return cls("sha256", h)

    @classmethod
    def from_buf(cls, data: bytes) -> "AnyHash":
        return cls.sha256(Sha256Hash.from_buf(data))

    @classmethod
    def parse(cls, s: str) -> "AnyHash":
        algo, sep, hexpart = s.partition("-")
        if not sep:
            raise SerdeError(f"invalid hash format: {s!r}")
        if algo != "sha256":
            raise SerdeError(f"unknown hash format: {algo!r}")
        return cls.sha256(Sha256Hash.from_hex(hexpart))

    def rehash(self, data: bytes) -> "AnyHash":
        """Hash ``data`` with this hash's algorithm (hash/any.rs:61-67)."""
        return AnyHash.from_buf(data)

    def verify(self, data: bytes) -> bool:
        return self.value.verify(data)

    async def verify_async(self, data: bytes) -> bool:
        return await asyncio.to_thread(self.verify, data)

    async def rehash_async(self, data: bytes) -> "AnyHash":
        return await asyncio.to_thread(self.rehash, data)

    def __str__(self) -> str:
        return f"{self.algorithm}-{self.value.hex()}"


async def hash_buf_async(data: bytes) -> AnyHash:
    return await asyncio.to_thread(AnyHash.from_buf, data)
