"""Streaming ingest pipeline.

Mirrors the reference's ``FileWriteBuilder`` (src/file/writer.rs): read
``d * chunk_size`` bytes per part, encode + write each part concurrently
(bounded by a semaphore, default concurrency 10), collect parts in order,
fail fast on the first error.  Defaults match writer.rs:50-59
(chunk_size 1 MiB, d=3, p=2, concurrency 10).

TPU twist: the reference encodes one part per call
(src/file/writer.rs:208-218 -> file_part.rs:161); a TPU wants batches.
``batch_parts > 1`` stages parts and encodes them in batched device
dispatches (grouped by shard length, so the full-size stripes share one
[B, d, S] dispatch), without changing ordered metadata assembly or the
fail-fast error path.

Staging streams: parts are handed to encode in sub-blocks of
``stage_parts`` (default 8) as they fill, so the read loop, the device
encode, and the destination writes all overlap — a large ``batch_parts``
raises the *dispatch* coalescing bound (an EncodeHashBatcher — the
caller's shared one, or one the writer creates for merge-preferring
device backends — merges concurrent sub-blocks into one [ΣB, d, S]
dispatch), not the amount of data serialized behind a single staging
copy.  Round-2 measurement of the unstreamed design: batch=256 collapsed
to 0.09 GiB/s because 2.5 GiB sat in buffers while nothing encoded or
wrote.

Zero-restage ingest: the read loop lands part bytes directly into rows
of the [stage_size, d, chunk] staging block (``aio.read_exact_into``,
zero-copy for ``readinto``-capable readers), so full-length parts reach
the encoder already in batched device layout with no intermediate bytes
objects or restaging memcpy; only the short tail part is repacked.

Multi-core host plane: each staged sub-block's encode+hash runs through
the shared host pipeline (parallel/host_pipeline.py) — per-stripe fused
encode+hash sliced across ``min(N, nproc)`` daemon workers — so the
socket/page-cache read loop overlaps compute on every scheduler core.
Ordered part assembly and the placement stagger are untouched: slices
write positionally into the staged batch's outputs.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, replace
from typing import Optional

import numpy as np

from chunky_bits_tpu.errors import FileWriteError
from chunky_bits_tpu.file.file_part import FilePart
from chunky_bits_tpu.file.file_reference import FileReference
from chunky_bits_tpu.ops import get_coder
from chunky_bits_tpu.utils import aio


@dataclass
class FileWriteBuilder:
    destination: object = None
    chunk_size: int = 1 << 20
    data: int = 3
    parity: int = 2
    concurrency: int = 10
    batch_parts: int = 1
    #: staging granularity: parts are flushed to encode in sub-blocks of
    #: this size, so staging never serializes more than this many parts
    #: behind one copy (batch_parts stays the dispatch coalescing bound).
    #: Swept on the 1-core bench host: 4-16 all sustain ~0.38 GiB/s
    #: through config 2 at any batch; 32+ collapses to ~0.1.
    stage_parts: int = 8
    backend: Optional[str] = None
    content_type: Optional[str] = None
    #: an ops.batching.EncodeHashBatcher shared across concurrent writes
    #: (coalesces many small files into one device dispatch), or a zero-arg
    #: callable resolving to one inside the running loop, or None.
    encode_batcher: object = None
    #: a parallel.host_pipeline.HostPipeline running this write's host
    #: compute (per-stripe encode + per-shard SHA sliced across daemon
    #: workers), or None for the process-shared one.  The scaling sweeps
    #: (bench --config 2 --sweep-threads) inject per-N instances here.
    host_pipeline: object = None
    #: block-digest tree granularity (the ``repair_block_bytes``
    #: tunable): chunks longer than this get a per-block sha256 tree in
    #: their metadata for damage localization (cluster/repair.py);
    #: 0 = off
    repair_block_bytes: int = 0
    #: erasure code for every part this writer emits: "rs" (classic
    #: Reed-Solomon, the default — refs stay byte-identical to older
    #: writers) or "pm-msr" (product-matrix MSR regenerating code,
    #: ops/pm_msr.py; needs parity >= data-1 and an alpha-divisible
    #: chunk size).  Cluster profiles route their ``code`` knob here.
    code: str = "rs"

    # builder setters (writer.rs:78-110); return copies like the Rust
    # builder's consume-and-return

    def with_destination(self, destination) -> "FileWriteBuilder":
        return replace(self, destination=destination)

    def with_chunk_size(self, chunk_size: int) -> "FileWriteBuilder":
        return replace(self, chunk_size=chunk_size)

    def with_data_chunks(self, data: int) -> "FileWriteBuilder":
        return replace(self, data=data)

    def with_parity_chunks(self, parity: int) -> "FileWriteBuilder":
        return replace(self, parity=parity)

    def with_concurrency(self, concurrency: int) -> "FileWriteBuilder":
        return replace(self, concurrency=concurrency)

    def with_batch_parts(self, batch_parts: int) -> "FileWriteBuilder":
        return replace(self, batch_parts=batch_parts)

    def with_stage_parts(self, stage_parts: int) -> "FileWriteBuilder":
        return replace(self, stage_parts=stage_parts)

    def with_backend(self, backend: Optional[str]) -> "FileWriteBuilder":
        return replace(self, backend=backend)

    def with_content_type(self, content_type: Optional[str]
                          ) -> "FileWriteBuilder":
        return replace(self, content_type=content_type)

    def with_encode_batcher(self, encode_batcher) -> "FileWriteBuilder":
        return replace(self, encode_batcher=encode_batcher)

    def with_host_pipeline(self, host_pipeline) -> "FileWriteBuilder":
        return replace(self, host_pipeline=host_pipeline)

    def with_repair_block_bytes(self, repair_block_bytes: int
                                ) -> "FileWriteBuilder":
        return replace(self, repair_block_bytes=repair_block_bytes)

    def with_code(self, code: str) -> "FileWriteBuilder":
        return replace(self, code=code)

    async def write(self, reader: aio.AsyncByteReader) -> FileReference:
        if self.concurrency <= 1:
            raise FileWriteError("concurrency must be > 1")
        batch_parts = max(1, min(self.batch_parts, self.concurrency))
        stage_size = max(1, min(batch_parts, self.stage_parts))
        d, p = self.data, self.parity
        # raises ErasureError on an unknown code or a geometry the code
        # cannot run (e.g. pm-msr with parity < data-1) — a writer must
        # fail loudly at the first part, not emit an unreadable ref
        coder = get_coder(d, p, self.backend, self.code)
        if coder.shard_len(d * self.chunk_size) != self.chunk_size:
            raise FileWriteError(
                f"chunk_size {self.chunk_size} incompatible with "
                f"code {coder.code!r}: full-length shards must not "
                f"need sub-symbol padding (pm-msr: chunk_size % "
                f"alpha == 0, alpha = data-1)")
        from chunky_bits_tpu.file.collection_destination import \
            as_destination
        from chunky_bits_tpu.parallel.host_pipeline import get_host_pipeline

        # the multi-core host plane: per-stripe encode + per-shard SHA
        # run sliced across the pipeline's daemon workers, so the read
        # loop (socket/page-cache) overlaps compute on every core the
        # scheduler was given, not just one
        pipeline = self.host_pipeline or get_host_pipeline()

        destination = as_destination(self.destination)

        sem = asyncio.Semaphore(self.concurrency)

        encode_batcher = self.encode_batcher
        if callable(encode_batcher):
            encode_batcher = encode_batcher()
        merging = getattr(coder.backend, "prefers_merged_batches", False)
        own_batcher = False
        if encode_batcher is None and merging and batch_parts > stage_size:
            # device backend with no shared batcher: coalesce this
            # write's own sub-blocks back into [<=batch_parts, d, S]
            # dispatches, so streamed staging doesn't shrink the device
            # batches that amortize per-dispatch overhead.  max_batch
            # counts batcher REQUESTS — sub-blocks of up to stage_size
            # parts each — so divide to keep the merged dispatch within
            # batch_parts parts.
            from chunky_bits_tpu.ops.batching import EncodeHashBatcher

            encode_batcher = EncodeHashBatcher(
                backend=self.backend,
                max_batch=max(1, batch_parts // stage_size),
                host_pipeline=pipeline)
            own_batcher = True

        # Read-ahead bound: by default at most two sub-blocks of raw parts
        # may sit staged-or-encoding at once (classic double buffer: one
        # encoding, one filling).  Without it a large concurrency lets the
        # read loop race GiBs of buffers ahead of the encoder, thrashing
        # caches and starving the pipeline it is supposed to feed
        # (measured round 4: batch=256 at 0.09 GiB/s, recovering to a
        # flat 0.38 with the bound).  Merge-preferring device backends
        # get a window of batch_parts instead — pending sub-blocks are
        # what the batcher merges into full-size dispatches.
        encode_ahead = asyncio.Semaphore(
            max(2 * stage_size, batch_parts if merging else 0))
        chunk = self.chunk_size
        part_bytes = d * chunk
        # The current staging block: the read loop lands part bytes
        # DIRECTLY into rows of this [stage_size, d, chunk] array (via
        # readinto when the reader supports it), so a full-length part
        # reaches the encoder with zero restaging copies — the bytes are
        # already in batched [B, d, S] device layout.
        block: Optional[np.ndarray] = None
        lens: list[int] = []
        total_bytes = 0

        def stage(blk: np.ndarray, ls: list[int]):
            """Group a staging block's parts by shard length.  The
            common group — full-length parts, which the read loop already
            laid out back-to-back — is handed to encode as a zero-copy
            slice view of the block; only a short tail part (at most one
            per write: a short read ends the stream) is repacked to its
            smaller shard length with zero padding.  Runs in a worker
            thread for the repack memcpy."""
            groups: dict[int, list[int]] = {}
            for i, length in enumerate(ls):
                shard_len = coder.shard_len(length)
                groups.setdefault(shard_len, []).append(i)
            staged_groups = []
            for shard_len, indices in groups.items():
                if shard_len == 0:
                    staged_groups.append((0, indices, None))
                    continue
                if shard_len == chunk:
                    # split full-length parts out first: a near-full tail
                    # (within d-1 bytes of part_bytes) shares this
                    # shard_len but needs zero padding, and must not drag
                    # the full parts through the repack
                    full = [i for i in indices if ls[i] == part_bytes]
                    if full and full[-1] + 1 - full[0] == len(full):
                        staged_groups.append(
                            (chunk, full, blk[full[0]:full[-1] + 1]))
                        indices = [i for i in indices
                                   if ls[i] != part_bytes]
                        if not indices:
                            continue
                stacked = np.empty((len(indices), d, shard_len),
                                   dtype=np.uint8)
                for bi, i in enumerate(indices):
                    length = ls[i]
                    flat = stacked[bi].reshape(d * shard_len)
                    flat[:length] = blk[i].reshape(-1)[:length]
                    if length < d * shard_len:
                        flat[length:] = 0
                staged_groups.append((shard_len, indices, stacked))
            return staged_groups

        async def encode_staged(blk: np.ndarray, ls: list[int]):
            """Encode + hash a batch of parts; same-shard-length stripes
            share one dispatch (and one fused native encode+hash pass).
            With a shared encode batcher, the dispatch additionally
            coalesces with other concurrent writes (many-small-files /
            gateway ingest)."""
            groups = await pipeline.run(
                "stage", lambda: stage(blk, ls), nbytes=sum(ls))
            results: dict[int, tuple[list, list, int, Optional[list]]] = {}

            async def encode_group(shard_len, indices, stacked):
                if shard_len == 0:
                    for i in indices:
                        results[i] = ([], [], 0, None)
                    return
                if encode_batcher is not None:
                    parity_batch, digest_batch = \
                        await encode_batcher.encode_hash(
                            d, p, stacked, code=coder.code)
                else:
                    parity_batch, digest_batch = \
                        await pipeline.encode_hash(coder, stacked)
                for bi, i in enumerate(indices):
                    results[i] = (
                        list(stacked[bi]),
                        list(parity_batch[bi]),
                        shard_len,
                        [row.tobytes() for row in digest_batch[bi]],
                    )

            await aio.gather_or_cancel(
                [encode_group(*g) for g in groups])
            return [results[i] for i in range(len(ls))]

        async def write_part(precomputed) -> FilePart:
            try:
                return await FilePart.write_with_coder(
                    coder, destination, b"", 0, precomputed=precomputed,
                    pipeline=pipeline,
                    block_bytes=self.repair_block_bytes,
                )
            finally:
                sem.release()

        batch_tasks: list[asyncio.Task] = []

        async def run_batch(blk, ls) -> list[FilePart]:
            try:
                pre = await encode_staged(blk, ls)
            except BaseException:
                for _ in ls:
                    sem.release()
                    encode_ahead.release()
                raise
            # staging block consumed; let the read loop fill the next
            # sub-block while these parts flow to the destination
            for _ in ls:
                encode_ahead.release()
            return await aio.gather_or_cancel(
                [write_part(x) for x in pre])

        def flush() -> None:
            """Hand the current staging block to a background
            encode+write task — the read loop keeps streaming into a
            fresh block while the previous one is on the device / in
            flight to storage (double buffering; the semaphore still
            bounds total parts in flight)."""
            nonlocal block, lens
            blk, ls, block, lens = block, lens, None, []
            if ls:
                batch_tasks.append(asyncio.create_task(run_batch(blk, ls)))

        checked = 0

        def check_failed() -> None:
            """Fail fast: surface the first completed batch's error
            without waiting for the read loop to finish (the reference's
            oneshot error short-circuit, writer.rs:235-247).  A cursor
            skips still-pending tasks already probed so the scan stays
            O(batches) over the whole stream."""
            nonlocal checked
            while checked < len(batch_tasks):
                t = batch_tasks[checked]
                if not t.done():
                    break
                if not t.cancelled():
                    exc = t.exception()
                    if exc is not None:
                        raise exc
                checked += 1

        async def cancel_all() -> None:
            for t in batch_tasks:
                t.cancel()
            await asyncio.gather(*batch_tasks, return_exceptions=True)

        # Zero-copy source path: a reader exposing ``view_parts`` (local
        # regular files, utils/aio.py) serves whole staging blocks as
        # read-only page-cache views — full-length parts reach the
        # encoder and the shard writers with no source memcpy at all.
        # The tail (< one part) falls through to the readinto path.
        view_parts = getattr(reader, "view_parts", None)

        try:
            while True:
                # these are flow-control permits, not mutual exclusion:
                # each staged part carries its permits until its write
                # task completes (released in _write_part / on failure
                # by cancel_all), so acquire/release pair across tasks
                # lint: lock-discipline-ok permit transferred to the write task
                await sem.acquire()
                # lint: lock-discipline-ok permit transferred to the write task
                await encode_ahead.acquire()
                if view_parts is not None and block is None:
                    mv = await view_parts(part_bytes, stage_size)
                    if mv is None:
                        view_parts = None  # tail/unmappable: byte path
                    else:
                        blk = np.frombuffer(mv, dtype=np.uint8
                                            ).reshape(-1, d, chunk)
                        # permits for the parts beyond the first
                        for _ in range(blk.shape[0] - 1):
                            # lint: lock-discipline-ok permit transferred to the write task
                            await sem.acquire()
                            # lint: lock-discipline-ok permit transferred to the write task
                            await encode_ahead.acquire()
                        total_bytes += blk.shape[0] * part_bytes
                        block, lens = blk, [part_bytes] * blk.shape[0]
                        flush()
                        check_failed()
                        continue
                if block is None:
                    block = np.empty((stage_size, d, chunk),
                                     dtype=np.uint8)
                got = await aio.read_exact_into(
                    reader, memoryview(block[len(lens)].reshape(-1)))
                if got == 0:
                    sem.release()
                    encode_ahead.release()
                    break
                total_bytes += got
                lens.append(got)
                short_read = got < part_bytes
                if len(lens) >= stage_size or short_read:
                    # the just-staged parts keep their permits until their
                    # write tasks complete
                    flush()
                    check_failed()
                if short_read:
                    break
            flush()
            nested = await asyncio.gather(*batch_tasks)
            parts = [part for batch in nested for part in batch]
        except BaseException:
            # Shards already written stay put: they are content-addressed
            # and may be shared with other files' identical parts, so
            # blind deletion could destroy durable data.  Orphans are
            # reclaimed by the reference-checking find-unused-hashes GC
            # (reference behavior, main.rs:329-435).
            await cancel_all()
            raise
        finally:
            if own_batcher:
                # writer-owned batcher: drain its in-flight dispatches so
                # no task outlives the write (shared batchers belong to
                # the caller's scope)
                await encode_batcher.aclose()
        return FileReference(
            content_type=self.content_type,
            compression=None,
            length=total_bytes,
            parts=list(parts),
        )
