"""A location with a placement weight; parses ``"750:/path"`` prefix syntax
(reference: src/file/weighted_location.rs:21-39; default weight 1000)."""

from __future__ import annotations

from dataclasses import dataclass

from chunky_bits_tpu.file.location import Location

DEFAULT_WEIGHT = 1000


@dataclass
class WeightedLocation:
    location: Location
    weight: int = DEFAULT_WEIGHT

    @classmethod
    def parse(cls, s: str) -> "WeightedLocation":
        prefix, sep, postfix = s.partition(":")
        if sep and prefix.isdigit():
            return cls(location=Location.parse(postfix), weight=int(prefix))
        return cls(location=Location.parse(s))

    @classmethod
    def from_obj(cls, obj) -> "WeightedLocation":
        if isinstance(obj, str):
            return cls.parse(obj)
        return cls(
            location=Location.parse(obj["location"]),
            weight=int(obj.get("weight", DEFAULT_WEIGHT)),
        )

    def to_obj(self) -> dict:
        return {"weight": self.weight, "location": str(self.location)}
