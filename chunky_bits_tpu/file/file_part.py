"""The erasure-coding stripe: ``d`` data + ``p`` parity chunks.

Mirrors the reference's ``FilePart`` (src/file/file_part.rs:57-65) and its
four per-part algorithms: read(+decode) (:73-135), encode(+write) (:137-226),
verify (:228-251), resilver (:253-389), plus the Integrity lattice
(:392-455) and the Verify/Resilver part reports (:570-838).

The erasure math goes through the pluggable ``ErasureCoder``
(chunky_bits_tpu.ops) instead of a CPU-only crate — on TPU it is a batched
bit-plane matmul; `encode_shards` is pure (no I/O) so a staging layer can
batch many parts into one device dispatch.
"""

from __future__ import annotations

import asyncio
import enum
import random
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Iterator, Optional

import numpy as np

from chunky_bits_tpu.errors import (
    FileReadError,
    FileWriteError,
    LocationError,
    NotEnoughChunks,
    ShardError,
    is_transient_error,
)
from chunky_bits_tpu.file.chunk import Chunk
from chunky_bits_tpu.file.hashing import AnyHash, Sha256Hash
from chunky_bits_tpu.file.location import Location, LocationContext, \
    default_context
from chunky_bits_tpu.obs import tracing as obs_tracing
from chunky_bits_tpu.ops import ErasureCoder, get_coder
from chunky_bits_tpu.utils import aio

#: the clock seam (canonical surface cluster/clock.py; imported from
#: utils/ for the file->cluster import-cycle hygiene): hedge and
#: straggler delays, retry backoff, and trace spans all read it so the
#: simulator's virtual timebase drives them
from chunky_bits_tpu.utils import clock as _clock

if TYPE_CHECKING:  # typing-only: none of these is needed at import time
    from chunky_bits_tpu.file.chunk_cache import ChunkCache
    from chunky_bits_tpu.file.collection_destination import (
        CollectionDestination,
    )
    from chunky_bits_tpu.ops.batching import ReconstructBatcher
    from chunky_bits_tpu.parallel.host_pipeline import HostPipeline

#: buffer-protocol payloads the codec surfaces accept (numpy rows are
#: normalized to memoryview at the boundaries that take them)
BufferLike = bytes | bytearray | memoryview


class LocationIntegrity(enum.IntEnum):
    """Ordered: lower is better (src/file/file_part.rs:397-423)."""

    VALID = 0
    RESILVERED = 1
    INVALID = 2
    UNAVAILABLE = 3

    def is_ideal(self) -> bool:
        return self in (LocationIntegrity.VALID, LocationIntegrity.RESILVERED)

    def is_available(self) -> bool:
        return self.is_ideal()

    def __str__(self) -> str:
        return self.name.capitalize()


class FileIntegrity(enum.IntEnum):
    """Ordered: higher is worse (src/file/file_part.rs:425-455)."""

    VALID = 0
    RESILVERED = 1
    DEGRADED = 2
    UNAVAILABLE = 3

    def is_ideal(self) -> bool:
        return self in (FileIntegrity.VALID, FileIntegrity.RESILVERED)

    def is_available(self) -> bool:
        return self != FileIntegrity.UNAVAILABLE

    def __str__(self) -> str:
        return self.name.capitalize()


def _pipe(pipeline: Optional["HostPipeline"] = None) -> "HostPipeline":
    """The host compute executor for this call: the injected one
    (verify/resilver fan-outs share a single instance; sweeps pin N) or
    the process-shared pipeline.  Hash verification hops here instead of
    ``asyncio.to_thread`` so every host path draws from the same bounded
    ``min(N, nproc)`` daemon worker set and shows up in the profiler's
    per-stage counters."""
    if pipeline is not None:
        return pipeline
    from chunky_bits_tpu.parallel.host_pipeline import get_host_pipeline

    return get_host_pipeline()


def _buf_len(data: object) -> int:
    try:
        return len(memoryview(data))  # type: ignore[arg-type]
    except TypeError:
        return 0


_FUSED_HASHER = None  # resolved once: sha256_file or False


async def _hash_local_fused(chunk: Chunk, location: Location,
                            cx: LocationContext,
                            pipeline: Optional["HostPipeline"] = None
                            ) -> Optional[bytes]:
    """Digest of a local or slab-packed chunk via the native streaming
    read+hash pass (C++ SHA-NI; ops/cpu_backend.sha256_file), which
    never surfaces the bytes to Python — slab extents hash in place
    as ``sha256_file(slab_path, extent_offset + start, length)``.
    Returns None when the fast path doesn't apply — http /
    extend-zeros-range locations, non-sha256 hashes, an active profiler
    (which must see the generic read), a missing native build, or any
    I/O failure (the generic path re-reads and reports the error in
    its own words)."""
    global _FUSED_HASHER
    if (cx.profiler is not None
            or not (location.is_local() or location.is_slab())
            or location.range.extend_zeros
            or chunk.hash.algorithm != "sha256"):
        return None
    if _FUSED_HASHER is None:
        try:
            from chunky_bits_tpu.ops.cpu_backend import (sha256_buf,
                                                         sha256_file)

            await asyncio.to_thread(sha256_buf, b"")  # force deferred build
            _FUSED_HASHER = sha256_file
        # lint: broad-except-ok native build probe; the generic read
        # path re-reads and re-hashes, so no verification is lost
        except Exception:
            _FUSED_HASHER = False
    if _FUSED_HASHER is False:
        return None
    hasher = _FUSED_HASHER
    path = location.target
    start = location.range.start or 0
    length = location.range.length
    if location.is_slab():
        ext = await asyncio.to_thread(location.slab_extent)
        if ext is None:
            return None  # generic path reports the miss in its words
        path, base, ext_len = ext
        avail = max(ext_len - start, 0)
        if length is None:
            length = avail
        elif length > avail:
            # a short range reads short on the generic path; the fused
            # pass must not hash past the extent into a neighbor chunk
            return None
        start += base
    try:
        return await _pipe(pipeline).run(
            "verify",
            lambda: hasher(path, start, length),
            nbytes=length or 0)
    except OSError:
        return None


async def _read_chunk_payload(location: Location, cx: LocationContext
                              ) -> bytes | memoryview:
    """Chunk bytes for the read/resilver paths: a zero-copy page-cache
    view for local chunks (``Location.read_view`` — hash verification,
    RS reconstruction, and shard re-writes all consume buffers), else
    the generic read."""
    view = await location.read_view(cx)
    if view is not None:
        return view
    return await location.read(cx)


async def _reconstruct(arrays: list[Optional[np.ndarray]], d: int, p: int,
                       coder: Optional[ErasureCoder], backend: Optional[str],
                       batcher: Optional[ReconstructBatcher],
                       data_only: bool,
                       code: str = "rs") -> list[Optional[np.ndarray]]:
    """Fill the ``None`` rows of ``arrays``: through the shared batcher
    when one is wired in (coalesced device dispatches), else via a lazily
    resolved coder off-loop — constructing a device backend (jax init) can
    take seconds and must neither block the event loop nor run on healthy
    reads.  ``code`` is the part's wire-format erasure code; an injected
    ``coder`` must already match it (the write path injects its own)."""
    if batcher is not None:
        return await batcher.reconstruct(d, p, arrays, data_only=data_only,
                                         code=code)
    if coder is None:
        coder = await asyncio.to_thread(get_coder, d, p, backend, code)
    fn = coder.reconstruct_data if data_only else coder.reconstruct
    return await asyncio.to_thread(fn, arrays)


def split_into_shards(data_buf: BufferLike, length: int, d: int,
                      shard_len: Optional[int] = None
                      ) -> tuple[list[memoryview], int]:
    """Split ``length`` meaningful bytes (backed by a zero-padded buffer)
    into d equal shards of ceil(length/d) bytes — the reference's round-up
    split (src/file/file_part.rs:150-158).  Returns (shards, shard_len).

    ``shard_len`` overrides the default round-up (sub-symbol codes round
    further so each shard divides into equal stripes; the extra tail is
    zero-padded exactly like the classic split's)."""
    buf_length = (shard_len if shard_len is not None
                  else (length + d - 1) // d if length > 0 else 0)
    view = memoryview(data_buf)
    if len(view) < buf_length * d:
        padded = bytearray(buf_length * d)
        padded[: len(view)] = view
        view = memoryview(padded)
    shards = [view[buf_length * i: buf_length * (i + 1)] for i in range(d)]
    return shards, buf_length


@dataclass
class FilePart:
    chunksize: int
    data: list[Chunk]
    parity: list[Chunk] = field(default_factory=list)
    encryption: Optional[str] = None
    #: erasure code of this part's stripe — "rs" (classic Reed-Solomon,
    #: the only value old references carry; the key is omitted on the
    #: wire so rs refs stay byte-identical to pre-code writers) or
    #: "pm-msr" (ops/pm_msr.py).  Values outside ops.backend.KNOWN_CODES
    #: parse fine but degrade every codec-touching operation (read,
    #: resilver, repair) to a clean FileReadError — a foreign code could
    #: be non-systematic, so even a healthy read must refuse to guess.
    code: str = "rs"

    def len_bytes(self) -> int:
        return self.chunksize * len(self.data)

    # ---- serde (wire-compatible with the reference YAML/JSON) ----

    def to_obj(self) -> dict:
        obj: dict = {}
        if self.encryption is not None:
            obj["encryption"] = self.encryption
        if self.code != "rs":
            # strictly additive: rs parts serialize without the key,
            # byte-identical to references written before this field
            obj["code"] = self.code
        obj["chunksize"] = self.chunksize
        obj["data"] = [c.to_obj() for c in self.data]
        if self.parity:
            obj["parity"] = [c.to_obj() for c in self.parity]
        return obj

    @classmethod
    def from_obj(cls, obj: dict) -> "FilePart":
        return cls(
            chunksize=int(obj["chunksize"]),
            data=[Chunk.from_obj(c) for c in obj["data"]],
            parity=[Chunk.from_obj(c) for c in obj.get("parity", [])],
            encryption=obj.get("encryption"),
            # an explicit ``code: null`` means unset, like an absent
            # key — never the string "None" (which would brick reads)
            code=str(obj.get("code") or "rs"),
        )

    def require_known_code(self) -> None:
        """Raise the clean per-part gate for codec-touching paths: a
        part declaring a code this reader does not implement must fail
        as a read error (the CLI and gateway report it per file), never
        crash or silently concatenate chunks of unknown semantics."""
        from chunky_bits_tpu.ops.backend import KNOWN_CODES

        if self.code not in KNOWN_CODES:
            raise FileReadError(
                f"part uses unknown erasure code {self.code!r} "
                f"(this reader knows {', '.join(KNOWN_CODES)}; "
                f"a newer writer produced this reference)")

    def all_chunks(self) -> list[Chunk]:
        return list(self.data) + list(self.parity)

    # ---- read + decode (src/file/file_part.rs:73-135) ----

    async def read(self, cx: Optional[LocationContext] = None,
                   coder: Optional[ErasureCoder] = None,
                   backend: Optional[str] = None,
                   batcher: Optional[ReconstructBatcher] = None,
                   cache: Optional[ChunkCache] = None,
                   pipeline: Optional[HostPipeline] = None) -> bytes:
        """``read_buffers`` joined into one bytes object (padding
        included; the file reader trims)."""
        return b"".join(
            await self.read_buffers(cx, coder, backend, batcher, cache,
                                    pipeline))

    async def read_buffers(self, cx: Optional[LocationContext] = None,
                           coder: Optional[ErasureCoder] = None,
                           backend: Optional[str] = None,
                           batcher: Optional[ReconstructBatcher] = None,
                           cache: Optional[ChunkCache] = None,
                           pipeline: Optional[HostPipeline] = None) -> list:
        """Scattered read: d workers randomly grab chunks from the shared
        d+p pool, falling through each chunk's locations; RS-reconstruct if
        any data chunk is missing.  Returns the d data-chunk buffers in
        order (bytes or zero-copy page-cache views, d*chunksize total,
        padding included) without joining them — the streaming reader
        yields them as-is, so a local `cat` moves chunk bytes from the
        page cache to the output with no intermediate copy.

        ``batcher`` (an ops.batching.ReconstructBatcher) coalesces this
        part's reconstruction with other parts in flight into one device
        dispatch.

        ``cache`` (a file.chunk_cache.ChunkCache) short-circuits fetch
        AND verify for chunks whose verified bytes it already holds:
        hits pre-fill their slots before any worker spawns, misses fetch
        through the cache's singleflight (concurrent readers of one
        digest share a single fetch), and whole verified buffers —
        never trimmed ranges — are what gets inserted."""
        self.require_known_code()
        cx = cx or default_context()
        pipe = _pipe(pipeline)
        if cx.profiler is not None:
            # read-side verification runs on the host pipeline, so its
            # per-stage busy/idle/bytes counters belong in the report
            cx.profiler.attach_pipeline(pipe)
        if cache is not None and cx.profiler is not None:
            # a cache hit produces no read log entry at all, so the
            # profiler surfaces the cache's own counters instead
            cx.profiler.attach_cache(cache)
        # the cluster's location-health scoreboard (cluster/health.py);
        # None outside a cluster context.  Hedging — racing the
        # next-best location after an adaptive delay — is armed only by
        # `tunables.hedge_ms` > 0; with it off this path walks
        # locations in metadata order exactly as before.
        health = cx.health
        hedging = health is not None and health.hedge_enabled
        if health is not None and cx.profiler is not None:
            cx.profiler.attach_health(health)
        d, p = len(self.data), len(self.parity)
        # slot payloads are bytes OR zero-copy memoryviews OR rebuilt
        # array views — deliberately untyped (the consumers take buffers)
        slots: list = [None] * (d + p)
        pool: list[tuple[int, Chunk]] = []
        for index, chunk in enumerate(self.all_chunks()):
            buf = (cache.get(chunk.cache_key())
                   if cache is not None and chunk.cache_key() is not None
                   else None)
            if buf is not None:
                slots[index] = buf
            else:
                pool.append((index, chunk))
        pool_lock = asyncio.Lock()

        async def read_verified(chunk: Chunk, location: Location
                                ) -> tuple[bool, object]:
            """(hash_ok, data) with local chunks served in ONE worker
            -thread hop: the page-cache map and the hash verification
            run in the same thread call.  The split read-then-verify
            path costs two hops per chunk, and on warm local reads the
            ~ms-scale hop latency — not the bytes — dominates."""
            mapper = location.read_view_mapper(cx)
            if mapper is not None:
                def mapped_and_verified() -> Optional[tuple[bool, object]]:
                    data = mapper()
                    if data is None:
                        return None  # unmappable: generic path below
                    return (chunk.hash.verify(data), data)

                # Deliberate tradeoff: chunks at or under the pipeline's
                # inline bound (128 KiB) map+verify ON the event loop —
                # a cold page costs a bounded small-read stall (~µs on
                # SSD, ms-scale worst case), but lockstep completion is
                # what lets concurrent degraded parts coalesce their
                # reconstruct dispatches (the thread hop both costs more
                # than the hash AND staggers arrivals).  Large chunks
                # always hop to the workers.
                fused = await pipe.run(
                    "verify", mapped_and_verified,
                    nbytes=location.range.length or self.chunksize)
                if fused is not None:
                    return fused
                # the mapper's None is deterministic — go straight to
                # the generic read, don't re-attempt the same mmap
                data = await location.read(cx)
            else:
                data = await _read_chunk_payload(location, cx)
            ok = await pipe.run(
                "verify", lambda data=data: chunk.hash.verify(data),
                nbytes=_buf_len(data))
            return (ok, data)

        async def read_one(chunk: Chunk, location: Location
                           ) -> tuple[bool, object]:
            """``read_verified`` plus up to ``cx.read_retries``
            jittered-backoff retries against the SAME location for
            transient HTTP errors (408/429/5xx minus 507) — a
            momentarily overloaded node should not cost its replica
            set a fall-through (the reference never retries,
            src/file/file_part.rs:83-101)."""
            attempt = 0
            while True:
                try:
                    return await read_verified(chunk, location)
                except LocationError as err:
                    if attempt >= cx.read_retries \
                            or not is_transient_error(err):
                        raise
                    attempt += 1
                    await _clock.sleep(
                        random.uniform(0.025, 0.075) * attempt)

        def _corrupt(failures: list, location: Location,
                     chunk: Chunk) -> None:
            failures.append(
                (location, f"hash mismatch (corrupt chunk "
                           f"{chunk.hash})"))
            if health is not None:
                # the I/O hook recorded a successful transfer; corrupt
                # content is still a demerit for the serving node
                health.record(location, False)

        async def fetch_serial(chunk: Chunk, failures: list
                               ) -> Optional[object]:
            for location in chunk.locations:
                try:
                    ok, data = await read_one(chunk, location)
                except LocationError as err:
                    failures.append((location, str(err)))
                    continue
                if ok:
                    return data
                _corrupt(failures, location, chunk)
            return None

        async def fetch_hedged(chunk: Chunk, failures: list
                               ) -> Optional[object]:
            """Tail-tolerant fetch (Dean & Barroso, "The Tail at
            Scale"): fire the best-health location; each time the
            adaptive hedge delay (scoreboard p95, floored/ceilinged by
            ``tunables.hedge_ms``) expires with the race undecided —
            and the global token-bucket budget allows — race the
            next-best location.  The first VERIFIED buffer wins;
            losers are cancelled AND awaited so a hedge can never leak
            a task past its read.  A failed racer falls through to the
            next location immediately, costing no hedge token."""
            locs = health.order(chunk.locations)
            pending: dict[asyncio.Task,
                          tuple[Location, bool, float]] = {}
            next_i = 0

            def spawn(is_hedge: bool) -> None:
                nonlocal next_i
                location = locs[next_i]
                next_i += 1
                task = asyncio.ensure_future(read_one(chunk, location))
                pending[task] = (location, is_hedge, _clock.monotonic())

            spawn(is_hedge=False)
            try:
                hedge_more = True
                while pending:
                    # the QoS gate pre-check (hedge_allowed) keeps a
                    # suppressed fetch from waking every hedge_delay
                    # just to be denied a token — under gateway
                    # admission pressure the race degrades to the
                    # serial walk's own network timeouts
                    timeout = (health.hedge_delay()
                               if hedge_more and next_i < len(locs)
                               and health.hedge_allowed()
                               else None)
                    # lint: unbounded-await-ok bounded by construction:
                    # either the hedge delay, or the racers' own
                    # network/location timeouts (the same bound the
                    # serial location walk has always had)
                    done, _ = await asyncio.wait(
                        set(pending), timeout=timeout,
                        return_when=asyncio.FIRST_COMPLETED)
                    if not done:
                        if health.try_fire_hedge():
                            spawn(is_hedge=True)
                        else:
                            # budget dry: stop racing this fetch, wait
                            # out the in-flight attempts
                            hedge_more = False
                        continue
                    for task in done:
                        location, is_hedge, _t0 = pending.pop(task)
                        try:
                            ok, data = task.result()
                        except LocationError as err:
                            failures.append((location, str(err)))
                            continue
                        if ok:
                            if is_hedge:
                                health.hedge_won()
                            return data
                        _corrupt(failures, location, chunk)
                    if not pending and next_i < len(locs):
                        # every racer failed: plain fall-through to the
                        # next location, not a hedge
                        spawn(is_hedge=False)
                return None
            finally:
                if pending:
                    # the cancelled-hedges counter counts HEDGES only —
                    # a slow primary cancelled because its hedge won is
                    # a hedge WIN, not a cancelled hedge
                    health.hedge_cancelled(
                        sum(1 for _l, is_h, _t in pending.values()
                            if is_h))
                    now = _clock.monotonic()
                    for task, (location, _h, t0) in pending.items():
                        task.cancel()
                        # a cancelled loser ran at least (now - t0)
                        # without producing a verdict: record that as a
                        # truthful lower-bound latency sample, so the
                        # scoreboard LEARNS the straggler and demotes
                        # it — the next read fires the fast replica
                        # first and needs no hedge token at all
                        health.record_latency_floor(location, now - t0)
                    await asyncio.gather(*pending,
                                         return_exceptions=True)

        async def fetch_chunk(chunk: Chunk) -> Optional[object]:
            """First verified buffer across the chunk's locations
            (health-ranked and hedged when armed), or None when every
            location is unreadable/corrupt.  WHICH location failed and
            why lands in the profiler's location-failure trail — a
            degraded cluster must stay diagnosable even though the
            read itself recovered."""
            failures: list[tuple[Location, str]] = []
            if health is not None:
                health.note_primary()  # hedge-budget accrual
            t0 = _clock.monotonic()
            if hedging and len(chunk.locations) > 1:
                data = await fetch_hedged(chunk, failures)
            else:
                data = await fetch_serial(chunk, failures)
            obs_tracing.record_span(
                "chunk_fetch", "network", t0, _clock.monotonic() - t0,
                "ok" if data is not None else "miss")
            if failures and cx.profiler is not None:
                for location, err in failures:
                    cx.profiler.log_location_failure(location, err)
            return data

        async def worker() -> Optional[tuple[int, object]]:
            while True:
                async with pool_lock:
                    if not pool:
                        return None
                    idx = random.randrange(len(pool))
                    index, chunk = pool.pop(idx)
                key = chunk.cache_key() if cache is not None else None
                if key is not None:
                    data = await cache.get_or_fetch(
                        key, lambda c=chunk: fetch_chunk(c))
                else:
                    data = await fetch_chunk(chunk)
                if data is not None:
                    return (index, data)

        async def straggler_race(needed: int) -> None:
            """The d-of-d+p scheduler's degraded-read race: run the
            chunk workers, and whenever the adaptive hedge delay
            passes with workers still out (budget allowing), draw one
            MORE chunk from the shared pool — by then usually parity —
            so a straggling data chunk can be counted as missing and
            beaten by fetch+reconstruct (cf. degraded-read scheduling
            in the product-matrix/regenerating-codes line, PAPERS.md).
            The moment >= d slots are filled the stragglers are
            cancelled and awaited; reconstruction below fills the
            gaps byte-identically."""
            tasks = {asyncio.ensure_future(worker())
                     for _ in range(needed)}
            extras: set = set()  # hedge-spawned workers, for counters
            try:
                hedge_more = True
                while tasks:
                    # 2x the location-hedge delay: the per-chunk
                    # location race gets first shot at a straggler
                    # (one token); only when THAT hasn't resolved —
                    # replica slow too, or none left — does the pool
                    # draw an extra chunk for reconstruction
                    timeout = (2.0 * health.hedge_delay()
                               if hedge_more and pool
                               and health.hedge_allowed() else None)
                    # lint: unbounded-await-ok bounded by construction:
                    # the hedge delay, or the workers' own per-location
                    # network timeouts once the pool/budget is dry
                    done, _ = await asyncio.wait(
                        tasks, timeout=timeout,
                        return_when=asyncio.FIRST_COMPLETED)
                    if not done:
                        if pool and health.try_fire_hedge():
                            extra = asyncio.ensure_future(worker())
                            tasks.add(extra)
                            extras.add(extra)
                        else:
                            hedge_more = False
                        continue
                    tasks -= done
                    for task in done:
                        item = task.result()
                        if item is not None:
                            slots[item[0]] = item[1]
                    if tasks and sum(
                            1 for s in slots if s is not None) >= d:
                        # any-d-of-d+p satisfied: the stragglers are
                        # officially "missing" — reconstruct beats
                        # waiting them out
                        break
            finally:
                # counter semantics: only hedge-spawned extras count as
                # cancelled hedges — the original workers are the read
                # itself, not hedge load
                health.hedge_cancelled(len(tasks & extras))
                for task in tasks:
                    task.cancel()
                if tasks:
                    await asyncio.gather(*tasks, return_exceptions=True)

        # cache hits above already filled some slots; only the shortfall
        # needs workers (a fully hot part spawns none at all)
        needed = max(d - sum(1 for s in slots if s is not None), 0)
        if hedging and needed > 0:
            await straggler_race(needed)
        else:
            results = await asyncio.gather(
                *[worker() for _ in range(needed)])
            for item in results:
                if item is not None:
                    slots[item[0]] = item[1]
        if not all(slots[i] is not None for i in range(d)):
            present = sum(1 for s in slots if s is not None)
            if present < d:
                raise NotEnoughChunks(
                    f"only {present} of {d}+{p} chunks readable"
                )
            rebuilt_idx = [i for i in range(d) if slots[i] is None]
            arrays: list[Optional[np.ndarray]] = [
                np.frombuffer(s, dtype=np.uint8) if s is not None else None
                for s in slots
            ]
            t0 = _clock.monotonic()
            arrays = await _reconstruct(arrays, d, p, coder, backend,
                                        batcher, data_only=True,
                                        code=self.code)
            obs_tracing.record_span("reconstruct", "compute", t0,
                                    _clock.monotonic() - t0)
            # rebuilt rows stay as buffers (memoryview over the array) —
            # every consumer downstream (join, hashing, socket/stdout
            # writes) takes buffer objects, so no tobytes copy
            slots = [memoryview(np.ascontiguousarray(a))
                     if isinstance(a, np.ndarray) else a
                     for a in arrays]
            if cache is not None:
                # reconstructed rows were never hash-verified, so they
                # enter through the verify-then-insert gate; a repeated
                # degraded read then hits instead of re-decoding
                for i in rebuilt_idx:
                    await cache.insert_verified(self.data[i].hash,
                                                slots[i])
        return [slots[i] for i in range(d)]  # type: ignore[misc]

    # ---- encode (pure compute half; no I/O) ----

    @staticmethod
    def encode_shards(coder: ErasureCoder, data_buf: BufferLike,
                      length: int
                      ) -> tuple[list[memoryview], list[np.ndarray], int]:
        """Split + parity computation (src/file/file_part.rs:150-165).
        Pure so batching layers can aggregate parts into one dispatch."""
        d = coder.data
        shards, buf_length = split_into_shards(
            data_buf, length, d, shard_len=coder.shard_len(length))
        if buf_length == 0:
            return shards, [], 0
        stacked = np.stack(
            [np.frombuffer(s, dtype=np.uint8) for s in shards]
        )[None, ...]
        parity = list(coder.encode_batch(stacked)[0])
        return shards, parity, buf_length

    # ---- encode + write (src/file/file_part.rs:137-226) ----

    @staticmethod
    async def write_with_coder(
        coder: ErasureCoder,
        destination: CollectionDestination,
        data_buf: BufferLike,
        length: int,
        precomputed: Optional[tuple] = None,
        pipeline: Optional[HostPipeline] = None,
        block_bytes: int = 0,
    ) -> "FilePart":
        """Encode one part and write all d+p shards concurrently,
        failing fast on the first shard error.

        ``precomputed`` is ``(shards, parity, buf_length)`` or
        ``(shards, parity, buf_length, digests)`` from a staging layer;
        ``digests`` (32-byte sha256 per shard, data then parity — the
        fused encode+hash output) skips re-hashing here.

        ``block_bytes`` > 0 additionally writes a per-chunk block-digest
        tree (file/chunk.py BlockDigests, the ``repair_block_bytes``
        tunable) into each chunk longer than one block, computed on the
        same host-pipeline hash stage the per-shard SHA runs on — the
        damage-localization metadata the repair planner
        (cluster/repair.py) schedules sub-chunk rebuilds from."""
        pipe = _pipe(pipeline)
        digests: Optional[list] = None
        if precomputed is not None:
            shards, parity, buf_length = precomputed[:3]
            if len(precomputed) > 3:
                digests = precomputed[3]
        else:
            shards, parity, buf_length = await pipe.run(
                "encode",
                lambda: FilePart.encode_shards(coder, data_buf, length),
                nbytes=length)
        d, p = coder.data, coder.parity
        if digests is not None and len(digests) != d + p:
            raise FileWriteError(
                f"staging layer produced {len(digests)} digests "
                f"for {d}+{p} shards")
        writers = destination.get_writers(d + p)

        async def hash_and_write(payload: Any, writer: Any,
                                 digest: Optional[bytes]) -> Chunk:
            # Zero-copy normalization: numpy rows and memoryviews flow
            # through to the writers as buffers; only exotic payloads pay
            # a bytes() copy.
            if isinstance(payload, np.ndarray):
                payload = memoryview(np.ascontiguousarray(payload))
            elif not isinstance(payload, (bytes, bytearray, memoryview)):
                payload = bytes(payload)
            if digest is not None:
                hash_ = AnyHash.sha256(Sha256Hash(digest))
            else:
                hash_ = await pipe.run(
                    "hash",
                    lambda payload=payload: AnyHash.from_buf(payload),
                    nbytes=_buf_len(payload))
            blocks = None
            if block_bytes > 0 and _buf_len(payload) > block_bytes:
                # single-block chunks carry no tree: the chunk hash
                # already localizes damage to the whole (one) block
                from chunky_bits_tpu.file.chunk import BlockDigests

                blocks = await pipe.run(
                    "hash",
                    lambda payload=payload: BlockDigests.from_buf(
                        payload, block_bytes),
                    nbytes=_buf_len(payload))
            try:
                locations = await writer.write_shard(hash_, payload)
            except ShardError as err:
                raise FileWriteError(str(err)) from err
            return Chunk(hash=hash_, locations=locations, blocks=blocks)

        payloads = list(shards) + list(parity)
        pre_digests = digests if digests is not None \
            else [None] * len(payloads)
        chunks = await aio.gather_or_cancel(
            [hash_and_write(pl, w, dg)
             for pl, w, dg in zip(payloads, writers, pre_digests)])
        return FilePart(
            chunksize=buf_length,
            data=list(chunks[:d]),
            parity=list(chunks[d:]),
            code=coder.code,
        )

    # ---- verify (src/file/file_part.rs:228-251) ----

    #: concurrent location reads per part during verify; with the
    #: file-level bound (RESILVER_CONCURRENCY parts in flight) this caps
    #: total open reads at 10×10 where the reference is unbounded
    #: (every location of every chunk at once, file_part.rs:228-251)
    VERIFY_READ_CONCURRENCY = 10

    async def verify(self, cx: Optional[LocationContext] = None,
                     pipeline: Optional[HostPipeline] = None
                     ) -> "VerifyPartReport":
        cx = cx or default_context()
        pipe = _pipe(pipeline)
        if cx.profiler is not None:
            cx.profiler.attach_pipeline(pipe)
        sem = asyncio.Semaphore(self.VERIFY_READ_CONCURRENCY)

        async def check(ci: int, chunk: Chunk, li: int,
                        location: Location) -> tuple:
            async with sem:
                digest = await _hash_local_fused(chunk, location, cx, pipe)
                if digest is not None:
                    return (ci, li, digest == chunk.hash.value.digest, None)
                try:
                    data = await location.read(cx)
                except LocationError as err:
                    return (ci, li, None, str(err))
                ok = await pipe.run(
                    "verify", lambda data=data: chunk.hash.verify(data),
                    nbytes=_buf_len(data))
                return (ci, li, ok, None)

        jobs = [
            check(ci, chunk, li, location)
            for ci, chunk in enumerate(self.all_chunks())
            for li, location in enumerate(chunk.locations)
        ]
        results = await aio.gather_or_cancel(jobs)
        read_results = {(ci, li): (ok, err) for ci, li, ok, err in results}
        return VerifyPartReport(self, read_results)

    # ---- resilver (src/file/file_part.rs:253-389) ----

    async def resilver(self, destination: CollectionDestination,
                       cx: Optional[LocationContext] = None,
                       coder: Optional[ErasureCoder] = None,
                       backend: Optional[str] = None,
                       batcher: Optional[ReconstructBatcher] = None,
                       pipeline: Optional[HostPipeline] = None
                       ) -> "ResilverPartReport":
        # Deviation from the reference: repair writes always overwrite.
        # Under the default `on_conflict: ignore` tunable the reference's
        # resilver silently keeps a corrupt chunk file when the rebuilt
        # shard lands on the node already holding it (write_subfile sees the
        # file exists and skips); overwriting a content-addressed chunk with
        # bytes matching its hash is always safe.
        self.require_known_code()
        overwrite = getattr(destination, "with_conflict_overwrite", None)
        if overwrite is not None:
            destination = overwrite()
        cx = cx or destination.get_context()
        pipe = _pipe(pipeline)
        if cx.profiler is not None:
            cx.profiler.attach_pipeline(pipe)
        chunks = self.all_chunks()
        d, p = len(self.data), len(self.parity)

        async def read_chunk(chunk: Chunk) -> tuple:
            report = []
            chunk_bytes = None
            for location in chunk.locations:
                try:
                    data = await _read_chunk_payload(location, cx)
                except LocationError as err:
                    report.append((None, str(err)))
                    continue
                ok = await pipe.run(
                    "verify",
                    lambda chunk=chunk, data=data: chunk.hash.verify(data),
                    nbytes=_buf_len(data))
                if ok and chunk_bytes is None:
                    chunk_bytes = data
                report.append((ok, None))
            return chunk_bytes, report

        gathered = await asyncio.gather(*[read_chunk(c) for c in chunks])
        data_bufs: list[Optional[bytes]] = [g[0] for g in gathered]
        read_results = {
            (ci, li): res
            for ci, g in enumerate(gathered)
            for li, res in enumerate(g[1])
        }
        chunk_status = [buf is not None for buf in data_bufs]

        write_error: Optional[str] = None
        write_results: dict[int, tuple[Optional[list[Location]], Optional[str]]] = {}
        if not all(chunk_status):
            # Reconstruct every missing chunk (data and parity).
            try:
                arrays: list[Optional[np.ndarray]] = [
                    np.frombuffer(b, dtype=np.uint8) if b is not None else None
                    for b in data_bufs
                ]
                arrays = await _reconstruct(arrays, d, p, coder, backend,
                                            batcher, data_only=False,
                                            code=self.code)
                rebuilt: list[Optional[bytes]] = [
                    a.tobytes() if isinstance(a, np.ndarray) else None
                    for a in arrays
                ]
            # lint: broad-except-ok surfaced as the report's
            # write_error (resilver reports failures, it never crashes
            # a sweep mid-file)
            except Exception as err:
                write_error = str(err)
                rebuilt = data_bufs
            else:
                # Request writers: existing healthy locations inform the
                # destination which nodes already hold shards
                # (src/file/file_part.rs:309-331).
                request: list[Optional[Location]] = []
                for status, chunk in zip(chunk_status, chunks):
                    if status:
                        request.extend(chunk.locations)
                    else:
                        request.append(None)
                try:
                    writers = destination.get_used_writers(request)
                # lint: broad-except-ok surfaced as the report's
                # write_error; read results above still stand
                except Exception as err:
                    write_error = str(err)
                    writers = []
                for ci, (chunk, status) in enumerate(zip(chunks,
                                                         chunk_status)):
                    if status:
                        continue
                    payload = rebuilt[ci]
                    if payload is None:
                        continue
                    if not writers:
                        write_results[ci] = (None, "no writer available")
                        continue
                    # Take writers from the head of the stagger chain —
                    # popping the tail (as the reference does,
                    # file_part.rs:341) makes every sequential repair wait
                    # out the full 100 ms stagger timeout.
                    writer = writers.pop(0)
                    try:
                        locations = await writer.write_shard(
                            chunk.hash, payload)
                    except ShardError as err:
                        write_results[ci] = (None, str(err))
                    else:
                        chunk.locations.extend(locations)
                        write_results[ci] = (list(locations), None)
        return ResilverPartReport(
            self, write_error, write_results, read_results)


class _PartReportBase:
    """Shared roll-ups (the reference's report_common! macro,
    src/file/file_part.rs:457-568)."""

    file_part: FilePart
    read_results: dict  # (chunk_idx, loc_idx) -> (ok: Optional[bool], err)

    def total_chunks(self) -> int:
        return len(self.file_part.all_chunks())

    def chunk_integrity(self, ci: int) -> LocationIntegrity:
        chunk = self.file_part.all_chunks()[ci]
        best = LocationIntegrity.UNAVAILABLE
        for li in range(len(chunk.locations)):
            res = self.read_results.get((ci, li))
            integ = self._to_integrity(res)
            if integ < best:
                best = integ
            if best == LocationIntegrity.VALID:
                break
        return best

    @staticmethod
    def _to_integrity(res: Optional[tuple]) -> LocationIntegrity:
        if res is None:
            return LocationIntegrity.VALID  # location never read (resilver)
        ok, _err = res
        if ok is True:
            return LocationIntegrity.VALID
        if ok is False:
            return LocationIntegrity.INVALID
        return LocationIntegrity.UNAVAILABLE

    def healthy_chunks(self) -> list[Chunk]:
        return [c for ci, c in enumerate(self.file_part.all_chunks())
                if self.chunk_integrity(ci) == LocationIntegrity.VALID]

    def unhealthy_chunks(self) -> list[Chunk]:
        return [c for ci, c in enumerate(self.file_part.all_chunks())
                if self.chunk_integrity(ci) != LocationIntegrity.VALID]

    def unavailable_locations(self) -> list[tuple[Location, str]]:
        out = []
        chunks = self.file_part.all_chunks()
        for (ci, li), (ok, err) in self.read_results.items():
            if ok is None:
                out.append((chunks[ci].locations[li], err or ""))
        return out

    def invalid_locations(self) -> list[Location]:
        chunks = self.file_part.all_chunks()
        return [chunks[ci].locations[li]
                for (ci, li), (ok, _e) in self.read_results.items()
                if ok is False]

    def locations_with_integrity(
            self) -> Iterator[tuple[Location, LocationIntegrity]]:
        chunks = self.file_part.all_chunks()
        for (ci, li), res in sorted(self.read_results.items()):
            yield chunks[ci].locations[li], self._to_integrity(res)

    def is_ideal(self) -> bool:
        return self.integrity().is_ideal()

    def is_available(self) -> bool:
        return self.integrity().is_available()


class VerifyPartReport(_PartReportBase):
    """(src/file/file_part.rs:570-647)"""

    def __init__(self, file_part: FilePart, read_results: dict) -> None:
        self.file_part = file_part
        self.read_results = read_results

    def integrity(self) -> FileIntegrity:
        d = len(self.file_part.data)
        total = self.total_chunks()
        healthy = len(self.healthy_chunks())
        if healthy == total:
            return FileIntegrity.VALID
        if healthy >= d:
            return FileIntegrity.DEGRADED
        return FileIntegrity.UNAVAILABLE

    def __str__(self) -> str:
        return (f"{self.integrity()}: {len(self.unhealthy_chunks())}/"
                f"{self.total_chunks()} unhealthy chunks")

    def display_full_report(self) -> str:
        lines = [f"part\t{self.integrity()}"]
        for ci, chunk in enumerate(self.file_part.all_chunks()):
            lines.append(
                f"chunk\t{self.chunk_integrity(ci)}\t{chunk.hash}")
            for li, location in enumerate(chunk.locations):
                ok, err = self.read_results.get((ci, li), (None, None))
                integ = self._to_integrity((ok, err))
                if err:
                    lines.append(f"location\t{integ}\t{location}\t{err}")
                else:
                    lines.append(f"location\t{integ}\t{location}")
        return "\n".join(lines) + "\n"


class ResilverPartReport(_PartReportBase):
    """(src/file/file_part.rs:671-838)"""

    def __init__(self, file_part: FilePart, write_error: Optional[str],
                 write_results: dict, read_results: dict) -> None:
        self.file_part = file_part
        self.write_error = write_error
        self.write_results = write_results
        self.read_results = read_results

    def chunk_integrity(self, ci: int) -> LocationIntegrity:
        integ = super().chunk_integrity(ci)
        if integ == LocationIntegrity.VALID:
            return integ
        locations, _err = self.write_results.get(ci, (None, None))
        if locations:
            return LocationIntegrity.VALID
        return integ

    def successful_writes(self) -> list[list[Location]]:
        return [locs for locs, err in self.write_results.values()
                if locs is not None]

    def failed_writes(self) -> list[str]:
        errors = [err for _l, err in self.write_results.values()
                  if err is not None]
        if self.write_error is not None:
            errors.append(self.write_error)
        return errors

    def new_locations(self) -> list[Location]:
        return [loc for locs in self.successful_writes() for loc in locs]

    def rebuild_error(self) -> Optional[str]:
        return self.write_error

    def integrity(self) -> FileIntegrity:
        d = len(self.file_part.data)
        total = self.total_chunks()
        healthy = sum(
            1 for ci in range(total)
            if self.chunk_integrity(ci) == LocationIntegrity.VALID
        )
        if healthy == total:
            # Preserves the reference's `> 1` (file_part.rs:698-704).
            if len(self.successful_writes()) > 1:
                return FileIntegrity.RESILVERED
            return FileIntegrity.VALID
        if healthy >= d:
            return FileIntegrity.DEGRADED
        return FileIntegrity.UNAVAILABLE

    def __str__(self) -> str:
        return (f"{self.integrity()}: {len(self.successful_writes())}/"
                f"{self.total_chunks()} chunks modified")

    def display_full_report(self) -> str:
        head = f"part\t{self.integrity()}"
        if self.write_error:
            head += f"\t{self.write_error}"
        lines = [head]
        for ci, chunk in enumerate(self.file_part.all_chunks()):
            lines.append(
                f"chunk\t{self.chunk_integrity(ci)}\t{chunk.hash}")
            for li, location in enumerate(chunk.locations):
                res = self.read_results.get((ci, li))
                integ = self._to_integrity(res)
                err = res[1] if res else None
                if err:
                    lines.append(f"location\t{integ}\t{location}\t{err}")
                else:
                    lines.append(f"location\t{integ}\t{location}")
            _locs, werr = self.write_results.get(ci, (None, None))
            if werr:
                lines.append(f"error\t{werr}")
        return "\n".join(lines) + "\n"
