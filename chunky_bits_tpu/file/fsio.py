"""The storage plane's filesystem seam (canonical surface).

``FsProvider`` / ``RecordingFsProvider`` / ``FaultyFsProvider`` /
``open`` / ``replace`` / ``unlink`` / ``truncate`` / ``makedirs`` /
``fsync`` / ``fsync_dir`` / ``install`` / ``active`` — every
durability-relevant filesystem op on the storage plane (slab append +
journal commit, compaction, atomic chunk/metadata publication, the
repair planner's in-place rewrites) resolves through this seam so the
crash-consistency harness (``chunky_bits_tpu/sim/crash.py``) can swap
in a recording provider, replay every "crash at op k" prefix into a
cloned directory, and prove the recovery invariants the docstrings
claim.  Lint rule CB109 (analysis/rules.py) pins the discipline:
direct ``os.replace``/``os.fsync``/``os.unlink``/write-mode ``open``
(and friends) in ``file/slab.py``, ``file/location.py``,
``cluster/metadata.py``, ``cluster/repair.py`` and
``cluster/scrub.py`` are flagged unless they carry a
``# lint: fsio-ok <reason>`` justification.

The implementation lives in ``chunky_bits_tpu/utils/fsio.py`` and is
re-exported here whole, exactly like the clock seam
(``cluster/clock.py`` re-exporting ``utils/clock.py``): ``file/``
modules must be importable without package-``__init__`` cycles, so
they import the utils side directly while ``cluster/`` modules import
this canonical surface.  Both names are the same module-level state:
``install`` through either rebinds the one active provider.
"""

from __future__ import annotations

#: re-exported whole — see the module docstring for why the
#: implementation lives on the utils side of the package graph
from chunky_bits_tpu.utils.fsio import (  # noqa: F401
    FaultyFsProvider,
    FsOp,
    FsProvider,
    RecordingFsProvider,
    active,
    fsync,
    fsync_dir,
    install,
    makedirs,
    open,
    replace,
    system_provider,
    truncate,
    unlink,
)

__all__ = [
    "FaultyFsProvider",
    "FsOp",
    "FsProvider",
    "RecordingFsProvider",
    "active",
    "fsync",
    "fsync_dir",
    "install",
    "makedirs",
    "open",
    "replace",
    "system_provider",
    "truncate",
    "unlink",
]
