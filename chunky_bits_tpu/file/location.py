"""Uniform storage addressing: local filesystem paths and HTTP endpoints.

Mirrors the reference's ``Location`` (src/file/location.rs:61-68): an address
is ``Local{path, range}`` or ``Http{url, range}``, serialized as a plain
string with an optional ``(start,len)`` range prefix
(location.rs:550-603).  Supported verbs: read (with Range/zero-extension),
write (with conflict policy), streaming write, subfile write
(content-addressed children), delete, exists, len.

The async substrate is asyncio + aiohttp (the reference's tokio + reqwest
role); filesystem calls hop to threads.  One deviation, documented: the
reference's HTTP ``file_len`` is ``todo!()`` (location.rs:394) — here it
reads Content-Length from a HEAD response.
"""

from __future__ import annotations

import asyncio
import errno
import io
import os
import re
from dataclasses import dataclass, field, replace
from typing import Optional
from urllib.parse import quote, urlsplit, urlunsplit

#: the clock seam (cluster/clock.py is the canonical surface; the
#: implementation lives in utils/ so file/ modules can import it
#: without triggering the cluster package __init__ — import-cycle
#: hygiene, same as errors.py).  Every latency the health scoreboard
#: and profiler see comes off this clock, so the simulator's virtual
#: timebase flows through unchanged.
from chunky_bits_tpu.utils import clock as _clock
from chunky_bits_tpu.utils import fsio as _fsio

from chunky_bits_tpu.errors import (
    HttpStatusError,
    LocationError,
    LocationParseError,
    ShardError,
    WriteToRangeError,
)
from chunky_bits_tpu.file.hashing import AnyHash
from chunky_bits_tpu.file.profiler import Profiler
from chunky_bits_tpu.utils import aio

OVERWRITE = "overwrite"
IGNORE = "ignore"


@dataclass(frozen=True)
class Range:
    """Byte range view over a location (src/file/location.rs:550-603)."""

    start: int = 0
    length: Optional[int] = None
    extend_zeros: bool = False

    def is_specified(self) -> bool:
        return self.start != 0 or self.length is not None

    def __str__(self) -> str:
        if self.length is not None and not self.extend_zeros:
            return f"({self.start},{self.length})"
        if self.length is not None and self.extend_zeros:
            return f"({self.start},0{self.length})"
        return f"({self.start},)"

    @staticmethod
    def from_str_prefix(s: str) -> tuple["Range", str]:
        """Split a leading ``(start,len)`` prefix off a location string;
        a length with a leading ``0`` marks zero-extension
        (location.rs:581-602)."""
        if s.startswith("("):
            inner, sep, suffix = s[1:].partition(")")
            if sep:
                left, comma, right = inner.partition(",")
                if comma:
                    try:
                        start = int(left)
                        length = int(right) if right else None
                    except ValueError:
                        return Range(), s
                    if right and (not right.lstrip("-").isdigit()):
                        return Range(), s
                    return (
                        Range(start, length, right.startswith("0")),
                        suffix,
                    )
        return Range(), s


class LocationContext:
    """Per-operation context: conflict policy, shared HTTP session,
    optional profiler, optional location-health scoreboard
    (src/file/location.rs:447-510).

    ``health`` (a ``cluster.health.HealthScoreboard``, duck-typed to
    avoid a file->cluster import cycle) receives a completion record
    for every read / write_subfile / read_view_mapper hit against a
    location — the feed for latency-ranked ordering, the per-location
    breaker, and the hedged-read delay.  ``read_retries`` bounds the
    per-location transient-HTTP retry loops in the read fall-through
    (file/file_part.py) and the shard-write failover
    (cluster/destination.py)."""

    def __init__(self, on_conflict: str = OVERWRITE,
                 profiler: Optional[Profiler] = None,
                 https_only: bool = False,
                 user_agent: Optional[str] = None,
                 read_retries: int = 1):
        if on_conflict not in (OVERWRITE, IGNORE):
            raise ValueError(f"invalid on_conflict {on_conflict!r}")
        self.on_conflict = on_conflict
        self.profiler = profiler
        self.https_only = https_only
        self.user_agent = user_agent
        self.read_retries = max(int(read_retries), 0)
        self.health = None  # set by Cluster.__init__ (one per cluster)
        self._sessions: dict[int, object] = {}

    def but_with(self, *, on_conflict: Optional[str] = None,
                 profiler: Optional[Profiler] = None) -> "LocationContext":
        cx = LocationContext(
            on_conflict=on_conflict or self.on_conflict,
            profiler=profiler if profiler is not None else self.profiler,
            https_only=self.https_only,
            user_agent=self.user_agent,
            read_retries=self.read_retries,
        )
        cx.health = self.health  # one scoreboard per cluster
        cx._sessions = self._sessions  # share the connection pools
        return cx

    def http_session(self):
        """The aiohttp session for the running loop (loop-bound, cached).

        Entries are validated against a weakref of their loop: ``id()``
        of a dead loop can be recycled by a new one, and handing out a
        session bound to a dead loop would fail strangely.  Each new
        session also arms a primed async generator whose finalizer
        closes it — ``asyncio.run``'s ``shutdown_asyncgens`` then tears
        the session down while its loop is still alive, so short-lived
        loops (tests, scripts) don't leak connectors even when nobody
        calls :meth:`aclose`."""
        import weakref

        import aiohttp

        loop = asyncio.get_running_loop()
        entry = self._sessions.get(id(loop))
        if entry is not None:
            loop_ref, sess = entry[0], entry[1]
            if loop_ref() is loop and not sess.closed:
                return sess
            del self._sessions[id(loop)]  # stale: dead/recycled loop
        headers = {}
        if self.user_agent:
            headers["User-Agent"] = self.user_agent
        sess = aiohttp.ClientSession(headers=headers)

        async def _closer():
            try:
                yield
            finally:
                if not sess.closed:
                    await sess.close()

        gen = _closer()
        # entries for dead loops can't be awaited-closed anymore; sweep
        # them here so a long-lived process running many short loops
        # doesn't pin one (ref, session, gen) tuple per dead loop
        for key, (ref, _s, _g, _p) in list(self._sessions.items()):
            if ref() is None:
                del self._sessions[key]
        # Prime it so the loop tracks the generator and finalizes it at
        # shutdown_asyncgens.  The cache entry holds the strong ref
        # (stored on the very next line so no statement can strand the
        # primer): the loop's own asyncgen registry is a WeakSet, and an
        # unreferenced suspended generator would be GC-finalized — and
        # close the session — while the loop is still serving.
        primer = asyncio.ensure_future(gen.__anext__())
        self._sessions[id(loop)] = (weakref.ref(loop), sess, gen, primer)
        return sess

    async def aclose(self) -> None:
        loop = asyncio.get_running_loop()
        entry = self._sessions.pop(id(loop), None)
        if entry is not None:
            _ref, sess, gen, primer = entry
            if not primer.done():
                primer.cancel()
            # retrieve the primer's outcome either way: closing the
            # generator before the primer ran leaves it dying with
            # StopAsyncIteration, which must not surface as a
            # never-retrieved task exception
            try:
                # lint: unbounded-deadline-ok primer is done or was
                # cancelled two lines up — this await only retrieves
                # the already-settled outcome
                await primer
            except (asyncio.CancelledError, StopAsyncIteration):
                pass
            await gen.aclose()  # runs the closer's finally
            if not sess.closed:
                await sess.close()


_DEFAULT_CONTEXT = LocationContext()


def default_context() -> LocationContext:
    return _DEFAULT_CONTEXT


#: Atomic local publication stages "<target>.tmp.<pid>.<8-hex>" and
#: os.replace()s it in; GC's stale-temp reaping (cli/main.py) and its
#: tests key off these same definitions so the format can't drift.
_PUBLISH_TEMP_RE = re.compile(r"\.tmp\.\d+\.[0-9a-f]{8}$")


def publish_temp_name(target: str) -> str:
    """The staging path for an atomic publication of ``target``."""
    return f"{target}.tmp.{os.getpid()}.{os.urandom(4).hex()}"


def is_publish_temp(name: str) -> bool:
    """True when ``name`` (a basename or path) is an atomic-publication
    temp file — invisible to readers until renamed, so one older than
    any reasonable write duration is a crashed writer's leak."""
    return _PUBLISH_TEMP_RE.search(name) is not None


async def _publish_atomically(target: str, write_body) -> int:
    """Local write published atomically where possible; the single
    implementation of the publish protocol for both the whole-buffer and
    streaming local write paths (``write_body(path) -> int`` lands the
    bytes at the given path).

    Regular-file targets are written to a sibling temp file and
    os.replace()d in, so a concurrent reader (including page-cache views
    from ``read_view``) never observes a torn or in-place-truncated
    file — the reference's direct open-truncate-write
    (src/file/location.rs:219-236) has that window.  Crash durability
    follows the filesystem's rename semantics (flush, no fsync —
    matching the reference's flush-only behavior): after power loss the
    path holds the old content, the new content, or on some filesystems
    an empty file, but never a torn mix.  This is machine-verified, not
    argued: the ops ride the filesystem seam (``file/fsio.py``) and the
    crash harness (sim/crash.py ``chunk_publish``/``repair_rewrite``,
    bench ``--config 16``) replays every crash point of this protocol —
    kill, torn temp write, power-cut writeback orders — asserting the
    published path is only ever old | new | content-address-detectable,
    and that crashed writers' temps stay reapable without touching it.
    (Chunk publication stays flush-only by design; metadata publication,
    the cluster's write acknowledgment, adds the fsync+dir-fsync
    barriers — cluster/metadata.py.)  Direct writes are kept for
    symlinks (write through, preserving the link), special targets
    (devices, fifos — rename would replace the node), and as a fallback
    when the parent directory refuses temp creation (EACCES/EPERM/EROFS
    — the in-place write only needs permission on the file itself).  An
    existing regular file's permission bits carry over to the
    replacement; ownership becomes the writing process's and hard links
    detach — correct for content-addressed chunks, where an in-place
    rewrite would mutate every linked path.

    The pre-check rides a thread hop (CB201: on a network filesystem
    its stat/exists syscalls are round trips, and this runs per chunk
    on the gateway PUT path; the temp file does not exist yet, so the
    hop opens no cleanup race).  The chmod+replace swap and the
    error-path temp reaping deliberately stay sync: a suspension point
    between the completed write and the rename would let a cancellation
    interleave the reap with an in-flight swap (unlink-vs-replace race,
    or a publish the caller observed as cancelled), and both are
    bounded local metadata syscalls on a just-created staging file."""
    direct, mode = await asyncio.to_thread(_publish_precheck, target)
    if direct:
        return await write_body(target)
    tmp = publish_temp_name(target)
    try:
        total = await write_body(tmp)
        if mode is not None:
            # lint: async-blocking-ok bounded local chmod on the
            # staging file; sync keeps publication atomic under
            # cancellation (see docstring)
            os.chmod(tmp, mode)
        # lint: async-blocking-ok bounded local rename; a suspension
        # here would let a cancellation race the reap against the
        # in-flight swap (see docstring)
        _fsio.replace(tmp, target)
        return total
    except OSError as err:
        created = _reap_publish_temp(tmp)
        if not created and err.errno in (errno.EACCES, errno.EPERM,
                                         errno.EROFS):
            return await write_body(target)
        raise
    except BaseException:
        _reap_publish_temp(tmp)
        raise


def _publish_precheck(target: str) -> tuple[bool, Optional[int]]:
    """(write-direct?, preserved mode) for one publication — the sync
    half of _publish_atomically's target inspection, batched into a
    single executor hop."""
    if os.path.islink(target) or (
            os.path.exists(target) and not os.path.isfile(target)):
        return True, None
    try:
        return False, os.stat(target).st_mode & 0o7777
    except OSError:
        return False, None


def _reap_publish_temp(tmp: str) -> bool:
    """Remove a staging temp; True when it existed (i.e. write_body got
    far enough to create it — the EACCES-fallback discriminator)."""
    created = os.path.exists(tmp)
    try:
        _fsio.unlink(tmp)
    except OSError:
        pass
    return created


async def _atomic_publish(target: str, data) -> None:
    def _write(path: str) -> int:
        with _fsio.open(path, "wb") as f:
            f.write(data)
            f.flush()
        return len(data)

    await _publish_atomically(
        target, lambda path: asyncio.to_thread(_write, path))


async def _atomic_publish_stream(reader, target: str) -> int:
    return await _publish_atomically(
        target, lambda path: aio.copy_reader_to_file(reader, path))


class _HttpBodyReader:
    """Wraps an aiohttp response body as an AsyncByteReader, closing the
    response at EOF (or on close(), for early-stopping consumers)."""

    def __init__(self, resp):
        self._resp = resp

    async def read(self, n: int = -1) -> bytes:
        if self._resp is None:
            return b""
        try:
            if n < 0:
                data = await self._resp.content.read()
            else:
                data = await self._resp.content.read(n)
        except Exception as err:
            # mid-body failures must surface as LocationError so per-location
            # failover (FilePart.read) can fall through to other replicas
            self._resp.close()
            self._resp = None
            raise LocationError(f"http body read failed: {err}") from err
        if not data:
            self._resp.release()
            self._resp = None
        return data

    async def close(self) -> None:
        if self._resp is not None:
            self._resp.release()
            self._resp = None


class _ProfiledReader:
    """Counts streamed bytes and emits exactly one profiler entry when
    the stream ends — EOF, error, or early close (the reference's
    streaming paths are unprofiled, ``// TODO: Profiler``
    src/file/location.rs:119,255)."""

    def __init__(self, base, profiler: Profiler, location: "Location",
                 start: float):
        self._base = base
        self._profiler = profiler
        self._location = location
        self._start = start
        self._total = 0
        self._logged = False

    def _log(self, ok: bool, err: Optional[str] = None) -> None:
        if not self._logged:
            self._logged = True
            self._profiler.log_read(ok, err, self._location, self._total,
                                    self._start)

    async def read(self, n: int = -1) -> bytes:
        try:
            data = await self._base.read(n)
        except Exception as err:
            self._log(False, str(err))
            raise
        if data:
            self._total += len(data)
        else:
            self._log(True)
        return data

    async def close(self) -> None:
        self._log(True)
        await aio.close_reader(self._base)


@dataclass(frozen=True, order=True)
class Location:
    """A storage address; value semantics, string serde."""

    kind: str  # "local" | "http" | "slab" | "sim"
    target: str  # filesystem path, full URL, slab <root>/<name>, or
    #            sim <fabric>/<node>/<chunk> path
    range: Range = field(default_factory=Range)

    # ---- construction / parsing ----

    @staticmethod
    def parse(s: str) -> "Location":
        rng, rest = Range.from_str_prefix(s)
        if rest.startswith("http://") or rest.startswith("https://"):
            parts = urlsplit(rest)
            if not parts.netloc:
                raise LocationParseError(f"invalid http url: {rest!r}")
            return Location("http", rest, rng)
        if rest.startswith("file://"):
            parts = urlsplit(rest)
            path = parts.path
            if not path.startswith("/"):
                raise LocationParseError("file:// path must be absolute")
            return Location("local", path, rng)
        if rest.startswith("slab:"):
            # packed slab store address (file/slab.py): the path names
            # <store root>/<chunk name> — chunk bytes live inside the
            # root's slab files, addressed through its index
            path = rest[len("slab:"):]
            if not path:
                raise LocationParseError("empty slab location")
            if "://" in path.split("/")[0]:
                raise LocationParseError(
                    f"invalid slab location: {rest!r}")
            return Location("slab", path, rng)
        if rest.startswith("sim:"):
            # simulated storage node (sim/fabric.py): the path names
            # <fabric>/<node>[/<chunk>] — bytes live in the in-process
            # fabric registry, resolved lazily exactly like slab:
            path = rest[len("sim:"):]
            if not path:
                raise LocationParseError("empty sim location")
            if "://" in path.split("/")[0]:
                raise LocationParseError(
                    f"invalid sim location: {rest!r}")
            return Location("sim", path, rng)
        if "://" in rest.split("/")[0]:
            raise LocationParseError(f"invalid location scheme: {rest!r}")
        if not rest:
            raise LocationParseError("empty location")
        return Location("local", rest, rng)

    @staticmethod
    def local(path: str, rng: Optional[Range] = None) -> "Location":
        return Location("local", str(path), rng or Range())

    @staticmethod
    def slab(path: str, rng: Optional[Range] = None) -> "Location":
        return Location("slab", str(path), rng or Range())

    @staticmethod
    def sim(path: str, rng: Optional[Range] = None) -> "Location":
        return Location("sim", str(path), rng or Range())

    @staticmethod
    def http(url: str, rng: Optional[Range] = None) -> "Location":
        if not (url.startswith("http://") or url.startswith("https://")):
            raise LocationParseError(f"not an http url: {url!r}")
        return Location("http", url, rng or Range())

    def __str__(self) -> str:
        prefix = "slab:" if self.is_slab() else \
            "sim:" if self.is_sim() else ""
        if self.range.is_specified():
            return f"{self.range}{prefix}{self.target}"
        return f"{prefix}{self.target}"

    def is_http(self) -> bool:
        return self.kind == "http"

    def is_local(self) -> bool:
        return self.kind == "local"

    def is_slab(self) -> bool:
        return self.kind == "slab"

    def is_sim(self) -> bool:
        return self.kind == "sim"

    def with_range(self, rng: Range) -> "Location":
        return replace(self, range=rng)

    # ---- slab addressing (file/slab.py) ----

    def _slab_parts(self) -> tuple[str, str]:
        """(store root, chunk name) for a slab chunk address."""
        root, name = os.path.split(self.target.rstrip("/"))
        if not root or not name:
            raise LocationError(
                f"slab location {self.target!r} names a store root, "
                "not a chunk")
        return root, name

    def _slab_store(self):
        from chunky_bits_tpu.file import slab

        return slab.get_store(self._slab_parts()[0])

    # ---- sim addressing (sim/fabric.py) ----

    def _sim_node(self) -> tuple[object, str]:
        """(simulated node, chunk name) for a sim chunk address.  The
        import is lazy and only runs for sim-kind locations — production
        paths never load the simulator (the slab: discipline)."""
        # lint: sim-purity-ok sanctioned inversion: lazy import only on
        # the sim: address branch; tests/test_sim.py pins that the
        # production default-import closure never loads sim/
        from chunky_bits_tpu.sim import fabric as sim_fabric

        return sim_fabric.resolve(self.target)

    def slab_extent(self) -> Optional[tuple[str, int, int]]:
        """(slab file path, offset, length) of a live packed chunk, or
        None (not a slab location / no such chunk).  Sync — may read
        the store's index journal; off-loop callers only."""
        if not self.is_slab():
            return None
        try:
            return self._slab_store().extent_path(self._slab_parts()[1])
        except (OSError, LocationError):
            return None

    # ---- hierarchy (src/file/location.rs:407-436) ----

    def child(self, name: str) -> "Location":
        if not self.is_http():
            return Location(self.kind, os.path.join(self.target, name))
        parts = urlsplit(self.target)
        path = parts.path.rstrip("/") + "/" + quote(name, safe="")
        return Location(
            "http", urlunsplit(parts._replace(path=path)))

    def is_child_of(self, other: "Location") -> bool:
        if self.range.is_specified():
            return False
        if self.kind != other.kind:
            return False
        if not self.is_http():
            return os.path.dirname(self.target) == other.target.rstrip("/") \
                or os.path.dirname(self.target) == other.target
        left = urlsplit(self.target)
        right = urlsplit(other.target)
        if (left.scheme, left.netloc) != (right.scheme, right.netloc):
            return False
        parent = left.path.rsplit("/", 1)[0]
        return parent == right.path.rstrip("/") or parent == right.path

    def is_parent_of(self, other: "Location") -> bool:
        return other.is_child_of(self)

    def _check_scheme(self, cx: LocationContext) -> None:
        """Enforce the ``https_only`` tunable: plain-http targets are
        refused on every network verb, matching the reference's client
        built with https-only (src/cluster/tunables.rs:25-32)."""
        if cx.https_only and self.target.startswith("http://"):
            raise LocationError(
                f"https_only is set: refusing plain-http location "
                f"{self.target}"
            )

    def _redirect_kwargs(self, cx: LocationContext) -> dict:
        """Request kwargs for the mutating/HEAD verbs: under https_only,
        redirects are not followed (a replayed PUT body could otherwise
        travel a plain-http hop before any post-hoc check)."""
        return {"allow_redirects": False} if cx.https_only else {}

    def _check_redirect(self, cx: LocationContext, resp) -> None:
        """Refuse 3xx answers under https_only (paired with
        ``_redirect_kwargs``); without the tunable aiohttp has already
        followed them."""
        if cx.https_only and 300 <= resp.status < 400:
            resp.release()
            raise LocationError(
                f"https_only is set: refusing redirect "
                f"({resp.status}) from {self.target}"
            )

    def _check_response_hops(self, cx: LocationContext, resp) -> None:
        """For GET (where the body is not consumed until after this
        check): refuse if any redirect hop or the final URL travelled
        plain http."""
        if not cx.https_only:
            return
        for r in (*resp.history, resp):
            if r.url.scheme == "http":
                resp.release()
                raise LocationError(
                    f"https_only is set: response for {self.target} "
                    f"travelled plain http via {r.url}"
                )

    # ---- read path ----

    async def reader(self, cx: Optional[LocationContext] = None
                     ) -> aio.AsyncByteReader:
        """Open a streaming reader honoring the range
        (src/file/location.rs:115-183).  Profiler-hooked: one entry per
        stream at EOF/close/error — the streaming-path hook the reference
        leaves as TODO (src/file/location.rs:119)."""
        cx = cx or default_context()
        start = _clock.monotonic()
        try:
            base = await self._open_reader(cx)
        except LocationError as err:
            # stream-open failure: one health sample (latency to the
            # error), one profiler entry
            if cx.health is not None:
                cx.health.record(self, False, _clock.monotonic() - start)
            if cx.profiler is not None:
                cx.profiler.log_read(False, str(err), self, 0, start)
            raise
        if cx.health is not None:
            # the scoreboard times the open (time-to-first-byte proxy);
            # stream duration depends on the consumer, not the node
            cx.health.record(self, True, _clock.monotonic() - start)
        if cx.profiler is None:
            return base
        return _ProfiledReader(base, cx.profiler, self, start)

    async def _open_reader(self, cx: LocationContext
                           ) -> aio.AsyncByteReader:
        rng = self.range
        if self.is_slab():
            # packed chunk: one indexed open+seek into the slab file,
            # bounded by the extent (the slab-plane analogue of the
            # one-file open below; short ranges read short, exactly
            # like a local file that ends early)
            if rng.start < 0 or (rng.length is not None
                                 and rng.length < 0):
                raise LocationError(
                    f"negative range {rng} on slab location")
            root, name = self._slab_parts()
            store = self._slab_store()

            def _open():
                ext = store.lookup(name)
                if ext is None:
                    raise FileNotFoundError(
                        f"no live chunk {name!r} in slab store {root}")
                f = open(store.slab_path(ext.slab), "rb")
                try:
                    f.seek(ext.offset + rng.start)
                except BaseException:
                    f.close()
                    raise
                return f, ext

            try:
                # cancel-safe hop: a scrub restart or hedge loser
                # cancelled mid-open must not orphan the slab handle
                f, ext = await aio.open_in_thread(
                    _open, lambda r: r[0].close())
            except OSError as err:
                raise LocationError(str(err)) from err
            base = aio.FileReader(store.slab_path(ext.slab), fileobj=f)
            avail = max(ext.length - rng.start, 0)
            if rng.length is None:
                return aio.TakeReader(base, avail)
            if rng.extend_zeros:
                return aio.ZeroExtendReader(
                    aio.TakeReader(base, min(avail, rng.length)),
                    rng.length)
            return aio.TakeReader(base, min(rng.length, avail))
        if self.is_sim():
            # simulated node: the fabric applies latency/fault/bandwidth
            # models and returns the (ranged) payload; range semantics
            # mirror the local branch (short ranges read short,
            # extend_zeros pads)
            if rng.start < 0 or (rng.length is not None
                                 and rng.length < 0):
                raise LocationError(
                    f"negative range {rng} on sim location")
            node, name = self._sim_node()
            data = await node.read(name, rng.start, rng.length)
            base = aio.BytesReader(data)
            if rng.length is not None and rng.extend_zeros:
                return aio.ZeroExtendReader(base, rng.length)
            return base
        if self.is_local():
            def _open_local():
                f = open(self.target, "rb")
                try:
                    if rng.start:
                        f.seek(rng.start)
                except BaseException:
                    f.close()
                    raise
                return f

            try:
                f = await aio.open_in_thread(
                    _open_local, lambda h: h.close())
            except OSError as err:
                raise LocationError(str(err)) from err
            base = aio.FileReader(self.target, fileobj=f)
            if rng.length is None:
                return base
            if rng.extend_zeros:
                return aio.ZeroExtendReader(base, rng.length)
            return aio.TakeReader(base, rng.length)
        # HTTP
        self._check_scheme(cx)
        headers = {}
        if rng.is_specified():
            if rng.length is not None:
                headers["Range"] = \
                    f"bytes={rng.start}-{rng.start + rng.length - 1}"
            else:
                headers["Range"] = f"bytes={rng.start}-"
        sess = cx.http_session()
        try:
            resp = await sess.get(self.target, headers=headers,
                                  **self._redirect_kwargs(cx))
        except Exception as err:
            raise LocationError(f"http get failed: {err}") from err
        # Under https_only the request ran with redirects disabled, so a
        # 3xx is refused before any follow-up leaves the machine; the hop
        # check is belt-and-braces.
        self._check_redirect(cx, resp)
        self._check_response_hops(cx, resp)
        if resp.status >= 400:
            resp.release()
            raise HttpStatusError(resp.status, self.target)
        if rng.is_specified() and resp.status != 206:
            resp.release()
            raise HttpStatusError(resp.status, self.target)
        if not rng.is_specified() and resp.status != 200:
            resp.release()
            raise HttpStatusError(resp.status, self.target)
        base = _HttpBodyReader(resp)
        if rng.length is None:
            return base
        if rng.extend_zeros:
            return aio.ZeroExtendReader(base, rng.length)
        return aio.TakeReader(base, rng.length)

    async def read(self, cx: Optional[LocationContext] = None) -> bytes:
        """Read the full (ranged) content; profiler-hooked
        (src/file/location.rs:95-113)."""
        cx = cx or default_context()
        start = _clock.monotonic()
        if cx.health is not None:
            cx.health.begin(self)
        try:
            # _open_reader, not reader(): this whole-buffer op logs its own
            # single profiler entry below.
            reader = await self._open_reader(cx)
            try:
                chunks = []
                while True:
                    data = await reader.read(1 << 20)
                    if not data:
                        break
                    chunks.append(data)
                out = b"".join(chunks)
            finally:
                # EOF does not release the underlying file/response;
                # an unclosed handle per whole-buffer read leaks fds
                # (surfaces as ResourceWarning under -W error)
                await aio.close_reader(reader)
        except LocationError as err:
            if cx.health is not None:
                cx.health.finish(self, False, _clock.monotonic() - start)
            if cx.profiler is not None:
                cx.profiler.log_read(False, str(err), self, 0, start)
            raise
        except BaseException:
            # cancellation (a hedge loser) or a non-Location failure:
            # close out the in-flight count without a latency/error
            # sample — a cancelled racer says nothing about the node
            if cx.health is not None:
                cx.health.finish(self, None, None)
            raise
        if cx.health is not None:
            cx.health.finish(self, True, _clock.monotonic() - start)
        if cx.profiler is not None:
            cx.profiler.log_read(True, None, self, len(out), start)
        return out

    async def read_view(self, cx: Optional[LocationContext] = None
                        ) -> Optional[memoryview]:
        """Zero-copy page-cache view of a local (optionally ranged)
        file, or ``None`` when the fast path doesn't apply — non-local
        targets, an active profiler (which must see the generic read),
        ``CHUNKY_BITS_TPU_NO_MMAP=1``, ranges reaching past EOF (the
        generic path owns short-read/extend-zeros semantics), or
        unmappable files.

        The read-only view keeps its backing map alive for its own
        lifetime.  Chunk files are published atomically (``write`` and
        streaming local writes replace via rename, never truncating a
        regular file in place), so a concurrent re-write of the same
        location can never invalidate a view already taken — the old
        inode stays mapped.  A file truncated by an *external* writer
        can still SIGBUS a held view; set ``CHUNKY_BITS_TPU_NO_MMAP=1``
        for clusters whose storage is shared with such writers."""
        mapper = self.read_view_mapper(cx)
        if mapper is None:
            return None
        return await asyncio.to_thread(mapper)

    def read_view_mapper(self, cx: Optional[LocationContext] = None):
        """The synchronous mapper behind :meth:`read_view` (or ``None``
        when the zero-copy path doesn't apply).  Callers already inside
        a worker thread can run it there and fuse their own sync work
        (e.g. hash verification) into the same thread hop — per-chunk
        hop latency, not bytes, dominates warm local reads on small
        hosts."""
        cx = cx or default_context()
        if (not (self.is_local() or self.is_slab())
                or cx.profiler is not None
                or aio.mmap_opted_out()):
            return None
        rng = self.range
        health = cx.health  # thread-safe scoreboard; _map runs off-loop
        if self.is_slab():
            try:
                root_name = self._slab_parts()
            except LocationError:
                return None
            store = self._slab_store()
            location = self

            def _map_slab() -> Optional[memoryview]:
                t0 = _clock.monotonic()
                view = store.map_view(root_name[1], rng.start or 0,
                                      rng.length)
                if view is not None and health is not None:
                    health.record(location, True,
                                  _clock.monotonic() - t0)
                return view

            return _map_slab

        def _map() -> Optional[memoryview]:
            import mmap

            t0 = _clock.monotonic()
            try:
                with open(self.target, "rb") as f:
                    mm = mmap.mmap(f.fileno(), 0,
                                   access=mmap.ACCESS_READ)
            except (OSError, ValueError, io.UnsupportedOperation):
                return None
            start = rng.start or 0
            if start < 0 or (rng.length is not None and rng.length < 0):
                # negative ranges: the generic path owns the error
                # (Python slicing would silently serve bytes from EOF)
                mm.close()
                return None
            end = len(mm) if rng.length is None else start + rng.length
            if end > len(mm) or start > len(mm):
                # short range / zero-extension: generic path semantics
                mm.close()
                return None
            if health is not None:
                # a None return above is "fast path doesn't apply", not
                # a node failure — the generic read re-records it; only
                # a served view is a health sample
                health.record(self, True, _clock.monotonic() - t0)
            return memoryview(mm)[start:end]

        return _map

    # ---- write path ----

    async def write(self, data: bytes,
                    cx: Optional[LocationContext] = None) -> None:
        """Whole-buffer write with conflict policy; profiler-hooked
        (src/file/location.rs:185-244)."""
        cx = cx or default_context()
        if self.range.is_specified():
            raise WriteToRangeError()
        start = _clock.monotonic()
        if cx.health is not None:
            cx.health.begin(self)
        try:
            if cx.on_conflict == IGNORE and await self.file_exists(cx):
                if cx.health is not None:
                    cx.health.finish(self, True,
                                     _clock.monotonic() - start)
                if cx.profiler is not None:
                    cx.profiler.log_write(True, None, self, len(data), start)
                return
            if self.is_slab():
                # packed publication: slab append + journal commit
                # (file/slab.py's atomic-index protocol) — the slab
                # plane's equivalent of the rename publication below
                root, name = self._slab_parts()
                store = self._slab_store()
                try:
                    await asyncio.to_thread(store.append, name, data)
                except OSError as err:
                    raise LocationError(str(err)) from err
            elif self.is_sim():
                node, name = self._sim_node()
                await node.write(name, data)
            elif self.is_local():
                try:
                    await _atomic_publish(self.target, data)
                except OSError as err:
                    raise LocationError(str(err)) from err
            else:
                self._check_scheme(cx)
                sess = cx.http_session()
                try:
                    resp = await sess.put(self.target, data=data,
                                          **self._redirect_kwargs(cx))
                    resp.release()
                except Exception as err:
                    raise LocationError(f"http put failed: {err}") from err
                self._check_redirect(cx, resp)
                if resp.status >= 400:
                    raise HttpStatusError(resp.status, self.target)
        except LocationError as err:
            if cx.health is not None:
                cx.health.finish(self, False, _clock.monotonic() - start)
            if cx.profiler is not None:
                cx.profiler.log_write(False, str(err), self, len(data), start)
            raise
        except BaseException:
            if cx.health is not None:
                cx.health.finish(self, None, None)  # cancelled: no verdict
            raise
        if cx.health is not None:
            cx.health.finish(self, True, _clock.monotonic() - start)
        if cx.profiler is not None:
            cx.profiler.log_write(True, None, self, len(data), start)

    async def write_from_reader(self, reader: aio.AsyncByteReader,
                                cx: Optional[LocationContext] = None) -> int:
        """Streaming write; 1 MiB chunks into a chunked HTTP PUT or a local
        file (src/file/location.rs:246-309).  Returns bytes written.
        Profiler-hooked (the reference's TODO at location.rs:255)."""
        cx = cx or default_context()
        if cx.profiler is None and cx.health is None:
            return await self._write_from_reader_impl(reader, cx)
        start = _clock.monotonic()
        # Count consumed bytes on the reader side so a stream that fails
        # mid-body still profiles its partial progress.
        counted = aio.CountingReader(reader)
        if cx.health is not None:
            cx.health.begin(self)
        try:
            total = await self._write_from_reader_impl(counted, cx)
        except LocationError as err:
            if cx.health is not None:
                cx.health.finish(self, False, _clock.monotonic() - start)
            if cx.profiler is not None:
                cx.profiler.log_write(False, str(err), self,
                                      counted.total, start)
            raise
        except BaseException:
            if cx.health is not None:
                cx.health.finish(self, None, None)  # cancelled: no verdict
            raise
        if cx.health is not None:
            cx.health.finish(self, True, _clock.monotonic() - start)
        if cx.profiler is not None:
            cx.profiler.log_write(True, None, self, total, start)
        return total

    async def _write_from_reader_impl(self, reader: aio.AsyncByteReader,
                                      cx: LocationContext) -> int:
        if self.range.is_specified():
            raise WriteToRangeError()
        if cx.on_conflict == IGNORE and await self.file_exists(cx):
            return 0
        async def drain() -> bytes:
            # whole-body buffering for the one-record publication
            # shapes below (chunk payloads are bounded by the
            # profile's chunksize)
            chunks: list[bytes] = []
            while True:
                data = await reader.read(1 << 20)
                if not data:
                    break
                chunks.append(data)
            return b"".join(chunks)

        if self.is_slab():
            # the slab journal commits (name -> extent) in one record,
            # so the whole body must be known before publication
            payload = await drain()
            root, name = self._slab_parts()
            store = self._slab_store()
            try:
                await asyncio.to_thread(store.append, name, payload)
            except OSError as err:
                raise LocationError(str(err)) from err
            return len(payload)
        if self.is_sim():
            # one fabric publication per chunk (mirrors the slab shape)
            payload = await drain()
            node, name = self._sim_node()
            await node.write(name, payload)
            return len(payload)
        if self.is_local():
            try:
                return await _atomic_publish_stream(reader, self.target)
            except OSError as err:
                raise LocationError(str(err)) from err
        self._check_scheme(cx)
        total = 0

        async def gen():
            nonlocal total
            while True:
                data = await reader.read(1 << 20)
                if not data:
                    break
                total += len(data)
                yield data

        sess = cx.http_session()
        try:
            resp = await sess.put(self.target, data=gen(),
                                  **self._redirect_kwargs(cx))
            resp.release()
        except Exception as err:
            raise LocationError(f"http streaming put failed: {err}") from err
        self._check_redirect(cx, resp)
        if resp.status >= 400:
            raise HttpStatusError(resp.status, self.target)
        return total

    async def write_subfile(self, name: str, data: bytes,
                            cx: Optional[LocationContext] = None
                            ) -> "Location":
        """Write a named child (content-addressed chunk) under this
        location; returns the child (src/file/location.rs:311-343)."""
        target = self.child(name)
        try:
            await target.write(data, cx)
        except LocationError as err:
            raise ShardError(str(err), location=target) from err
        return target

    # ---- management ----

    async def delete(self, cx: Optional[LocationContext] = None) -> None:
        cx = cx or default_context()
        if self.is_slab():
            # GC of a packed chunk marks the extent dead in the index
            # (reclaimed by SlabStore.compact), never punches the slab
            root, name = self._slab_parts()
            store = self._slab_store()
            try:
                await asyncio.to_thread(store.mark_dead, name)
            except OSError as err:
                raise LocationError(str(err)) from err
        elif self.is_sim():
            node, name = self._sim_node()
            await node.delete(name)
        elif self.is_local():
            try:
                await asyncio.to_thread(_fsio.unlink, self.target)
            except OSError as err:
                raise LocationError(str(err)) from err
        else:
            self._check_scheme(cx)
            sess = cx.http_session()
            try:
                resp = await sess.delete(self.target,
                                         **self._redirect_kwargs(cx))
                resp.release()
            except Exception as err:
                raise LocationError(f"http delete failed: {err}") from err
            self._check_redirect(cx, resp)
            if resp.status >= 400:
                raise HttpStatusError(resp.status, self.target)

    async def file_exists(self, cx: Optional[LocationContext] = None) -> bool:
        cx = cx or default_context()
        if self.is_slab():
            store = self._slab_store()
            name = self._slab_parts()[1]
            return await asyncio.to_thread(store.lookup, name) is not None
        if self.is_sim():
            node, name = self._sim_node()
            return await node.exists(name)
        if self.is_local():
            return await asyncio.to_thread(os.path.exists, self.target)
        self._check_scheme(cx)
        sess = cx.http_session()
        try:
            resp = await sess.head(self.target,
                                   **self._redirect_kwargs(cx))
            resp.release()
        except Exception as err:
            raise LocationError(f"http head failed: {err}") from err
        self._check_redirect(cx, resp)
        return resp.status < 400

    async def file_len(self, cx: Optional[LocationContext] = None) -> int:
        cx = cx or default_context()
        if self.is_slab():
            store = self._slab_store()
            name = self._slab_parts()[1]
            ext = await asyncio.to_thread(store.lookup, name)
            if ext is None:
                raise LocationError(
                    f"no live chunk {name!r} in slab store")
            return ext.length
        if self.is_sim():
            node, name = self._sim_node()
            return await node.length(name)
        if self.is_local():
            try:
                st = await asyncio.to_thread(os.stat, self.target)
            except OSError as err:
                raise LocationError(str(err)) from err
            return st.st_size
        self._check_scheme(cx)
        sess = cx.http_session()
        try:
            resp = await sess.head(self.target,
                                   **self._redirect_kwargs(cx))
            resp.release()
        except Exception as err:
            raise LocationError(f"http head failed: {err}") from err
        self._check_redirect(cx, resp)
        if resp.status >= 400:
            raise HttpStatusError(resp.status, self.target)
        length = resp.headers.get("Content-Length")
        if length is None:
            raise LocationError(f"no Content-Length from {self.target}")
        return int(length)

    # ---- shard writing (ShardWriter for Location,
    #      src/file/location.rs:605-616) ----

    async def write_shard(self, hash_: AnyHash, data: bytes,
                          cx: Optional[LocationContext] = None
                          ) -> list["Location"]:
        loc = await self.write_subfile(str(hash_), data, cx)
        return [loc]
